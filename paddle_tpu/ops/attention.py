"""Fused attention ops.

Reference: paddle/fluid/operators/fused/multihead_matmul_op.cu (fused
transformer attention) and math/bert_encoder_functor.cu (SURVEY §2.5 fused/).
TPU-native: one `fused_multihead_attention` op whose lowering is (a) a Pallas
flash-attention kernel on TPU for long sequences (pallas_kernels.py), or
(b) an XLA-fused softmax(QK^T)V otherwise.  The op boundary is what enables
kernel substitution without touching model code — and since the kernel tier
landed (fluid/passes/kernel_tier.py), the `fuse_attention` pass PRODUCES
this op from the naive matmul→softmax→matmul chain, so plain static
programs get the kernel too.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register_op

_PALLAS_MIN_SEQ_DEFAULT = 1024
# Crossover rationale (measured, BERT sweep round 3): below ~1024 the XLA
# softmax(QK^T)V fusion is already near-roofline — at seq 512 the flash
# kernel LOSES end-to-end (23.4% vs 34.8% MFU) despite winning a fwd+bwd
# microbench, because the [B,H,T,T] score tensor still fits fusion scale
# and the kernel's block bookkeeping is pure overhead.  Only above the
# crossover does streaming K/V blocks through VMEM pay.  The knob
# (FLAGS_pallas_min_seq) exists so bench.py/tpu_watch can sweep the real
# crossover per chip generation and the future auto-tuner (ROADMAP item 5)
# can own the value instead of this constant.


def _pallas_min_seq() -> int:
    """Runtime crossover knob: FLAGS_pallas_min_seq (default 1024)."""
    try:
        from ..fluid import core
        v = core.get_flag("pallas_min_seq", _PALLAS_MIN_SEQ_DEFAULT)
        return int(v) if v is not None else _PALLAS_MIN_SEQ_DEFAULT
    except Exception:               # noqa: BLE001 — dispatch must not die
        return _PALLAS_MIN_SEQ_DEFAULT


def _reference_attention(q, k, v, mask, scale, causal,
                         dropout_rate=0.0, dropout_key=None,
                         dropout_upscale=True, prob_scale=None):
    # q,k,v: [B, H, T, D]
    acc = jnp.float32
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=acc) * scale
    if causal:
        t = s.shape[-1]
        neg = jnp.finfo(acc).min
        causal_mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(causal_mask[None, None], s, neg)
    if mask is not None:
        s = s + mask.astype(acc)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    # attention dropout ON THE PROBABILITIES, spelled exactly like the
    # standalone dropout lowering (ops/nn_ops.py) so a kernel-tier rewrite
    # that absorbed a dropout op reproduces the identical mask from the
    # identical key — CPU-fallback parity is bit-level, not just allclose
    if dropout_rate and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, p.shape)
        if dropout_upscale:
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0).astype(p.dtype)
        else:
            p = jnp.where(keep, p, 0.0).astype(p.dtype)
    elif prob_scale is not None:
        # downgrade_in_infer at test time: probs scaled by (1 - rate)
        p = (p * p.dtype.type(prob_scale)).astype(p.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _bias_broadcastable(mask, q, k) -> bool:
    """Can ``mask`` serve as the Pallas kernel's additive-bias ``ab``
    argument — i.e. broadcast to [B, H, Tq, Tk]?"""
    if mask is None or mask.ndim != 4:
        return False
    target = (q.shape[0], q.shape[1], q.shape[2], k.shape[2])
    return all(m == 1 or m == t for m, t in zip(mask.shape, target))


def flash_attention(q, k, v, mask=None, scale=None, causal=False,
                    dropout_rate=0.0, dropout_key=None,
                    dropout_upscale=True, prob_scale=None):
    """Dispatch to the Pallas TPU kernel when profitable, else XLA.

    The Pallas path handles additive-bias masks via the kernel's ``ab``
    argument (anything broadcastable to [B, H, Tq, Tk]); genuinely
    unsupported mask shapes and active attention dropout fall back to the
    XLA reference (the jax flash kernel has no in-kernel prob dropout).
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    seq = q.shape[-2]
    on_tpu = jax.default_backend() not in ("cpu",)
    drop_active = bool(dropout_rate) and dropout_key is not None
    if on_tpu and seq >= _pallas_min_seq() and not drop_active \
            and prob_scale is None and scale != 0.0 \
            and (mask is None or _bias_broadcastable(mask, q, k)):
        try:
            from .pallas_kernels import flash_attention_tpu
        except ImportError:
            flash_attention_tpu = None
        if flash_attention_tpu is not None:
            ab = None
            if mask is not None:
                # the Pallas kernel computes softmax((QKᵀ + ab)·scale);
                # our contract is softmax(QKᵀ·scale + mask), so the bias
                # rides in pre-divided by the scale
                ab = (jnp.broadcast_to(
                    mask, (q.shape[0], q.shape[1], q.shape[2], k.shape[2])
                ).astype(jnp.float32) / scale).astype(q.dtype)
            return flash_attention_tpu(q, k, v, scale=scale, causal=causal,
                                       ab=ab)
    return _reference_attention(q, k, v, mask, scale, causal,
                                dropout_rate if drop_active else 0.0,
                                dropout_key, dropout_upscale, prob_scale)


@register_op("fused_multihead_attention", nondiff_inputs=("Mask",))
def _fused_mha(ins, attrs, ctx):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    mask = ins["Mask"][0] if ins.get("Mask") else None
    # attention-dropout attrs stamped by the fuse_attention pass when it
    # absorbs a dropout op: same op_seed -> same ctx key -> same mask as
    # the unrewritten program on the XLA path
    rate = float(attrs.get("dropout_rate", 0.0) or 0.0)
    dropout_key = None
    prob_scale = None
    upscale = attrs.get("dropout_implementation",
                        "downgrade_in_infer") == "upscale_in_train"
    if rate:
        is_test = attrs.get("dropout_is_test", False) or ctx.is_test
        if is_test:
            if not upscale:
                prob_scale = 1.0 - rate
        else:
            dropout_key = ctx.key_for(attrs.get("dropout_seed", 0))
    out = flash_attention(q, k, v, mask,
                          scale=attrs.get("scale", None),
                          causal=attrs.get("causal", False),
                          dropout_rate=rate, dropout_key=dropout_key,
                          dropout_upscale=upscale, prob_scale=prob_scale)
    return {"Out": [out]}


def _paged_reference(q, kp, vp, idx, valid, scale, neg):
    """The XLA fallback: bit-for-bit the op-by-op lowering of the paged
    decode attend chain (serving/decode.py demo paged program) —
    gather → reshape → mul+reduce_sum scores → scale → masked add →
    softmax → mul+reduce_sum context.  The fuse_paged_attention pass
    (fluid/passes/kernel_tier.py) swaps the chain for this op, so every
    spelling here must reproduce the individual op lowerings exactly
    (jnp.take for gather, the same reduce axes, ``x * scale + bias`` for
    scale) or the rewrite would not be bit-transparent on CPU."""
    b = q.shape[0]
    s_len = valid.shape[1]
    d = kp.shape[-1]
    ii = idx.astype(jnp.int32)
    kg = jnp.take(kp, ii, axis=0).reshape(b, s_len, d)
    vg = jnp.take(vp, ii, axis=0).reshape(b, s_len, d)
    s = jnp.sum(jnp.multiply(kg, q.reshape(b, 1, d)), axis=(2,))
    s = s * scale + 0.0
    s = jnp.add(jnp.multiply(s, valid), valid * neg + (-neg))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.sum(jnp.multiply(vg, p.reshape(b, s_len, 1)), axis=(1,))


@register_op("paged_attention", nondiff_inputs=("Index", "Valid"))
def _paged_attention(ins, attrs, ctx):
    """Decode-step attention over a block-paged KV pool.

    Q [B, d]; KPool/VPool [R, d] flat page pools; Index [B*S] (or [B, S])
    int32 pool-row per logical position; Valid [B, S] float 0/1 mask.
    On TPU with lane-aligned shapes the lowering is the Pallas paged
    flash kernel (pallas_kernels.paged_flash_attention_tpu); elsewhere
    the XLA gather fallback mirrors the unfused chain bit-for-bit."""
    q = ins["Q"][0]
    kp, vp = ins["KPool"][0], ins["VPool"][0]
    idx, valid = ins["Index"][0], ins["Valid"][0]
    scale = float(attrs.get("scale", 1.0))
    neg = float(attrs.get("neg", 1e30))
    b, s_len = valid.shape
    idx2 = idx.reshape(b, s_len)
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        try:
            from .pallas_kernels import (paged_attention_supported,
                                         paged_flash_attention_tpu)
        except ImportError:
            paged_attention_supported = None
        if paged_attention_supported is not None \
                and paged_attention_supported(q, kp, idx2):
            ps = int(attrs.get("page_size", 1) or 1)
            if s_len % ps != 0:
                ps = 1
            lengths = jnp.sum(valid, axis=1, keepdims=True).astype(jnp.int32)
            return {"Out": [paged_flash_attention_tpu(
                q, kp, vp, idx2, lengths, scale, page_size=ps)]}
    return {"Out": [_paged_reference(q, kp, vp, idx.reshape(-1), valid,
                                     scale, neg)]}


@register_op("multihead_matmul", nondiff_inputs=("BiasQK",))
def _multihead_matmul(ins, attrs, ctx):
    """Reference multihead_matmul_op.cu API: packed QKV input."""
    x = ins["Input"][0]            # [B, T, 3*H*D]
    bias_qk = ins["BiasQK"][0] if ins.get("BiasQK") else None
    h = attrs["head_number"]
    b, t, c3 = x.shape
    d = c3 // 3 // h
    qkv = x.reshape(b, t, 3, h, d).transpose(2, 0, 3, 1, 4)
    out = flash_attention(qkv[0], qkv[1], qkv[2], bias_qk,
                          scale=attrs.get("alpha", None))
    return {"Out": [out.transpose(0, 2, 1, 3).reshape(b, t, h * d)]}
