"""Fused attention ops.

Reference: paddle/fluid/operators/fused/multihead_matmul_op.cu (fused
transformer attention) and math/bert_encoder_functor.cu (SURVEY §2.5 fused/).
TPU-native: one `fused_multihead_attention` op whose lowering is (a) a Pallas
flash-attention kernel on TPU for long sequences (pallas_kernels.py), or
(b) an XLA-fused softmax(QK^T)V otherwise.  The op boundary is what enables
kernel substitution without touching model code.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from .registry import register_op

_PALLAS_MIN_SEQ = 1024     # below this XLA fusion is already near-roofline
                           # (measured: at seq512 the flash kernel LOSES
                           # end-to-end — 23.4% vs 34.8% MFU on the BERT
                           # sweep — despite winning a fwd+bwd microbench;
                           # only enable where the [B,H,T,T] score tensor
                           # actually blows past fusion scale)


def _reference_attention(q, k, v, mask, scale, causal):
    # q,k,v: [B, H, T, D]
    acc = jnp.float32
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=acc) * scale
    if causal:
        t = s.shape[-1]
        neg = jnp.finfo(acc).min
        causal_mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(causal_mask[None, None], s, neg)
    if mask is not None:
        s = s + mask.astype(acc)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def flash_attention(q, k, v, mask=None, scale=None, causal=False):
    """Dispatch to the Pallas TPU kernel when profitable, else XLA."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    seq = q.shape[-2]
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu and seq >= _PALLAS_MIN_SEQ and mask is None:
        try:
            from .pallas_kernels import flash_attention_tpu
        except ImportError:
            flash_attention_tpu = None
        if flash_attention_tpu is not None:
            return flash_attention_tpu(q, k, v, scale=scale, causal=causal)
    return _reference_attention(q, k, v, mask, scale, causal)


@register_op("fused_multihead_attention", nondiff_inputs=("Mask",))
def _fused_mha(ins, attrs, ctx):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    mask = ins["Mask"][0] if ins.get("Mask") else None
    out = flash_attention(q, k, v, mask,
                          scale=attrs.get("scale", None),
                          causal=attrs.get("causal", False))
    return {"Out": [out]}


@register_op("multihead_matmul", nondiff_inputs=("BiasQK",))
def _multihead_matmul(ins, attrs, ctx):
    """Reference multihead_matmul_op.cu API: packed QKV input."""
    x = ins["Input"][0]            # [B, T, 3*H*D]
    bias_qk = ins["BiasQK"][0] if ins.get("BiasQK") else None
    h = attrs["head_number"]
    b, t, c3 = x.shape
    d = c3 // 3 // h
    qkv = x.reshape(b, t, 3, h, d).transpose(2, 0, 3, 1, 4)
    out = flash_attention(qkv[0], qkv[1], qkv[2], bias_qk,
                          scale=attrs.get("alpha", None))
    return {"Out": [out.transpose(0, 2, 1, 3).reshape(b, t, h * d)]}
