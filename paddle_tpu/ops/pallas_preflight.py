"""Offline Mosaic-lowering pre-flight for Pallas TPU kernels.

Round-3's one hardware up-window was burned discovering that ``lax.erf``
has no Mosaic lowering rule — the kernel traced fine, interpret mode ran
fine, and the failure only surfaced on the real chip.  This module makes
that class of failure a CPU-testable property: trace a function that
contains ``pl.pallas_call``s, walk every kernel jaxpr (recursing through
scan/cond/jit/custom-vjp sub-jaxprs), and reject any primitive the Mosaic
TensorCore lowering registry has no rule for.

The registry is read from jax's own
``jax._src.pallas.mosaic.lowering.lowering_rules`` (the dict Mosaic
consults at lowering time, keyed by kernel type — TC is the TensorCore
set), so the check can't drift from what the compiler actually supports.
Reference analog: the per-op kernel-availability check in
``paddle/fluid/framework/operator.cc:1161`` (ChooseKernel raises before
launch when no kernel is registered for the place) — here the "place" is
the Mosaic TC target and the check runs at test time instead of on chip.
"""
from __future__ import annotations

import jax

__all__ = ["mosaic_tc_primitives", "find_unlowerable",
           "assert_mosaic_lowerable", "MosaicLoweringError"]


class MosaicLoweringError(RuntimeError):
    """A pallas kernel uses a primitive Mosaic cannot lower."""


def mosaic_tc_primitives() -> frozenset:
    """Names of primitives the Mosaic TensorCore backend can lower."""
    from jax._src.pallas.mosaic import lowering as _ml
    rules = _ml.lowering_rules
    # keyed by KernelType since jax 0.8; TC (TensorCore) is what
    # pl.pallas_call targets on TPU.  On 0.4.x the registry is flat —
    # primitive -> rule directly — so the keys ARE the TC set.
    tc_key = next((k for k in rules if getattr(k, "name", "") == "TC"
                   or str(k).endswith("TC")), None)
    if tc_key is not None:
        return frozenset(p.name for p in rules[tc_key])
    if rules and all(hasattr(k, "name") for k in rules):
        return frozenset(p.name for p in rules)
    raise MosaicLoweringError(
        f"could not locate the TensorCore rule set in jax's Mosaic "
        f"lowering registry (keys: {list(rules)}) — jax internals "
        f"moved; update mosaic_tc_primitives()")


def _sub_jaxprs(eqn):
    """Yield every Jaxpr/ClosedJaxpr reachable from an eqn's params."""
    from jax._src import core as jcore
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for item in vs:
            if isinstance(item, jcore.ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, jcore.Jaxpr):
                yield item


def _walk_kernel(jaxpr, allowed, bad, kernel_name):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name not in allowed:
            bad.append((kernel_name, name))
        for sub in _sub_jaxprs(eqn):
            _walk_kernel(sub, allowed, bad, kernel_name)


def _find_pallas_calls(jaxpr, out):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            kernel = eqn.params.get("jaxpr")
            kname = eqn.params.get("name_and_src_info", None)
            out.append((str(kname) if kname is not None else "<kernel>",
                        kernel))
        else:
            for sub in _sub_jaxprs(eqn):
                _find_pallas_calls(sub, out)


def find_unlowerable(fn, *args, **kwargs):
    """Trace ``fn(*args, **kwargs)`` (no execution, works on any backend)
    and return ``(bad, n_kernels)``: ``bad`` is a list of (kernel_name,
    primitive_name) pairs for every primitive inside a pallas kernel that
    Mosaic TC cannot lower (empty = all lowerable), ``n_kernels`` the
    number of pallas_call sites found."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    calls = []
    _find_pallas_calls(closed.jaxpr, calls)
    allowed = mosaic_tc_primitives()
    bad = []
    for kname, kernel in calls:
        if kernel is None:
            continue
        from jax._src import core as jcore
        if isinstance(kernel, jcore.ClosedJaxpr):
            kernel = kernel.jaxpr
        _walk_kernel(kernel, allowed, bad, kname)
    return bad, len(calls)


def assert_mosaic_lowerable(fn, *args, require_kernels=True, **kwargs):
    """Raise MosaicLoweringError naming the offending (kernel, primitive)
    pairs; with require_kernels, also fail if NO pallas_call was found
    (the sweep would silently pass on a refactor that drops the kernel)."""
    bad, n_calls = find_unlowerable(fn, *args, **kwargs)
    if require_kernels and n_calls == 0:
        raise MosaicLoweringError(
            "no pallas_call found in traced function — preflight entry is "
            "not exercising a kernel")
    if bad:
        lines = ", ".join(f"{k}: '{p}'" for k, p in bad)
        raise MosaicLoweringError(
            f"pallas kernel uses primitives with no Mosaic TC lowering "
            f"rule (would fail at compile time on real TPU): {lines}")
