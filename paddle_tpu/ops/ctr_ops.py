"""CTR / ads ops (the qingshui/PaddleBox fork's flagship op family).

Reference (SURVEY §A.1 "CTR/ads" + §A.4): operators/cvm_op.{cc,h},
operators/fused/fused_seqpool_cvm_op.cc, operators/batch_fc_op.cc,
operators/rank_attention_op.cc, operators/scaled_fc_op.cc,
operators/cross_norm_hadamard_op.cc, operators/filter_by_instag_op.cc,
operators/hash_op.cc, operators/pyramid_hash_op.cc, operators/tdm_child_op.cc,
operators/tdm_sampler_op.cc, operators/shuffle_batch_op.cc (already in
random_ops), operators/pull_box_sparse_op.cc, operators/push_dense_op.cc.

TPU-native design: the ragged LoD batches of the reference become padded
[B, T, D] + Length tensors (sequence_lod.py convention); the GPU scatter
kernels of BoxPS pull/push become host-side table lookups staged through the
PS tier (distributed/ps) — the device-side ops here are the dense compute.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, wide_int


def _x(ins, slot="X"):
    return ins[slot][0]


# --- CVM (continuous value model: show/click statistics) --------------------
def _cvm_fwd(x, use_cvm):
    # cvm_op.h CvmComputeKernel: col0=log(show+1), col1=log(click+1)-col0;
    # use_cvm=False drops the two leading statistic columns.
    if use_cvm:
        c0 = jnp.log(x[:, 0:1] + 1.0)
        c1 = jnp.log(x[:, 1:2] + 1.0) - c0
        return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
    return x[:, 2:]


# --- fused sparse embedding: gather + pool in one op ------------------------
# Reference: operators/fused/fused_embedding_seq_pool_op.cc (the PaddleBox
# CTR hot path).  Produced by the kernel-tier fuse_sparse_embedding pass
# (fluid/passes/kernel_tier.py) from lookup_table(+sequence_pool/reduce_sum)
# chains; on TPU the lowering is the Pallas fused gather+pool kernel with a
# fused scatter-add (segment-sum) gradient (ops/pallas_kernels.py), on CPU
# an XLA take + masked sum that mirrors the unfused chain bit-for-bit.

def _emb_pool_prep(ins, attrs):
    """(w, ids, wgt, denom-applied weights): the per-(row, position)
    contribution weight folds padding_idx zeroing, the Length mask, and
    mean-pool division into one [B, S] tensor."""
    w, ids = _x(ins, "W"), _x(ins, "Ids").astype(jnp.int32)
    if attrs.get("squeeze_ids") and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])     # lookup_table [.., 1] squeeze
    b, s = ids.shape
    pool = str(attrs.get("pooltype", "SUM")).upper()
    padding_idx = attrs.get("padding_idx", -1)
    length = ins["Length"][0] if ins.get("Length") else None
    if length is not None:
        wgt = (jnp.arange(s)[None, :]
               < length.reshape(-1, 1)).astype(w.dtype)
        denom = jnp.maximum(length.reshape(-1, 1).astype(w.dtype), 1)
    else:
        wgt = jnp.ones((b, s), w.dtype)
        denom = jnp.full((b, 1), float(s), w.dtype)
    if padding_idx is not None and padding_idx >= 0:
        wgt = wgt * (ids != padding_idx).astype(w.dtype)
    if pool == "AVERAGE":
        wgt = wgt / denom
    return w, ids, wgt


def _fused_embedding_pool_grad(ins, outs, out_grads, attrs, ctx):
    """Fused gradient: dW via one weighted scatter-add — the SelectedRows
    sparse grad of the reference's fused_embedding_seq_pool, as a dense
    segment-sum.  Never materialises the [B, S, D] per-position cotangent."""
    w, ids, wgt = _emb_pool_prep(ins, attrs)
    g = out_grads.get("Out")
    if g is None:
        return {"W": [jnp.zeros_like(w)]}
    g = g.astype(w.dtype)
    vocab = w.shape[0]
    if jax.default_backend() == "tpu":
        from .pallas_kernels import (embedding_pool_grad_tpu,
                                     fused_embedding_pool_supported)
        if fused_embedding_pool_supported(w, ids):
            return {"W": [embedding_pool_grad_tpu(g, ids, wgt, vocab)]}
    rows = g[:, None, :] * wgt[:, :, None]          # [B, S, D]
    dw = jax.ops.segment_sum(rows.reshape(-1, g.shape[-1]),
                             ids.reshape(-1), num_segments=vocab)
    return {"W": [dw.astype(w.dtype)]}


@register_op("fused_embedding_pool", nondiff_inputs=("Ids", "Length"),
             custom_grad=_fused_embedding_pool_grad)
def _fused_embedding_pool(ins, attrs, ctx):
    w, ids, wgt = _emb_pool_prep(ins, attrs)
    if jax.default_backend() == "tpu":
        from .pallas_kernels import (fused_embedding_pool_supported,
                                     fused_embedding_pool_tpu)
        if fused_embedding_pool_supported(w, ids):
            return {"Out": [fused_embedding_pool_tpu(w, ids, wgt)]}
    # XLA fallback mirrors the unfused lookup_table + sequence_pool chain
    # (take -> zero padding rows -> masked sum); for sum pooling the
    # elementwise structure is identical, so a kernel-tier rewrite matches
    # the unrewritten program bit-for-bit on CPU (mean folds the divide
    # into the weights — allclose, one rounding step apart)
    gathered = jnp.take(w, ids, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        gathered = jnp.where((ids == padding_idx)[..., None], 0.0, gathered)
    return {"Out": [jnp.sum(gathered * wgt[..., None], axis=1)]}


@register_op("cvm", nondiff_inputs=("CVM",))
def _cvm(ins, attrs, ctx):
    return {"Y": [_cvm_fwd(_x(ins), attrs.get("use_cvm", True))]}


@register_op("continuous_value_model", nondiff_inputs=("CVM",))
def _continuous_value_model(ins, attrs, ctx):
    return {"Y": [_cvm_fwd(_x(ins), attrs.get("use_cvm", True))]}


@register_op("fused_seqpool_cvm", nondiff_inputs=("CVM", "Length"))
def _fused_seqpool_cvm(ins, attrs, ctx):
    """SUM-pool each padded slot sequence then apply CVM.

    Reference fused_seqpool_cvm_op.cc: a vector of LoD slot tensors is pooled
    and CVM-transformed in one kernel.  Padded layout: every X input is
    [B, T, D] with a shared Length [B]; outputs are [B, D(-2)].
    """
    use_cvm = attrs.get("use_cvm", True)
    pad_value = attrs.get("pad_value", 0.0)
    length = ins["Length"][0] if ins.get("Length") else None
    outs = []
    for x in ins["X"]:
        if length is not None:
            m = (jnp.arange(x.shape[1])[None, :] <
                 length.reshape(-1, 1)).astype(x.dtype)[..., None]
            pooled = jnp.sum(x * m, axis=1)
            # empty sequences pool to pad_value (fused_seqpool_cvm_op.cc)
            empty = (length.reshape(-1, 1) == 0)
            pooled = jnp.where(empty, pad_value, pooled)
        else:
            pooled = jnp.sum(x, axis=1)
        outs.append(_cvm_fwd(pooled, use_cvm))
    return {"Out": outs}


# --- batched / scaled FC -----------------------------------------------------
@register_op("batch_fc")
def _batch_fc(ins, attrs, ctx):
    """Per-slot batched FC (batch_fc_op.cc): Input [S, N, in], W [S, in, out],
    Bias [S, out] -> relu(Input @ W + Bias)."""
    x, w, b = _x(ins, "Input"), _x(ins, "W"), _x(ins, "Bias")
    out = jnp.einsum("sni,sio->sno", x, w,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = out + b[:, None, :]
    return {"Out": [jax.nn.relu(out)]}


@register_op("scaled_fc")
def _scaled_fc(ins, attrs, ctx):
    """scaled_fc_op.cc: inputs and bias are pre-scaled (int8-friendly CTR
    trick): out = relu((x*input_scale) @ w + b*bias_scale)."""
    x, w, b = _x(ins, "Input"), _x(ins, "W"), _x(ins, "Bias")
    isf = attrs.get("input_scale_factor", 1.0)
    bsf = attrs.get("bias_scale_factor", 1.0)
    out = (x * isf) @ w + b * bsf
    return {"Out": [jax.nn.relu(out)]}


@register_op("rank_attention", nondiff_inputs=("RankOffset",))
def _rank_attention(ins, attrs, ctx):
    """rank_attention_op.cc: every instance picks per-(its-rank, other-rank)
    parameter blocks from RankParam and contracts its features against them.

    X: [N, x_dim]; RankOffset: [N, 1+2*max_rank] int — col0 = ins rank
    (1-based, 0 = absent), then (other_rank, param_row_index) pairs;
    RankParam: [max_size, x_dim * para_col] — block row per index.
    Out: [N, para_col] = mean over present pairs of X[i] @ block.
    """
    x = _x(ins)
    rank_offset = ins["RankOffset"][0].astype(jnp.int32)
    param = _x(ins, "RankParam")
    max_rank = attrs.get("MaxRank", 3)
    n, x_dim = x.shape
    para_col = param.shape[1] // x_dim
    blocks = param.reshape(param.shape[0], x_dim, para_col)

    idx = rank_offset[:, 2::2]                      # [N, max_rank] block rows
    present = (rank_offset[:, 1::2] >= 0) & (rank_offset[:, 0:1] > 0)
    safe = jnp.maximum(idx, 0)
    sel = blocks[safe]                              # [N, max_rank, x_dim, pc]
    contrib = jnp.einsum("ni,nrip->nrp", x, sel,
                         preferred_element_type=jnp.float32)
    w = present.astype(contrib.dtype)[..., None]
    out = jnp.sum(contrib * w, axis=1) / jnp.maximum(
        jnp.sum(w, axis=1), 1.0)
    return {"Out": [out.astype(x.dtype)],
            "InputHelp": [x], "ParamHelp": [param],
            "InsRank": [rank_offset[:, 0:1].astype(x.dtype)]}


@register_op("cross_norm_hadamard")
def _cross_norm_hadamard(ins, attrs, ctx):
    """cross_norm_hadamard_op.cc: paired fields [a, b] of width fields_num ->
    concat(a, b, a*b) per pair, then (x-mean)/scale normalization using
    SummaryInput running stats."""
    x = _x(ins, "Input")
    summary = _x(ins, "SummaryInput")
    fields = attrs.get("fields_num", 1)
    embed = attrs.get("embed_dim", x.shape[1] // (2 * fields))
    pairs = x.reshape(x.shape[0], fields, 2, embed)
    a, b = pairs[:, :, 0], pairs[:, :, 1]
    had = jnp.concatenate([a, b, a * b], axis=-1)   # [N, fields, 3*embed]
    out = had.reshape(x.shape[0], -1)
    mean, scale = summary[0], jnp.maximum(summary[1], 1e-6)
    return {"Out": [(out - mean) / scale],
            "CudaMeans": [mean], "CudaScales": [scale]}


# --- instag filtering --------------------------------------------------------
@register_op("filter_by_instag",
             nondiff_inputs=("Ins_tag", "Filter_tag"), differentiable=False)
def _filter_by_instag(ins, attrs, ctx):
    """filter_by_instag_op.cc: keep rows whose tag set intersects filter tags.
    Static-shape version: rows failing the filter are zeroed and LossWeight=0
    (out_val_if_empty analog), instead of compacting the batch — the mask is
    what downstream loss-weighting consumes."""
    rows = ins["Ins"][0]
    tags = ins["Ins_tag"][0]          # [N, T] padded tag ids (-1 pad)
    filt = ins["Filter_tag"][0]       # [F]
    if tags.ndim == 1:
        tags = tags[:, None]
    hit = (tags[:, :, None] == filt[None, None, :]).any(axis=(1, 2))
    w = hit.astype(rows.dtype)
    shaped = w.reshape((-1,) + (1,) * (rows.ndim - 1))
    return {"Out": [rows * shaped],
            "LossWeight": [w.reshape(-1, 1)],
            "IndexMap": [jnp.stack([jnp.arange(rows.shape[0])] * 2, 1)]}


# --- hashing -----------------------------------------------------------------
def _xxhash_like(x, mod, seed):
    import jax
    if jax.config.jax_enable_x64:
        # mix the high word first so full 64-bit ids keep their entropy
        xu = x.astype(jnp.uint64)
        lo = (xu & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (xu >> jnp.uint64(32)).astype(jnp.uint32)
        lo = lo ^ (hi * jnp.uint32(2246822519))
    else:
        # x64 off: ids are at most 32-bit on device (the executor refuses
        # truncating int64 feeds), so hash the one word we actually have
        lo = x.astype(jnp.uint32)
    h = lo * jnp.uint32(2654435761) + jnp.uint32(seed)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(2246822519)
    h = h ^ (h >> 13)
    return (h % jnp.uint32(mod)).astype(wide_int())


@register_op("hash", differentiable=False)
def _hash(ins, attrs, ctx):
    """hash_op.cc: num_hash hashes of each int id row into [0, mod_by)."""
    x = _x(ins)
    num_hash = attrs.get("num_hash", 1)
    mod = attrs.get("mod_by", 1)
    outs = [_xxhash_like(x, mod, seed) for seed in range(num_hash)]
    return {"Out": [jnp.stack(outs, axis=-1)]}


@register_op("pyramid_hash", nondiff_inputs=("X",))
def _pyramid_hash(ins, attrs, ctx):
    """pyramid_hash_op.cc: hash n-gram windows of token ids into an embedding
    table (search-ads text matching).  Padded [B, T] ids; sums the embeddings
    of all (space_len) n-grams per sequence."""
    x = _x(ins).astype(wide_int())
    w = _x(ins, "W")
    num_emb = attrs.get("num_emb", w.shape[1])
    space_len = attrs.get("space_len", w.shape[0])
    pyramid_layer = attrs.get("pyramid_layer", 2)
    b, t = x.shape[:2]
    acc = jnp.zeros((b, num_emb), w.dtype)
    for n in range(2, 2 + pyramid_layer):
        if t < n:
            break
        for s in range(t - n + 1):
            gram = x[:, s:s + n]
            h = jnp.sum(gram * (jnp.arange(n) + 1)[None, :], axis=1)
            idx = (h % space_len).astype(jnp.int32)
            acc = acc + w[idx][:, :num_emb]
    return {"Out": [acc]}


# --- TDM (tree-based deep match) --------------------------------------------
@register_op("tdm_child", nondiff_inputs=("X", "TreeInfo"),
             differentiable=False)
def _tdm_child(ins, attrs, ctx):
    """tdm_child_op.cc: look up each node's children in the TreeInfo table.
    TreeInfo rows: [item_id, layer_id, parent_id, child_0..child_{n-1}]."""
    x = _x(ins).astype(jnp.int32)
    tree = ins["TreeInfo"][0].astype(jnp.int32)
    child_nums = attrs.get("child_nums", tree.shape[1] - 3)
    children = tree[:, 3:3 + child_nums]
    out = children[x.reshape(-1)].reshape(x.shape + (child_nums,))
    leaf = (out == 0).astype(jnp.int32)
    return {"Child": [out], "LeafMask": [1 - leaf]}


@register_op("tdm_sampler", nondiff_inputs=("X", "Travel", "Layer"),
             differentiable=False, stateful_rng=True)
def _tdm_sampler(ins, attrs, ctx):
    """tdm_sampler_op.cc: for each item, emit its travel path node per tree
    layer plus `neg_samples_num_list[i]` negatives sampled from that layer."""
    x = _x(ins).astype(jnp.int32).reshape(-1)
    travel = ins["Travel"][0].astype(jnp.int32)     # [n_items, n_layers]
    layer = ins["Layer"][0].astype(jnp.int32)       # [n_layers, width] padded
    negs = attrs.get("neg_samples_num_list", [1] * travel.shape[1])
    n = x.shape[0]
    outs, labels, masks = [], [], []
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    for li in range(travel.shape[1]):
        pos = travel[x][:, li:li + 1]
        k = jax.random.fold_in(key, li)
        neg_idx = jax.random.randint(k, (n, negs[li]), 0, layer.shape[1])
        neg = layer[li][neg_idx]
        outs.append(jnp.concatenate([pos, neg], axis=1))
        labels.append(jnp.concatenate(
            [jnp.ones((n, 1), jnp.int32), jnp.zeros((n, negs[li]), jnp.int32)],
            axis=1))
        masks.append((outs[-1] != 0).astype(jnp.int32))
    out = jnp.concatenate(outs, axis=1)
    return {"Out": [out.reshape(n, -1, 1)],
            "Labels": [jnp.concatenate(labels, 1).reshape(n, -1, 1)],
            "Mask": [jnp.concatenate(masks, 1).reshape(n, -1, 1)]}


@register_op("store_q_value", differentiable=False)
def _store_q_value(ins, attrs, ctx):
    """store_q_value_op (qingshui): passthrough that snapshots Q values for
    the AucRunner — device side is identity; persistence happens host-side."""
    return {"Out": [ins["Input"][0]]}


# --- sparse PS pull/push (device-side dense halves) --------------------------
@register_op("pull_box_sparse", nondiff_inputs=("Ids",))
def _pull_box_sparse(ins, attrs, ctx):
    """pull_box_sparse_op.cc device half: gather rows of the (HBM-cached)
    table for each id tensor.  The host BoxPS tier keeps W fresh between
    passes (distributed/ps HBM cache — BoxWrapper::PullSparse analog)."""
    w = ins["W"][0]
    outs = [w[ids.astype(jnp.int32)] for ids in ins["Ids"]]
    return {"Out": outs}


@register_op("push_box_sparse", differentiable=False)
def _push_box_sparse(ins, attrs, ctx):
    """Grad-side of pull_box_sparse: scatter-add grads into the table slot.
    Emitted explicitly by the PS meta-optimizer; returns the dense delta."""
    w = ins["W"][0]
    delta = jnp.zeros_like(w)
    for ids, g in zip(ins["Ids"], ins["Grad"]):
        delta = delta.at[ids.astype(jnp.int32)].add(g.astype(w.dtype))
    return {"Out": [delta]}


@register_op("pull_sparse", nondiff_inputs=("Ids",))
def _pull_sparse(ins, attrs, ctx):
    w = ins["W"][0]
    outs = [w[ids.astype(jnp.int32)] for ids in ins["Ids"]]
    return {"Out": outs}


@register_op("push_dense", differentiable=False)
def _push_dense(ins, attrs, ctx):
    """push_dense_op: device half is identity — the trainer runtime ships the
    grads to the PS (distributed/ps tables) after the step."""
    return {"Out": list(ins["Ids"]) if ins.get("Ids") else [ins["X"][0]]}


@register_op("merge_ids", nondiff_inputs=("Ids", "Rows"),
             differentiable=False)
def _merge_ids(ins, attrs, ctx):
    """merge_ids_op: re-interleave rows pulled from sharded tables back into
    the original id order (PS sharded-lookup plumbing)."""
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    parts = ins["X"]
    n_shard = len(parts)
    dim = parts[0].shape[-1]
    stacked = jnp.concatenate(parts, axis=0)
    shard = ids % n_shard
    # position of each id within its shard, in arrival order
    offsets = jnp.zeros_like(ids)
    for s in range(n_shard):
        in_s = (shard == s).astype(jnp.int32)
        offsets = offsets + in_s * (jnp.cumsum(in_s) - 1)
    base = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(jnp.asarray([p.shape[0] for p in parts[:-1]],
                                jnp.int32))])
    return {"Out": [stacked[base[shard] + offsets].reshape(
        ids.shape + (dim,))]}


@register_op("ps_lookup_rows", nondiff_inputs=("Ids",))
def _ps_lookup_rows(ins, attrs, ctx):
    """Device half of a PS-served embedding lookup: `Rows` is the per-batch
    host feed of rows pulled for each (flattened) id position — the XLA
    analog of DownpourWorker FillSparseValue (downpour_worker.cc:183)
    writing pulled values into the lookup output.  The vjp w.r.t. Rows is
    exactly the per-position row gradient the trainer pushes back
    (downpour_worker.cc:765); padding_idx positions are zeroed so their
    pushed grad is zero.  Emitted by distributed/ps/program_pass.py."""
    rows = ins["Rows"][0]
    ids = ins["Ids"][0]
    if attrs.get("v1") and ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])   # lookup_table squeezes [.., 1]
    dim = rows.shape[-1]
    out = rows.reshape(tuple(ids.shape) + (dim,))
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    return {"Out": [out]}


@register_op("data_norm",
             nondiff_inputs=("BatchSize", "BatchSum", "BatchSquareSum"),
             nondiff_outputs=("Means", "Scales", "BatchSizeOut",
                              "BatchSumOut", "BatchSquareSumOut"))
def _data_norm(ins, attrs, ctx):
    """CTR feature normalization with PERSISTABLE summary statistics
    (operators/data_norm_op.cc:292-303 forward; :650-698 stat
    accumulation).  Unlike batch_norm, the normalizer comes from the
    running summary (means = batch_sum/batch_size, scales =
    sqrt(batch_size/batch_square_sum)) and the backward treats it as a
    constant — d_x = d_y * scales falls out of the vjp because the stats
    are nondiff inputs.  TPU-native: the reference routes stat deltas
    through grad-op outputs + a PS summary accessor; here the op itself
    emits the decayed running update (summary_decay_rate) as write-back
    outputs, which the executor persists — one mechanism for single-chip
    and PS runs.  slot_dim > 0 replicates the show!=0 gating: instances
    whose slot's first element (the show count) is ~zero are skipped in
    the stat update (:655-663)."""
    x = ins["X"][0]
    bsize, bsum, bsq = (ins["BatchSize"][0], ins["BatchSum"][0],
                        ins["BatchSquareSum"][0])
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    y = (x - means) * scales
    if ins.get("ScaleW"):
        y = y * ins["ScaleW"][0] + ins["Bias"][0]
    outs = {"Y": [y], "Means": [means], "Scales": [scales]}
    if getattr(ctx, "is_test", False):
        return outs
    eps = attrs.get("epsilon", 1e-4)
    decay = attrs.get("summary_decay_rate", 0.9999999)
    slot_dim = int(attrs.get("slot_dim", -1))
    n, c = x.shape[0], x.shape[-1]
    if slot_dim > 0 and c % slot_dim == 0:
        xm = x.reshape(n, c // slot_dim, slot_dim)
        live = (jnp.abs(xm[:, :, 0]) > 1e-7)[..., None]      # show != 0
        cnt_s = live.sum(0).astype(x.dtype)                  # [slots, 1]
        cnt = jnp.broadcast_to(cnt_s, (c // slot_dim, slot_dim)).reshape(c)
        ssum = (xm * live).sum(0).reshape(c)
        ssq = (((xm - means.reshape(c // slot_dim, slot_dim)) ** 2)
               * live).sum(0).reshape(c)
        # per-batch normalization to size 1 (data_norm_op.cc:672-683)
        safe = jnp.maximum(cnt, 1.0)
        d_size = jnp.where(cnt >= 1, 1.0, 0.0)
        d_sum = jnp.where(cnt >= 1, ssum / safe, 0.0)
        d_sq = jnp.where(cnt >= 1, ssq / safe + cnt * eps, 0.0)
    else:
        d_size = jnp.full((c,), float(n), x.dtype)
        d_sum = x.reshape(-1, c).sum(0)
        d_sq = ((x - means) ** 2).reshape(-1, c).sum(0) + n * eps
    outs["BatchSizeOut"] = [decay * bsize + d_size]
    outs["BatchSumOut"] = [decay * bsum + d_sum]
    outs["BatchSquareSumOut"] = [decay * bsq + d_sq]
    return outs
