"""Remaining op-catalog entries: optimizer variants, math/manipulation
stragglers, fused CPU/GPU kernels re-expressed as XLA-fusable compositions.

Reference (SURVEY §A.1): operators/optimizers/{adamax,proximal_adagrad,
proximal_gd}_op.cc, operators/bilinear_tensor_product_op.cc,
operators/multiplex_op.cc, operators/minus_op.cc,
operators/modified_huber_loss_op.cc, operators/fill_diagonal (tril fill),
operators/pad_constant_like_op.cc, operators/partial_concat_op.cc (qingshui),
operators/partial_sum_op.cc, operators/pool_op (pool3d),
operators/spectral_norm_op.cc, operators/spp_op.cc,
operators/shuffle_channel_op.cc, operators/center_loss_op.cc,
operators/teacher_student_sigmoid_loss_op.cc, operators/bpr_loss_op.cc,
operators/positive_negative_pair_op.cc, operators/unique_op.cc,
operators/scatter_nd_add (scatter_nd), operators/fused/
fused_elemwise_activation_op.cc, fused_embedding_eltwise_layernorm_op.cu,
operators/metrics/precision_recall (detection_map in detection/),
operators/lod_reset_op.cc (no-op in padded layout), operators/diag_op.cc,
operators/lookup_table_dequant_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _x(ins, slot="X"):
    return ins[slot][0]


# --- optimizer variants ------------------------------------------------------
@register_op("adamax", differentiable=False)
def _adamax(ins, attrs, ctx):
    p, g = _x(ins, "Param"), _x(ins, "Grad")
    m, u = _x(ins, "Moment"), _x(ins, "InfNorm")
    lr = _x(ins, "LearningRate").reshape(())
    b1p = _x(ins, "Beta1Pow").reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m2 = b1 * m + (1 - b1) * g
    u2 = jnp.maximum(b2 * u, jnp.abs(g))
    p2 = p - (lr / (1 - b1p)) * m2 / (u2 + eps)
    return {"ParamOut": [p2], "MomentOut": [m2], "InfNormOut": [u2]}


@register_op("proximal_gd", differentiable=False)
def _proximal_gd(ins, attrs, ctx):
    p, g = _x(ins, "Param"), _x(ins, "Grad")
    lr = _x(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p2 = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
          / (1.0 + lr * l2))
    return {"ParamOut": [p2]}


@register_op("proximal_adagrad", differentiable=False)
def _proximal_adagrad(ins, attrs, ctx):
    p, g, m = _x(ins, "Param"), _x(ins, "Grad"), _x(ins, "Moment")
    lr = _x(ins, "LearningRate").reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    m2 = m + g * g
    alr = lr / jnp.sqrt(m2 + 1e-12)
    prox = p - alr * g
    p2 = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - alr * l1, 0.0)
          / (1.0 + alr * l2))
    return {"ParamOut": [p2], "MomentOut": [m2]}


# --- math stragglers ---------------------------------------------------------
@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ins, attrs, ctx):
    """out[b,k] = x[b] @ W[k] @ y[b] + bias[k] (bilinear_tensor_product_op)."""
    x, y, w = _x(ins), _x(ins, "Y"), _x(ins, "Weight")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": [out]}


@register_op("multiplex", nondiff_inputs=("Ids",))
def _multiplex(ins, attrs, ctx):
    """row r of output = row r of candidate X[Ids[r]] (multiplex_op.cc)."""
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    stacked = jnp.stack(ins["X"], axis=0)        # [K, B, D]
    return {"Out": [stacked[ids, jnp.arange(ids.shape[0])]]}


@register_op("minus")
def _minus(ins, attrs, ctx):
    return {"Out": [_x(ins) - _x(ins, "Y")]}


@register_op("elementwise_heaviside")
def _heaviside(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    return {"Out": [jnp.where(x > 0, 1.0, jnp.where(x == 0, y, 0.0))
                    .astype(x.dtype)]}


@register_op("modified_huber_loss", nondiff_inputs=("Y",))
def _modified_huber_loss(ins, attrs, ctx):
    """modified_huber_loss_op.cc: labels {0,1} -> {-1,+1}; quadratic inside
    margin, linear outside."""
    x, y = _x(ins), _x(ins, "Y")
    s = 2.0 * y - 1.0
    z = x * s
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"Out": [loss], "IntermediateVal": [z]}


@register_op("fill_diagonal")
def _fill_diagonal(ins, attrs, ctx):
    x = _x(ins)
    val = attrs.get("value", 0.0)
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n)
    return {"Out": [x.at[..., idx, idx].set(val)]}


@register_op("pad_constant_like", nondiff_inputs=("X",))
def _pad_constant_like(ins, attrs, ctx):
    """pad Y up to X's shape with pad_value (pad_constant_like_op.cc).
    Grad flows to Y only."""
    x, y = _x(ins), _x(ins, "Y")
    pad_value = attrs.get("pad_value", 0.0)
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads, constant_values=pad_value)]}


@register_op("partial_concat")
def _partial_concat(ins, attrs, ctx):
    """partial_concat_op.cc (qingshui): concat a column slice
    [start_index : start_index+length] of every input."""
    start = attrs.get("start_index", 0)
    length = attrs.get("length", -1)
    parts = []
    for x in ins["X"]:
        end = x.shape[1] if length < 0 else start + length
        parts.append(x[:, start:end])
    return {"Out": [jnp.concatenate(parts, axis=1)]}


@register_op("partial_sum")
def _partial_sum(ins, attrs, ctx):
    start = attrs.get("start_index", 0)
    length = attrs.get("length", -1)
    acc = None
    for x in ins["X"]:
        end = x.shape[1] if length < 0 else start + length
        piece = x[:, start:end]
        acc = piece if acc is None else acc + piece
    return {"Out": [acc]}


@register_op("pool3d")
def _pool3d(ins, attrs, ctx):
    x = _x(ins)                          # [B, C, D, H, W]
    ksize = attrs.get("ksize", [2, 2, 2])
    strides = attrs.get("strides", ksize)
    pads = attrs.get("paddings", [0, 0, 0])
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:])
        strides = ksize
        pads = [0, 0, 0]
    window = (1, 1) + tuple(ksize)
    stride = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, stride,
                                    padding)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride,
                                  padding)
        out = s / float(ksize[0] * ksize[1] * ksize[2])
    return {"Out": [out]}


@register_op("spp")
def _spp(ins, attrs, ctx):
    """spp_op.cc: spatial pyramid pooling — pyramid_height levels of adaptive
    max/avg pool, flattened and concatenated."""
    x = _x(ins)
    levels = attrs.get("pyramid_height", 1)
    ptype = attrs.get("pooling_type", "max")
    b, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = kh * bins - h, kw * bins - w
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, ph), (0, pw)),
                     constant_values=-jnp.inf if ptype == "max" else 0.0)
        r = xp.reshape(b, c, bins, kh, bins, kw)
        if ptype == "max":
            v = r.max(axis=(3, 5))
        else:
            v = r.sum(axis=(3, 5)) / (kh * kw)
        outs.append(v.reshape(b, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("shuffle_channel")
def _shuffle_channel(ins, attrs, ctx):
    x = _x(ins)
    g = attrs.get("group", 1)
    b, c, h, w = x.shape
    return {"Out": [x.reshape(b, g, c // g, h, w).swapaxes(1, 2)
                    .reshape(b, c, h, w)]}


@register_op("spectral_norm", nondiff_inputs=("U", "V"))
def _spectral_norm(ins, attrs, ctx):
    """spectral_norm_op.cc: weight / sigma where sigma from power iteration
    on (U, V) buffers."""
    w = _x(ins, "Weight")
    u = _x(ins, "U").reshape(-1)
    v = _x(ins, "V").reshape(-1)
    dim = attrs.get("dim", 0)
    iters = attrs.get("power_iters", 1)
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(max(iters, 0)):
        v = wm.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), 1e-12)
        u = wm @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), 1e-12)
    sigma = u @ wm @ v
    return {"Out": [w / jnp.maximum(sigma, 1e-12)]}


@register_op("center_loss", nondiff_inputs=("Label", "Centers",
                                            "CenterUpdateRate"))
def _center_loss(ins, attrs, ctx):
    """center_loss_op.cc: 0.5*||x - center[label]||^2 plus center EMA update."""
    x = _x(ins)
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)
    centers = ins["Centers"][0]
    alpha = (ins["CenterUpdateRate"][0].reshape(())
             if ins.get("CenterUpdateRate") else 0.5)
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    if attrs.get("need_update", True):
        cnt = jnp.zeros((centers.shape[0],), x.dtype).at[label].add(1.0)
        delta = jnp.zeros_like(centers).at[label].add(diff)
        centers = centers + alpha * delta / (cnt[:, None] + 1.0)
    return {"Loss": [loss], "SampleCenterDiff": [diff],
            "CentersOut": [centers]}


@register_op("teacher_student_sigmoid_loss", nondiff_inputs=("Label",))
def _ts_sigmoid_loss(ins, attrs, ctx):
    """teacher_student_sigmoid_loss_op.cc (CTR distillation): label < 0 means
    teacher soft score; label >= 0 the hard click bit."""
    x = _x(ins).reshape(-1)
    label = ins["Label"][0].reshape(-1)
    sl = attrs.get("soft_max_low_threshold", -2.0)
    sh = attrs.get("soft_max_up_threshold", 2.0)
    log1e = jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0.0)
    hard = log1e - x * (label > 0).astype(x.dtype)
    teacher = jnp.clip(-label, sl, sh)
    soft = log1e - x * jax.nn.sigmoid(teacher)
    loss = jnp.where(label < 0, soft, hard)
    return {"Y": [loss.reshape(-1, 1)]}


@register_op("positive_negative_pair", nondiff_inputs=("Label", "QueryID"),
             differentiable=False)
def _positive_negative_pair(ins, attrs, ctx):
    """positive_negative_pair_op.cc (ranking metric): within each query,
    count score-ordered pairs consistent/inconsistent with label order."""
    score = _x(ins, "Score").reshape(-1)
    label = ins["Label"][0].reshape(-1)
    qid = ins["QueryID"][0].astype(jnp.int32).reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), k=1)
    lbl_gt = label[:, None] > label[None, :]
    sc_gt = score[:, None] > score[None, :]
    sc_eq = score[:, None] == score[None, :]
    considered = same_q & upper & (label[:, None] != label[None, :])
    pos = jnp.sum(considered & (lbl_gt == sc_gt) & ~sc_eq)
    neu = jnp.sum(considered & sc_eq)
    neg = jnp.sum(considered) - pos - neu
    f = lambda v: v.reshape(1, 1).astype(jnp.float32)
    return {"PositivePair": [f(pos)], "NegativePair": [f(neg)],
            "NeutralPair": [f(neu)]}


@register_op("unique", differentiable=False)
def _unique(ins, attrs, ctx):
    """unique_op.cc static-shape analog: sorted unique with inverse Index;
    output padded to input length, UniqueCount gives the valid prefix."""
    x = _x(ins).reshape(-1)
    n = x.shape[0]
    s = jnp.sort(x)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    uniq_count = first.sum()
    rank = jnp.cumsum(first) - 1
    order = jnp.argsort(~first, stable=True)
    uniq = jnp.where(jnp.arange(n) < uniq_count, s[order], 0)
    pos_in_sorted = jnp.argsort(jnp.argsort(x, stable=True), stable=True)
    inverse = rank[pos_in_sorted]
    return {"Out": [uniq], "Index": [inverse.astype(jnp.int32)],
            "UniqueCount": [uniq_count.reshape(1).astype(jnp.int32)]}


@register_op("scatter_nd", nondiff_inputs=("Index", "Shape"))
def _scatter_nd(ins, attrs, ctx):
    idx = ins["Index"][0].astype(jnp.int32)
    upd = ins["Updates"][0]
    import numpy as np
    shape = [int(v) for v in np.asarray(ins["Shape"][0])] if ins.get(
        "Shape") else attrs["shape"]
    out = jnp.zeros(shape, upd.dtype)
    return {"Out": [out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)]}


@register_op("gaussian_random_batch_size_like", nondiff_inputs=("Input",),
             differentiable=False, stateful_rng=True)
def _grbsl(ins, attrs, ctx):
    ref = ins["Input"][0]
    shape = list(attrs.get("shape"))
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)]
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    out = (attrs.get("mean", 0.0)
           + attrs.get("std", 1.0) * jax.random.normal(key, tuple(shape)))
    return {"Out": [out.astype(jnp.float32)]}


@register_op("diag", differentiable=False)
def _diag(ins, attrs, ctx):
    return {"Out": [jnp.diag(ins["Diagonal"][0].reshape(-1))]}


@register_op("lookup_table_dequant", nondiff_inputs=("Ids",))
def _lookup_table_dequant(ins, attrs, ctx):
    """lookup_table_dequant_op.cc: rows store [min, max, int8 codes]; output
    dequantized embeddings (pslib quantized table format)."""
    w = _x(ins, "W")
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    rows = w[ids]
    mn, mx = rows[:, 0:1], rows[:, 1:2]
    codes = rows[:, 2:]
    out = mn + (mx - mn) * codes / 255.0
    return {"Out": [out]}


# --- fused compositions (XLA fuses; op kept for graph parity) ---------------
_UNARY = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
          "identity": lambda v: v, "": lambda v: v}


@register_op("fused_elemwise_activation")
def _fused_elemwise_activation(ins, attrs, ctx):
    """fused_elemwise_activation_op.cc: functor_list like
    ['elementwise_add', 'relu'] applied as f2(f1(x, y)).  Honors the
    elementwise `axis` attr with the same alignment as the standalone
    elementwise ops (the fuse_elewise_add_act pass folds fc's axis=1 bias
    add), and IntermediateOut is f1's result, not the final value."""
    from .math import _bcast
    x, y = _bcast(_x(ins), _x(ins, "Y"), attrs.get("axis", -1))
    functors = attrs.get("functor_list", ["elementwise_add", "relu"])
    binop = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply,
             "elementwise_sub": jnp.subtract}
    cur = inter = None
    for f in functors:
        if f in binop:
            cur = binop[f](x, y) if cur is None else binop[f](cur, y)
        else:
            name = f.replace("scale", "identity")
            cur = _UNARY.get(name, _UNARY["identity"])(
                cur if cur is not None else x)
        inter = cur if inter is None else inter
    return {"Out": [cur], "IntermediateOut": [inter]}


@register_op("fused_embedding_eltwise_layernorm",
             nondiff_inputs=("Ids",))
def _fused_emb_ln(ins, attrs, ctx):
    """fused_embedding_eltwise_layernorm_op.cu: sum N embedding lookups then
    LayerNorm — the BERT embedding block as one op."""
    ids_list = ins["Ids"]
    embs = ins["Embs"]
    acc = None
    for ids, emb in zip(ids_list, embs):
        v = emb[ids.astype(jnp.int32).reshape(ids.shape[:2])]
        acc = v if acc is None else acc + v
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    mu = acc.mean(-1, keepdims=True)
    var = jnp.var(acc, -1, keepdims=True)
    return {"Out": [(acc - mu) / jnp.sqrt(var + eps) * scale + bias]}


@register_op("fusion_group")
def _fusion_group(ins, attrs, ctx):
    """fusion_group_pass's NVRTC-codegen op: on TPU, XLA is the fusion
    compiler, so this is identity over its inputs (graph-parity stub)."""
    return {"Outs": list(ins["Inputs"])}


@register_op("dropout_nd", stateful_rng=True, nondiff_outputs=("Mask",))
def _dropout_nd(ins, attrs, ctx):
    x = _x(ins)
    p = attrs.get("dropout_prob", 0.5)
    axis = attrs.get("axis", None)
    if attrs.get("is_test", False) or ctx.is_test:
        return {"Out": [x], "Mask": [jnp.ones_like(x, jnp.uint8)]}
    shape = list(x.shape)
    if axis is not None:
        shape = [s if i in (axis if isinstance(axis, (list, tuple))
                            else [axis]) else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(
        ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0)), 1 - p,
                                tuple(shape))
    return {"Out": [jnp.where(keep, x / (1 - p), 0.0).astype(x.dtype)],
            "Mask": [jnp.broadcast_to(keep, x.shape).astype(jnp.uint8)]}


@register_op("lod_reset", nondiff_inputs=("Y",))
def _lod_reset(ins, attrs, ctx):
    """LoD is replaced by explicit Length tensors in this framework; data
    passes through unchanged (lod_reset_op.cc parity stub)."""
    return {"Out": [_x(ins)]}


# lod_rank_table moved to plumbing_ops.py (full lengths+index table that
# max_sequence_len / reorder_lod_tensor_by_rank / shrink_rnn_memory consume)
