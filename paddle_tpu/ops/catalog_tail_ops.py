"""Op-catalog tail: fc/py_func/rnn/recurrent, compare_all, sequence tail,
detection tail, and sparse-table fused updates.

Reference files (SURVEY A.1): fc_op.cc, py_func_op.cc, rnn_op.cc (2.0
generic RNN), recurrent_op.cc (StaticRNN), attention_lstm_op.cc,
controlflow/compare_all_op.cc, sequence_ops/sequence_reshape_op.cc,
sequence_ops/sequence_topk_avg_pooling_op.cc, detection/{box_clip,
box_decoder_and_assign,matrix_nms,locality_aware_nms,mine_hard_examples,
yolov3_loss,generate_proposals_v2,roi_perspective_transform}_op.cc,
detection_map_op.cc, deformable_psroi_pooling_op.cc, bilateral_slice_op.cc,
fused/fusion_conv_inception_op.cc, pull_box_extended_sparse_op.cc,
pull_sparse_v2 (pull_sparse_op.cc), distributed_ops/lookup_sparse_table_
{fuse_sgd,fuse_adam,merge,grad_split}_op.cc.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, get_op


def _p(ins, slot):
    return ins[slot][0]


def _act(name, x):
    if not name or name == "identity":
        return x
    return {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
            "tanh": jnp.tanh, "gelu": jax.nn.gelu}[name](x)


# ---------------------------------------------------------------------------
# framework tail
# ---------------------------------------------------------------------------

@register_op("fc")
def _fc(ins, attrs, ctx):
    """fc_op.cc: flatten to in_num_col_dims, matmul, bias, activation."""
    x, w = _p(ins, "Input"), _p(ins, "W")
    ncol = attrs.get("in_num_col_dims", 1)
    lead = int(np.prod(x.shape[:ncol]))
    out = x.reshape(lead, -1) @ w
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(1, -1)
    out = _act(attrs.get("activation_type", ""), out)
    return {"Out": [out.reshape(tuple(x.shape[:ncol]) + (w.shape[1],))]}


_PY_FUNCS = []


def register_py_func(fn) -> int:
    """Reference py_func_op registers callables by index attr."""
    _PY_FUNCS.append(fn)
    return len(_PY_FUNCS) - 1


@register_op("py_func", differentiable=False)
def _py_func(ins, attrs, ctx):
    """py_func_op.cc: call registered Python on the host via pure_callback.
    Output shapes/dtypes come from `out_shapes`/`out_dtypes` attrs (the
    reference infers them from the declared out vars)."""
    fn = _PY_FUNCS[int(attrs["forward_callable_id"])]
    xs = list(ins.get("X", []))
    shapes = attrs.get("out_shapes", [])
    dtypes = attrs.get("out_dtypes", ["float32"] * len(shapes))
    structs = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
               for s, d in zip(shapes, dtypes)]

    def host(*arrays):
        out = fn(*[np.asarray(a) for a in arrays])
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(np.asarray(o, structs[i].dtype).reshape(
            structs[i].shape) for i, o in enumerate(out))

    outs = jax.pure_callback(host, tuple(structs), *xs)
    return {"Out": list(outs)}


@register_op("equal_all", differentiable=False)
def _equal_all(ins, attrs, ctx):
    x, y = _p(ins, "X"), _p(ins, "Y")
    same = (x.shape == y.shape) and bool_all(jnp.equal(x, y))
    return {"Out": [jnp.asarray(same) if isinstance(same, bool)
                    else same]}


def bool_all(x):
    return jnp.all(x)


@register_op("rnn", nondiff_inputs=("SequenceLength", "PreState"))
def _rnn(ins, attrs, ctx):
    """rnn_op.cc (2.0 generic): mode selects LSTM/GRU/RNN_TANH/RNN_RELU;
    weights arrive as the flat WeightList [Wx_l0, Wh_l0, bx_l0, bh_l0, ...].
    Single direction; layers chain."""
    x = _p(ins, "Input")                    # [B, T, I] (batch_first here)
    wl = list(ins["WeightList"])
    mode = attrs.get("mode", "LSTM").upper()
    num_layers = attrs.get("num_layers", 1)
    hidden = attrs.get("hidden_size", wl[1].shape[0])
    per = len(wl) // num_layers
    h = x
    for l in range(num_layers):
        # WeightList convention here: Wx [I, G] input-major, Wh [H, G]
        wx, wh = wl[l * per], wl[l * per + 1]
        bias = None
        if per >= 3:
            bias = sum(b.reshape(-1) for b in wl[l * per + 2: (l + 1) * per])
        proj = h @ wx
        if bias is not None:
            proj = proj + bias
        if mode == "LSTM":
            outs = get_op("lstm").fn(
                {"Input": [proj], "Weight": [wh.T]},
                {"use_peepholes": False}, ctx)
            h = outs["Hidden"][0]
        elif mode == "GRU":
            outs = get_op("gru").fn({"Input": [proj], "Weight": [wh.T]},
                                    {}, ctx)
            h = outs["Hidden"][0]
        else:
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

            def step(carry, xt):
                nh = act(xt + carry @ wh.T)
                return nh, nh

            h0 = jnp.zeros((h.shape[0], hidden), h.dtype)
            _, ys = lax.scan(step, h0, jnp.swapaxes(proj, 0, 1))
            h = jnp.swapaxes(ys, 0, 1)
    return {"Out": [h]}


@register_op("recurrent", differentiable=False)
def _recurrent(ins, attrs, ctx):
    """recurrent_op.cc (StaticRNN): run the step sub-block once per time
    step, feeding sequence inputs step-wise and threading state.  Unrolled
    at trace time (T is static under XLA); lax.scan-backed rnn ops are the
    performant path — this exists for program parity."""
    from ..fluid.executor import run_block_ops
    block_idx = attrs["sub_block"]
    program = attrs["__program__"]          # bound by the executor path
    sub = program.blocks[block_idx]
    seq_ins = {n: v for n, v in zip(attrs.get("inputs", []),
                                    ins.get("Inputs", []))}
    states = {n: v for n, v in zip(attrs.get("ex_states", []),
                                   ins.get("InitStates", []))}
    params = {n: v for n, v in zip(attrs.get("parameters", []),
                                   ins.get("Parameters", []))}
    state_names = attrs.get("states", [])
    out_names = attrs.get("outputs", [])
    T = next(iter(seq_ins.values())).shape[1] if seq_ins else attrs["len"]
    collected = {n: [] for n in out_names}
    for t in range(T):
        env = dict(params)      # weights visible inside the step block
        for n, v in seq_ins.items():
            env[n] = v[:, t]
        for (ex_n, v), cur_n in zip(states.items(), state_names):
            env[ex_n] = v
        run_block_ops(sub, env, ctx)
        states = {ex_n: env[cur_n] for ex_n, cur_n
                  in zip(states.keys(), state_names)}
        for n in out_names:
            collected[n].append(env[n])
    return {"Out": [jnp.stack(collected[n], axis=1) for n in out_names]}


@register_op("attention_lstm")
def _attention_lstm(ins, attrs, ctx):
    """attention_lstm_op.cc: per step, softmax attention over the input
    sequence conditioned on prev hidden, then one LSTM cell step."""
    x = _p(ins, "X")                        # [B, T, I]
    aw = _p(ins, "AttentionWeight")         # [I+H, 1]
    lw = _p(ins, "LSTMWeight")              # [I+H, 4H]
    lb = _p(ins, "LSTMBias").reshape(-1)    # [4H]
    b, t, d = x.shape
    hdim = lw.shape[1] // 4
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, hdim), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, hdim), x.dtype)

    def step(carry, _):
        h, c = carry
        hx = jnp.concatenate(
            [x, jnp.broadcast_to(h[:, None], (b, t, hdim))], axis=-1)
        score = jnp.squeeze(hx @ aw, -1)              # [B, T]
        alpha = jax.nn.softmax(score, axis=-1)
        ctx_vec = jnp.einsum("bt,btd->bd", alpha, x)  # [B, I]
        gates = jnp.concatenate([ctx_vec, h], -1) @ lw + lb
        i, f, cc, o = jnp.split(gates, 4, axis=1)
        nc = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(cc)
        nh = jax.nn.sigmoid(o) * jnp.tanh(nc)
        return (nh, nc), nh

    (h, c), hs = lax.scan(step, (h0, c0), jnp.arange(t))
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "Cell": [c]}


# ---------------------------------------------------------------------------
# sequence tail
# ---------------------------------------------------------------------------

@register_op("sequence_reshape")
def _sequence_reshape(ins, attrs, ctx):
    x = _p(ins, "X")
    new_dim = attrs["new_dim"]
    return {"Out": [x.reshape(x.shape[0], -1, new_dim)
                    if x.ndim == 3 else x.reshape(-1, new_dim)]}


@register_op("sequence_topk_avg_pooling", nondiff_inputs=("ROW", "COLUMN"))
def _sequence_topk_avg_pooling(ins, attrs, ctx):
    """Top-k average over the last axis per channel (padded layout)."""
    x = _p(ins, "X")                        # [B, C, L]
    topks = attrs.get("topks", [1])
    outs = []
    for k in topks:
        top = lax.top_k(x, min(k, x.shape[-1]))[0]
        outs.append(jnp.mean(top, axis=-1))
    return {"Out": [jnp.concatenate(outs, axis=-1)]}


# ---------------------------------------------------------------------------
# detection tail
# ---------------------------------------------------------------------------

@register_op("box_clip", nondiff_inputs=("ImInfo",))
def _box_clip(ins, attrs, ctx):
    boxes, im_info = _p(ins, "Input"), _p(ins, "ImInfo")
    h = im_info[..., 0:1] - 1.0
    w = im_info[..., 1:2] - 1.0
    x1 = jnp.clip(boxes[..., 0::4], 0, w)
    y1 = jnp.clip(boxes[..., 1::4], 0, h)
    x2 = jnp.clip(boxes[..., 2::4], 0, w)
    y2 = jnp.clip(boxes[..., 3::4], 0, h)
    out = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(boxes.shape)
    return {"Output": [out]}


@register_op("box_decoder_and_assign", nondiff_inputs=("PriorBox",
                                                       "BoxScore"))
def _box_decoder_and_assign(ins, attrs, ctx):
    prior, var = _p(ins, "PriorBox"), attrs.get("box_var", [0.1, 0.1,
                                                            0.2, 0.2])
    target, score = _p(ins, "TargetBox"), _p(ins, "BoxScore")
    n, c4 = target.shape
    ncls = c4 // 4
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    cx = prior[:, 0] + pw * 0.5
    cy = prior[:, 1] + ph * 0.5
    t = target.reshape(n, ncls, 4)
    dx, dy, dw, dh = (t[..., 0] * var[0], t[..., 1] * var[1],
                      t[..., 2] * var[2], t[..., 3] * var[3])
    gx = cx[:, None] + dx * pw[:, None]
    gy = cy[:, None] + dy * ph[:, None]
    gw = jnp.exp(dw) * pw[:, None]
    gh = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([gx - gw * 0.5, gy - gh * 0.5,
                         gx + gw * 0.5 - 1, gy + gh * 0.5 - 1], axis=-1)
    best = jnp.argmax(score[:, 1:], axis=1) + 1   # skip background col 0
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return {"DecodeBox": [decoded.reshape(n, c4)],
            "OutputAssignBox": [assigned]}


@register_op("matrix_nms", differentiable=False)
def _matrix_nms(ins, attrs, ctx):
    """matrix_nms_op.cc: soft suppression by pairwise-IoU decay matrix."""
    boxes, scores = _p(ins, "BBoxes"), _p(ins, "Scores")
    # boxes [B, M, 4], scores [B, C, M]
    bsz, m = boxes.shape[0], boxes.shape[1]
    ncls = scores.shape[1]
    thr = attrs.get("score_threshold", 0.0)
    use_gauss = attrs.get("use_gaussian", False)
    sigma = attrs.get("gaussian_sigma", 2.0)

    def iou(b):
        area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area[:, None] + area[None] - inter, 1e-9)

    outs = []
    for bi in range(bsz):
        per_cls = []
        m_iou = iou(boxes[bi])
        for c in range(ncls):
            s = scores[bi, c]
            order = jnp.argsort(-s)
            sorted_iou = m_iou[order][:, order]
            upper = jnp.triu(sorted_iou, k=1)
            max_iou = jnp.max(upper, axis=0)       # vs higher-scored
            if use_gauss:
                decay = jnp.exp(-(max_iou ** 2) / sigma)
            else:
                decay = 1.0 - max_iou
            dec = s[order] * decay
            keep = dec > thr
            cls_col = jnp.full((m, 1), float(c))
            per_cls.append(jnp.concatenate(
                [cls_col, jnp.where(keep, dec, -1.0)[:, None],
                 boxes[bi][order]], axis=1))
        outs.append(jnp.concatenate(per_cls, axis=0))
    out = jnp.stack(outs)
    return {"Out": [out],
            "Index": [jnp.zeros((bsz, out.shape[1]), jnp.int32)],
            "RoisNum": [jnp.full((bsz,), out.shape[1], jnp.int32)]}


@register_op("locality_aware_nms", differentiable=False)
def _locality_aware_nms(ins, attrs, ctx):
    """locality_aware_nms_op.cc: weighted-merge overlapping boxes by
    score, then suppress.  Padded-output version: suppressed entries keep
    score -1 (fixed shapes; one-vs-higher-scored suppression in place of
    sequential greedy — same keep set whenever overlaps are transitive)."""
    boxes, scores = _p(ins, "BBoxes"), _p(ins, "Scores")
    # boxes [B, M, 4], scores [B, C, M]
    nms_thr = attrs.get("nms_threshold", 0.3)
    score_thr = attrs.get("score_threshold", 0.0)
    bsz, m = boxes.shape[0], boxes.shape[1]
    ncls = scores.shape[1]

    def iou_matrix(b):
        area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area[:, None] + area[None] - inter, 1e-9)

    outs = []
    for bi in range(bsz):
        m_iou = iou_matrix(boxes[bi])
        per_cls = []
        for c in range(ncls):
            s = scores[bi, c]
            # weighted merge of overlapping boxes (locality-aware step)
            wsum = jnp.sum(jnp.where(m_iou > nms_thr, s[None, :], 0.0),
                           axis=1)
            merged = jnp.einsum(
                "ij,jk->ik", jnp.where(m_iou > nms_thr, s[None, :], 0.0),
                boxes[bi]) / jnp.maximum(wsum, 1e-9)[:, None]
            # suppress: any higher-scored box overlapping > thr wins
            higher = (s[None, :] > s[:, None]) & (m_iou > nms_thr)
            keep = (~jnp.any(higher, axis=1)) & (s > score_thr)
            cls_col = jnp.full((m, 1), float(c))
            per_cls.append(jnp.concatenate(
                [cls_col, jnp.where(keep, s, -1.0)[:, None], merged],
                axis=1))
        outs.append(jnp.concatenate(per_cls, axis=0))
    out = jnp.stack(outs)
    return {"Out": [out]}


@register_op("mine_hard_examples", differentiable=False)
def _mine_hard_examples(ins, attrs, ctx):
    """mine_hard_examples_op.cc: pick top-k negative anchors by loss with
    neg_pos_ratio against the positive count (padded mask output)."""
    cls_loss = _p(ins, "ClsLoss")           # [B, A]
    match = _p(ins, "MatchIndices")         # [B, A], -1 = negative
    ratio = attrs.get("neg_pos_ratio", 3.0)
    pos = match >= 0
    n_pos = jnp.sum(pos, axis=1, keepdims=True)
    n_neg = jnp.minimum((n_pos * ratio).astype(jnp.int32),
                        jnp.sum(~pos, axis=1, keepdims=True))
    neg_loss = jnp.where(pos, -jnp.inf, cls_loss)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.argsort(order, axis=1)
    neg_mask = rank < n_neg
    return {"NegIndices": [jnp.where(neg_mask, 1, 0).astype(jnp.int32)],
            "UpdatedMatchIndices": [jnp.where(neg_mask, -1, match)]}


@register_op("yolov3_loss", nondiff_inputs=("GTBox", "GTLabel", "GTScore"))
def _yolov3_loss(ins, attrs, ctx):
    """yolov3_loss_op.cc — per-cell objectness + box + class loss against
    assigned ground truth (simplified assignment: best anchor per gt by
    IoU of shapes, as the reference does at the matched downsample)."""
    x = _p(ins, "X")                        # [B, A*(5+C), H, W]
    gt_box = _p(ins, "GTBox")               # [B, G, 4] (cx,cy,w,h) in [0,1]
    gt_label = _p(ins, "GTLabel")           # [B, G]
    anchors = np.asarray(attrs.get("anchors", [10, 13, 16, 30, 33, 23]),
                         np.float32).reshape(-1, 2)
    mask = attrs.get("anchor_mask", list(range(len(anchors))))
    ncls = attrs.get("class_num", 1)
    down = attrs.get("downsample_ratio", 32)
    bsz, _, h, w = x.shape
    na = len(mask)
    pred = x.reshape(bsz, na, 5 + ncls, h, w)
    px, py = jax.nn.sigmoid(pred[:, :, 0]), jax.nn.sigmoid(pred[:, :, 1])
    pw, ph = pred[:, :, 2], pred[:, :, 3]
    pobj = pred[:, :, 4]
    pcls = pred[:, :, 5:]

    input_size = down * h
    amask = anchors[mask] / input_size

    # gt -> responsible cell + best anchor (shape IoU)
    gx, gy = gt_box[..., 0], gt_box[..., 1]
    gw, gh = gt_box[..., 2], gt_box[..., 3]
    valid = (gw > 0) & (gh > 0)
    ci = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
    cj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
    inter = (jnp.minimum(gw[..., None], amask[None, None, :, 0])
             * jnp.minimum(gh[..., None], amask[None, None, :, 1]))
    union = (gw * gh)[..., None] + (amask[:, 0] * amask[:, 1])[None, None] \
        - inter
    best_a = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)

    obj_target = jnp.zeros((bsz, na, h, w))
    loss = jnp.zeros((bsz,))
    g = gt_box.shape[1]
    bidx = jnp.arange(bsz)[:, None].repeat(g, 1).reshape(-1)
    aidx = best_a.reshape(-1)
    jidx, iidx = cj.reshape(-1), ci.reshape(-1)
    vflat = valid.reshape(-1)
    obj_target = obj_target.at[bidx, aidx, jidx, iidx].max(
        jnp.where(vflat, 1.0, 0.0))

    tx = gx * w - jnp.floor(gx * w)
    ty = gy * h - jnp.floor(gy * h)
    tw = jnp.log(jnp.maximum(gw[..., None] / amask[None, None, :, 0],
                             1e-9))[jnp.arange(bsz)[:, None],
                                    jnp.arange(g)[None, :], best_a]
    th = jnp.log(jnp.maximum(gh[..., None] / amask[None, None, :, 1],
                             1e-9))[jnp.arange(bsz)[:, None],
                                    jnp.arange(g)[None, :], best_a]
    sel = (bidx, aidx, jidx, iidx)
    box_scale = (2.0 - gw * gh).reshape(-1)
    bce = lambda p_, t_: jnp.maximum(p_, 0) - p_ * t_ + jnp.log1p(
        jnp.exp(-jnp.abs(p_)))
    box_loss = (bce(jax.scipy.special.logit(
        jnp.clip(px[sel], 1e-6, 1 - 1e-6)), tx.reshape(-1))
        + bce(jax.scipy.special.logit(
            jnp.clip(py[sel], 1e-6, 1 - 1e-6)), ty.reshape(-1))
        + jnp.square(pw[sel] - tw.reshape(-1))
        + jnp.square(ph[sel] - th.reshape(-1))) * box_scale
    obj_loss = jnp.sum(bce(pobj, obj_target), axis=(1, 2, 3))
    cls_t = jax.nn.one_hot(gt_label.reshape(-1), ncls)
    cls_loss = jnp.sum(bce(jnp.moveaxis(pcls, 2, -1)[sel], cls_t),
                       axis=-1)
    per_gt = jnp.where(vflat, box_loss + cls_loss, 0.0)
    loss = obj_loss + jnp.sum(per_gt.reshape(bsz, g), axis=1)
    return {"Loss": [loss]}


@register_op("detection_map", differentiable=False)
def _detection_map(ins, attrs, ctx):
    """detection_map_op.cc: mean average precision accumulator — padded
    one-shot version: AP over provided detections vs labels."""
    det = _p(ins, "DetectRes")              # [N, 6] label,score,x1,y1,x2,y2
    label = _p(ins, "Label")                # [M, 6] label,x1,y1,x2,y2,diff?
    thr = attrs.get("overlap_threshold", 0.5)

    def host_map(d, l):
        d, l = np.asarray(d), np.asarray(l)
        if len(l) == 0 or len(d) == 0:
            return np.zeros((1,), np.float32)
        aps = []
        for cls in np.unique(l[:, 0]):
            gt = l[l[:, 0] == cls][:, 1:5]
            dd = d[d[:, 0] == cls]
            dd = dd[np.argsort(-dd[:, 1])]
            used = np.zeros(len(gt), bool)
            tp = np.zeros(len(dd))
            for i, row in enumerate(dd):
                box = row[2:6]
                if not len(gt):
                    continue
                xx1 = np.maximum(gt[:, 0], box[0])
                yy1 = np.maximum(gt[:, 1], box[1])
                xx2 = np.minimum(gt[:, 2], box[2])
                yy2 = np.minimum(gt[:, 3], box[3])
                inter = np.clip(xx2 - xx1, 0, None) * np.clip(
                    yy2 - yy1, 0, None)
                a1 = (box[2] - box[0]) * (box[3] - box[1])
                a2 = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
                iou = inter / np.maximum(a1 + a2 - inter, 1e-9)
                j = int(np.argmax(iou))
                if iou[j] >= thr and not used[j]:
                    tp[i] = 1
                    used[j] = True
            cum_tp = np.cumsum(tp)
            prec = cum_tp / (np.arange(len(dd)) + 1)
            rec = cum_tp / len(gt)
            ap = 0.0
            for t in np.arange(0, 1.01, 0.1):
                p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                ap += p / 11
            aps.append(ap)
        return np.asarray([np.mean(aps)], np.float32)

    out = jax.pure_callback(host_map, jax.ShapeDtypeStruct((1,),
                                                           jnp.float32),
                            det, label)
    return {"MAP": [out], "AccumPosCount": [jnp.zeros((1,), jnp.int32)],
            "AccumTruePos": [jnp.zeros((1,), jnp.float32)],
            "AccumFalsePos": [jnp.zeros((1,), jnp.float32)]}


@register_op("generate_proposals_v2", differentiable=False)
def _generate_proposals_v2(ins, attrs, ctx):
    return get_op("generate_proposals").fn(ins, attrs, ctx)


@register_op("roi_perspective_transform", nondiff_inputs=("ROIs",))
def _roi_perspective_transform(ins, attrs, ctx):
    """roi_perspective_transform_op.cc: warp quadrilateral rois to a fixed
    rectangle — approximated by axis-aligned roi_align over the quad's
    bounding box (TPU-friendly, no gather-scatter irregularity)."""
    x, rois = _p(ins, "X"), _p(ins, "ROIs")   # rois [N, 8] quad corners
    xs, ys = rois[:, 0::2], rois[:, 1::2]
    bbox = jnp.stack([jnp.min(xs, 1), jnp.min(ys, 1),
                      jnp.max(xs, 1), jnp.max(ys, 1)], axis=1)
    out = get_op("roi_align").fn(
        {"X": [x], "ROIs": [bbox]},
        {"pooled_height": attrs.get("transformed_height", 8),
         "pooled_width": attrs.get("transformed_width", 8),
         "spatial_scale": attrs.get("spatial_scale", 1.0)}, ctx)
    return {"Out": out["Out"]}


@register_op("deformable_psroi_pooling", nondiff_inputs=("ROIs", "Trans"))
def _deformable_psroi_pooling(ins, attrs, ctx):
    """deformable_psroi_pooling_op.cc: psroi pooling with learned part
    offsets; offsets shift each bin's sampling box."""
    x, rois = _p(ins, "X"), _p(ins, "ROIs")
    trans = ins["Trans"][0] if ins.get("Trans") else None
    ph = attrs.get("pooled_height", attrs.get("pooled_size", 7))
    pw = attrs.get("pooled_width", attrs.get("pooled_size", 7))
    if trans is not None:
        ts = attrs.get("trans_std", 0.1)
        n = rois.shape[0]
        off = trans.reshape(n, 2, -1)[:, :, 0] * ts
        w = rois[:, 2] - rois[:, 0]
        h = rois[:, 3] - rois[:, 1]
        rois = rois + jnp.stack([off[:, 0] * w, off[:, 1] * h,
                                 off[:, 0] * w, off[:, 1] * h], axis=1)
    return get_op("psroi_pool").fn(
        {"X": [x], "ROIs": [rois]},
        {"pooled_height": ph, "pooled_width": pw,
         "output_channels": attrs.get("output_channels",
                                      attrs.get("output_dim", 1)),
         "spatial_scale": attrs.get("spatial_scale", 1.0)}, ctx)


@register_op("bilateral_slice")
def _bilateral_slice(ins, attrs, ctx):
    """bilateral_slice_op.cc (HDRnet): slice a bilateral grid by (x, y,
    guide) with trilinear interpolation."""
    grid, guide = _p(ins, "Grid"), _p(ins, "Guide")
    # grid [B, C, D, GH, GW], guide [B, H, W] in [0,1]
    b, c, d, gh, gw = grid.shape
    h, w = guide.shape[1:]
    ys = jnp.linspace(0, gh - 1, h)
    xs = jnp.linspace(0, gw - 1, w)
    gz = jnp.clip(guide * (d - 1), 0, d - 1)

    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    z0 = jnp.floor(gz).astype(jnp.int32)
    fy = (ys - y0)[None, :, None]
    fx = (xs - x0)[None, None, :]
    fz = gz - z0
    y1 = jnp.clip(y0 + 1, 0, gh - 1)
    x1 = jnp.clip(x0 + 1, 0, gw - 1)
    z1 = jnp.clip(z0 + 1, 0, d - 1)

    # vectorized trilinear: gather 8 corners
    def corner(zi, yi, xi):
        gp = grid[:, :, :, yi[:, None], xi[None, :]]     # [B,C,D,H,W]
        zi_b = jnp.broadcast_to(zi[:, None, :, :], (b, c, h, w))
        return jnp.take_along_axis(gp, zi_b[:, :, None], axis=2)[:, :, 0]

    c000 = corner(z0, y0, x0)
    c001 = corner(z0, y0, x1)
    c010 = corner(z0, y1, x0)
    c011 = corner(z0, y1, x1)
    c100 = corner(z1, y0, x0)
    c101 = corner(z1, y0, x1)
    c110 = corner(z1, y1, x0)
    c111 = corner(z1, y1, x1)
    fzb = fz[:, None]
    out = ((1 - fzb) * ((1 - fy) * ((1 - fx) * c000 + fx * c001)
                        + fy * ((1 - fx) * c010 + fx * c011))
           + fzb * ((1 - fy) * ((1 - fx) * c100 + fx * c101)
                    + fy * ((1 - fx) * c110 + fx * c111)))
    return {"Out": [out]}


@register_op("fusion_conv_inception")
def _fusion_conv_inception(ins, attrs, ctx):
    """fusion_conv_inception_op: parallel conv branches concatenated on
    channels (XLA fuses; parity composition)."""
    x = _p(ins, "Input")
    outs = []
    for i, w in enumerate(ins["Filter"]):
        o = get_op("conv2d").fn(
            {"Input": [x], "Filter": [w]},
            {"strides": [1, 1], "paddings": [w.shape[2] // 2,
                                             w.shape[3] // 2]}, ctx)
        y = o["Output"][0]
        if i < len(ins.get("Bias", [])):
            y = y + ins["Bias"][i].reshape(1, -1, 1, 1)
        outs.append(jax.nn.relu(y))
    return {"Output": [jnp.concatenate(outs, axis=1)]}


# ---------------------------------------------------------------------------
# CTR / sparse-table tail
# ---------------------------------------------------------------------------

@register_op("pull_sparse_v2", differentiable=False)
def _pull_sparse_v2(ins, attrs, ctx):
    return get_op("pull_sparse").fn(ins, attrs, ctx)


@register_op("pull_box_extended_sparse", differentiable=False)
def _pull_box_extended_sparse(ins, attrs, ctx):
    """pull_box_extended_sparse_op.cc: base embedding plus an extended
    vector per id — both from the BoxPS table family."""
    outs = get_op("pull_box_sparse").fn(ins, attrs, ctx)
    base = outs["Out"]
    edim = attrs.get("emb_extended_size", 8)
    ext = [jnp.zeros(o.shape[:-1] + (edim,), o.dtype) for o in base]
    return {"Out": base, "OutExtend": ext}


def _table(ins, attrs, dim):
    from .plumbing_ops import _get_table
    return _get_table(attrs["table_name"], dim,
                      attrs.get("optimizer", "sgd"), attrs.get("lr", 1.0))


@register_op("lookup_sparse_table_fuse_sgd", differentiable=False)
def _lookup_sparse_table_fuse_sgd(ins, attrs, ctx):
    from jax.experimental import io_callback
    ids, grads = _p(ins, "Ids"), _p(ins, "Grad")
    lr = attrs.get("lr", 0.01)

    def push(i, g):
        from .plumbing_ops import _get_table
        t = _get_table(attrs["table_name"], int(np.asarray(g).shape[-1]),
                       "sgd", lr)
        t.lr = lr
        t.push(np.asarray(i).reshape(-1),
               np.asarray(g).reshape(len(np.asarray(i).reshape(-1)), -1))
        return np.zeros((), np.int32)

    io_callback(push, jax.ShapeDtypeStruct((), jnp.int32),
                ids.reshape(-1), grads, ordered=True)
    return {}


@register_op("lookup_sparse_table_fuse_adam", differentiable=False)
def _lookup_sparse_table_fuse_adam(ins, attrs, ctx):
    from jax.experimental import io_callback
    ids, grads = _p(ins, "Ids"), _p(ins, "Grad")

    def push(i, g):
        from .plumbing_ops import _get_table
        t = _get_table(attrs["table_name"], int(np.asarray(g).shape[-1]),
                       "adam", attrs.get("lr", 0.001))
        t.push(np.asarray(i).reshape(-1),
               np.asarray(g).reshape(len(np.asarray(i).reshape(-1)), -1))
        return np.zeros((), np.int32)

    io_callback(push, jax.ShapeDtypeStruct((), jnp.int32),
                ids.reshape(-1), grads, ordered=True)
    return {}


@register_op("lookup_sparse_table_merge", differentiable=False)
def _lookup_sparse_table_merge(ins, attrs, ctx):
    """Merge duplicate-id grads (SelectedRows MergeAdd, dense layout)."""
    ids, grads = _p(ins, "Ids").reshape(-1), _p(ins, "Grad")
    uniq, inv = jnp.unique(ids, return_inverse=True,
                           size=ids.shape[0], fill_value=-1)
    merged = jnp.zeros_like(grads).at[inv].add(
        grads.reshape(ids.shape[0], -1))
    return {"Ids": [uniq], "Out": [merged]}


@register_op("lookup_sparse_table_grad_split", differentiable=False)
def _lookup_sparse_table_grad_split(ins, attrs, ctx):
    ids, grads = _p(ins, "Ids").reshape(-1), _p(ins, "Grad")
    n = attrs.get("num", 1)
    outs_i, outs_g = [], []
    for s in range(n):
        mask = (ids % n) == s
        outs_i.append(jnp.where(mask, ids, -1))
        outs_g.append(jnp.where(mask[:, None],
                                grads.reshape(ids.shape[0], -1), 0.0))
    return {"OutIds": outs_i, "OutGrads": outs_g}


@register_op("generate_proposal_labels", differentiable=False,
             stateful_rng=True)
def _generate_proposal_labels(ins, attrs, ctx):
    """generate_proposal_labels_op.cc: sample fg/bg rois against gt boxes
    and emit classification labels + regression targets.  Padded layout:
    exactly batch_size_per_im rois per image (score-ranked rather than
    randomly subsampled — deterministic and XLA-static)."""
    rois = _p(ins, "RpnRois")               # [R, 4]
    gt_boxes = _p(ins, "GtBoxes")           # [G, 4]
    gt_classes = _p(ins, "GtClasses").reshape(-1)
    per_im = attrs.get("batch_size_per_im", 256)
    fg_frac = attrs.get("fg_fraction", 0.25)
    fg_thr = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)

    def iou(a, b):
        area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter,
                                   1e-9)

    all_rois = jnp.concatenate([rois, gt_boxes], axis=0)
    m = iou(all_rois, gt_boxes)             # [R+G, G]
    best = jnp.max(m, axis=1)
    argbest = jnp.argmax(m, axis=1)
    n_fg = int(per_im * fg_frac)
    fg_score = jnp.where(best >= fg_thr, best, -1.0)
    fg_idx = jnp.argsort(-fg_score)[:n_fg]
    bg_score = jnp.where((best < bg_hi) & (best >= bg_lo), best, -1.0)
    bg_idx = jnp.argsort(-bg_score)[: per_im - n_fg]
    keep = jnp.concatenate([fg_idx, bg_idx])
    out_rois = all_rois[keep]
    labels = jnp.where(
        jnp.arange(per_im) < n_fg, gt_classes[argbest[keep]], 0)
    matched = gt_boxes[argbest[keep]]
    w = jnp.maximum(out_rois[:, 2] - out_rois[:, 0], 1e-6)
    h = jnp.maximum(out_rois[:, 3] - out_rois[:, 1], 1e-6)
    gw = jnp.maximum(matched[:, 2] - matched[:, 0], 1e-6)
    gh = jnp.maximum(matched[:, 3] - matched[:, 1], 1e-6)
    tx = ((matched[:, 0] + matched[:, 2]) - (out_rois[:, 0]
                                             + out_rois[:, 2])) / (2 * w)
    ty = ((matched[:, 1] + matched[:, 3]) - (out_rois[:, 1]
                                             + out_rois[:, 3])) / (2 * h)
    targets = jnp.stack([tx, ty, jnp.log(gw / w), jnp.log(gh / h)], axis=1)
    fg_mask = (jnp.arange(per_im) < n_fg)[:, None].astype(jnp.float32)
    return {"Rois": [out_rois], "LabelsInt32": [labels.astype(jnp.int32)],
            "BboxTargets": [targets * fg_mask],
            "BboxInsideWeights": [jnp.broadcast_to(fg_mask, (per_im, 4))],
            "BboxOutsideWeights": [jnp.broadcast_to(fg_mask, (per_im, 4))]}


@register_op("generate_mask_labels", differentiable=False)
def _generate_mask_labels(ins, attrs, ctx):
    """generate_mask_labels_op.cc: rasterise gt masks into per-roi
    resolution x resolution binary targets.  Simplified: gt arrives as
    full-image binary masks [G, H, W]; each fg roi crops + resizes its
    matched gt mask (nearest sampling — mask targets are binary)."""
    rois = _p(ins, "Rois")                  # [N, 4]
    masks = _p(ins, "GtSegms")              # [G, H, W] binary
    labels = _p(ins, "LabelsInt32").reshape(-1)
    match = _p(ins, "MatchIndices").reshape(-1) if ins.get("MatchIndices") \
        else jnp.zeros((rois.shape[0],), jnp.int32)
    res = attrs.get("resolution", 14)
    n = rois.shape[0]
    h, w = masks.shape[1:]

    ys = jnp.linspace(0.0, 1.0, res)
    xs = jnp.linspace(0.0, 1.0, res)

    def one(roi, mi):
        y = jnp.clip((roi[1] + ys * (roi[3] - roi[1])).astype(jnp.int32),
                     0, h - 1)
        x = jnp.clip((roi[0] + xs * (roi[2] - roi[0])).astype(jnp.int32),
                     0, w - 1)
        return masks[mi][y[:, None], x[None, :]]

    out = jax.vmap(one)(rois, jnp.clip(match, 0, masks.shape[0] - 1))
    out = jnp.where((labels > 0)[:, None, None], out, -1)
    return {"MaskRois": [rois], "RoiHasMaskInt32":
            [(labels > 0).astype(jnp.int32)],
            "MaskInt32": [out.reshape(n, -1).astype(jnp.int32)]}
