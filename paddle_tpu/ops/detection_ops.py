"""Detection ops beyond the core set in fluid/layers/detection.py.

Reference (SURVEY §2.5 `detection/` ~18K LoC): operators/detection/
roi_pool_op.cc, psroi_pool_op.cc, prroi_pool_op.cc, anchor_generator_op.cc,
density_prior_box_op.cc, bipartite_match_op.cc, target_assign_op.cc,
rpn_target_assign_op.cc, generate_proposals_op.cc,
distribute_fpn_proposals_op.cc, collect_fpn_proposals_op.cc,
sigmoid_focal_loss_op.cc, retinanet_detection_output_op.cc,
polygon_box_transform_op.cc, deformable_conv_op.cc,
plus operators/affine_grid_op.cc, operators/grid_sampler (grid_generator).

TPU-native notes: proposal/assignment ops that the reference runs as ragged
CPU loops are expressed as static-shape top-k / argmax / segment operations;
"variable number of boxes" becomes a fixed budget + validity mask, the XLA
equivalent of LoD outputs (SURVEY §7 hard part #1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, wide_int

_NEG = -1e30


def _xyxy_wh(boxes):
    w = boxes[..., 2] - boxes[..., 0] + 1.0
    h = boxes[..., 3] - boxes[..., 1] + 1.0
    return w, h


def _iou(a, b):
    """a: [N,4], b: [M,4] -> [N,M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.prod(jnp.clip(a[:, 2:] - a[:, :2], 0, None), -1)
    area_b = jnp.prod(jnp.clip(b[:, 2:] - b[:, :2], 0, None), -1)
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-10)


# --- RoI pooling family ------------------------------------------------------
def _roi_bins(x, rois, ph, pw, spatial_scale, reduce="max"):
    """Shared RoI binning: x [C,H,W] one image, rois [R,4] xyxy."""
    c, h, w = x.shape
    r = rois.shape[0]
    x1 = jnp.round(rois[:, 0] * spatial_scale)
    y1 = jnp.round(rois[:, 1] * spatial_scale)
    x2 = jnp.round(rois[:, 2] * spatial_scale)
    y2 = jnp.round(rois[:, 3] * spatial_scale)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)
    out = []
    for i in range(ph):
        for j in range(pw):
            y_lo = y1 + bin_h * i
            y_hi = y1 + bin_h * (i + 1)
            x_lo = x1 + bin_w * j
            x_hi = x1 + bin_w * (j + 1)
            my = ((ys[None, :] >= jnp.floor(y_lo)[:, None])
                  & (ys[None, :] < jnp.ceil(y_hi)[:, None]))   # [R, H]
            mx = ((xs[None, :] >= jnp.floor(x_lo)[:, None])
                  & (xs[None, :] < jnp.ceil(x_hi)[:, None]))   # [R, W]
            m = (my[:, None, :, None] & mx[:, None, None, :])  # [R,1,H,W]
            if reduce == "max":
                v = jnp.where(m, x[None], _NEG).max(axis=(2, 3))
                v = jnp.where(jnp.isfinite(v) & (v > _NEG / 2), v, 0.0)
            else:
                cnt = jnp.maximum(m.sum(axis=(2, 3)), 1.0)
                v = jnp.where(m, x[None], 0.0).sum(axis=(2, 3)) / cnt
            out.append(v)                                      # [R, C]
    return jnp.stack(out, -1).reshape(r, c, ph, pw)


@register_op("roi_pool", nondiff_inputs=("ROIs", "RoisNum"))
def _roi_pool(ins, attrs, ctx):
    """roi_pool_op.cc: max pool per RoI bin.  Single-image batch layout (the
    RoIs' batch index is taken as 0 — trainers feed per-image)."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    out = _roi_bins(x[0], rois, ph, pw, scale, "max")
    return {"Out": [out], "Argmax": [jnp.zeros(out.shape, wide_int())]}


@register_op("psroi_pool", nondiff_inputs=("ROIs",))
def _psroi_pool(ins, attrs, ctx):
    """psroi_pool_op.cc: position-sensitive RoI average pooling — input
    channels C = out_c * ph * pw; bin (i,j) reads its own channel group."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    oc = attrs.get("output_channels", x.shape[1] // (ph * pw))
    scale = attrs.get("spatial_scale", 1.0)
    full = _roi_bins(x[0], rois, ph, pw, scale, "avg")  # [R, C, ph, pw]
    r = full.shape[0]
    grouped = full.reshape(r, oc, ph, pw, ph, pw)
    idx = jnp.arange(ph)
    jdx = jnp.arange(pw)
    out = grouped[:, :, idx[:, None], jdx[None, :], idx[:, None], jdx[None, :]]
    return {"Out": [out.reshape(r, oc, ph, pw)]}


@register_op("prroi_pool", nondiff_inputs=("ROIs",))
def _prroi_pool(ins, attrs, ctx):
    """prroi_pool_op.cc (precise RoI pooling): continuous integral average —
    approximated with the same average binning (exact for aligned bins)."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    return {"Out": [_roi_bins(x[0], rois, ph, pw, scale, "avg")]}


# --- anchors / priors --------------------------------------------------------
@register_op("anchor_generator", differentiable=False)
def _anchor_generator(ins, attrs, ctx):
    """anchor_generator_op.cc: dense anchors over the feature map grid."""
    x = ins["Input"][0]
    sizes = attrs.get("anchor_sizes", [64.0, 128.0, 256.0, 512.0])
    ratios = attrs.get("aspect_ratios", [0.5, 1.0, 2.0])
    stride = attrs.get("stride", [16.0, 16.0])
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = x.shape[-2], x.shape[-1]
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * (r ** 0.5)
            ah = s / (r ** 0.5)
            anchors.append([-aw / 2, -ah / 2, aw / 2, ah / 2])
    base = jnp.asarray(anchors)                     # [A, 4]
    grid = jnp.stack(jnp.meshgrid(cx, cy), -1)      # [H, W, 2]
    shift = jnp.concatenate([grid, grid], -1)       # [H, W, 4]
    out = shift[:, :, None, :] + base[None, None]
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Anchors": [out], "Variances": [var]}


@register_op("density_prior_box", differentiable=False)
def _density_prior_box(ins, attrs, ctx):
    """density_prior_box_op.cc: SSD priors with per-size densities — each
    fixed_size spawns density^2 shifted boxes per cell."""
    x = ins["Input"][0]
    img = ins["Image"][0]
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [1.0])
    densities = attrs.get("densities", [1] * len(fixed_sizes))
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = x.shape[-2], x.shape[-1]
    ih, iw = img.shape[-2], img.shape[-1]
    sw = step_w or iw / w
    sh = step_h or ih / h
    boxes = []
    for size, dens in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            shift = size / dens
            for di in range(dens):
                for dj in range(dens):
                    ox = (dj + 0.5) * shift - size / 2
                    oy = (di + 0.5) * shift - size / 2
                    boxes.append((ox, oy, bw, bh))
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    gx, gy = jnp.meshgrid(cx, cy)                  # [H, W]
    prior = []
    for ox, oy, bw, bh in boxes:
        b = jnp.stack([(gx + ox - bw / 2) / iw, (gy + oy - bh / 2) / ih,
                       (gx + ox + bw / 2) / iw, (gy + oy + bh / 2) / ih], -1)
        prior.append(b)
    out = jnp.stack(prior, 2)                      # [H, W, P, 4]
    out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Boxes": [out], "Variances": [var]}


# --- matching / assignment ---------------------------------------------------
@register_op("bipartite_match", differentiable=False)
def _bipartite_match(ins, attrs, ctx):
    """bipartite_match_op.cc: greedy argmax matching of columns (priors) to
    rows (gt) on the DistMat, then per_prediction fill for unmatched."""
    dist = ins["DistMat"][0]
    if dist.ndim == 2:
        dist = dist[None]
    typ = attrs.get("match_type", "bipartite")
    thr = attrs.get("dist_threshold", 0.5)
    b, n, m = dist.shape

    def one(d):
        match = jnp.full((m,), -1, jnp.int32)
        md = jnp.zeros((m,), d.dtype)

        def body(i, carry):
            match, md, dd = carry
            flat = jnp.argmax(dd)
            r, c = flat // m, flat % m
            ok = dd[r, c] > 0
            match = jnp.where(ok, match.at[c].set(r.astype(jnp.int32)),
                              match)
            md = jnp.where(ok, md.at[c].set(dd[r, c]), md)
            dd = jnp.where(ok, dd.at[r, :].set(-1.0).at[:, c].set(-1.0), dd)
            return match, md, dd
        match, md, _ = jax.lax.fori_loop(0, min(n, m), body,
                                         (match, md, d))
        if typ == "per_prediction":
            col_best = jnp.argmax(d, axis=0).astype(jnp.int32)
            col_val = jnp.max(d, axis=0)
            fill = (match < 0) & (col_val >= thr)
            match = jnp.where(fill, col_best, match)
            md = jnp.where(fill, col_val, md)
        return match, md
    matches, dists = jax.vmap(one)(dist)
    return {"ColToRowMatchIndices": [matches],
            "ColToRowMatchDist": [dists]}


@register_op("target_assign", nondiff_inputs=("MatchIndices", "NegIndices"),
             differentiable=False)
def _target_assign(ins, attrs, ctx):
    """target_assign_op.cc: gather per-prior targets by match index; weight 1
    where matched (or negative), 0 elsewhere."""
    x = ins["X"][0]                        # [B, N, K] gt attributes
    match = ins["MatchIndices"][0].astype(jnp.int32)   # [B, M]
    mismatch_value = attrs.get("mismatch_value", 0.0)
    safe = jnp.maximum(match, 0)
    out = jnp.take_along_axis(x, safe[..., None], axis=1)
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, out, mismatch_value)
    w = matched.astype(x.dtype)
    return {"Out": [out], "OutWeight": [w]}


@register_op("rpn_target_assign", differentiable=False, stateful_rng=True)
def _rpn_target_assign(ins, attrs, ctx):
    """rpn_target_assign_op.cc: label anchors pos/neg by IoU vs gt, sample a
    fixed budget.  Static-shape: returns per-anchor labels/weights instead of
    compacted index lists (the LoD-free equivalent)."""
    anchor = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0].reshape(-1, 4)
    pos_thr = attrs.get("rpn_positive_overlap", 0.7)
    neg_thr = attrs.get("rpn_negative_overlap", 0.3)
    iou = _iou(anchor, gt)                  # [A, G]
    best = iou.max(axis=1)
    argbest = iou.argmax(axis=1)
    label = jnp.where(best >= pos_thr, 1, jnp.where(best < neg_thr, 0, -1))
    # anchors that are the best for some gt are positive too
    best_per_gt = iou.argmax(axis=0)
    label = label.at[best_per_gt].set(1)
    tgt = gt[argbest]
    return {"LocationIndex": [jnp.where(label == 1, 1, 0).astype(jnp.int32)],
            "ScoreIndex": [jnp.where(label >= 0, 1, 0).astype(jnp.int32)],
            "TargetLabel": [label.astype(jnp.int32)],
            "TargetBBox": [tgt],
            "BBoxInsideWeight": [(label == 1).astype(anchor.dtype)[:, None]
                                 * jnp.ones((1, 4), anchor.dtype)]}


@register_op("generate_proposals", differentiable=False)
def _generate_proposals(ins, attrs, ctx):
    """generate_proposals_op.cc: decode anchor deltas, clip, take top
    post_nms_topN by score with IoU suppression (static-budget NMS)."""
    scores = ins["Scores"][0]               # [B, A, H, W]
    deltas = ins["BboxDeltas"][0]           # [B, A*4, H, W]
    anchors = ins["Anchors"][0].reshape(-1, 4)
    im_info = ins["ImInfo"][0] if ins.get("ImInfo") else None
    pre_n = attrs.get("pre_nms_topN", 6000)
    post_n = attrs.get("post_nms_topN", 1000)
    nms_thr = attrs.get("nms_thresh", 0.7)
    b = scores.shape[0]
    sc = scores.reshape(b, -1)
    dl = deltas.reshape(b, -1, 4, deltas.shape[-2], deltas.shape[-1])
    dl = jnp.moveaxis(dl, 2, -1).reshape(b, -1, 4)
    aw, ah = _xyxy_wh(anchors)
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    n = min(pre_n, sc.shape[1])

    def one(s, d):
        top_s, top_i = jax.lax.top_k(s, n)
        dd = d[top_i]
        cx = acx[top_i] + dd[:, 0] * aw[top_i]
        cy = acy[top_i] + dd[:, 1] * ah[top_i]
        w = aw[top_i] * jnp.exp(jnp.clip(dd[:, 2], None, 4.0))
        h = ah[top_i] * jnp.exp(jnp.clip(dd[:, 3], None, 4.0))
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)
        if im_info is not None:
            boxes = jnp.clip(boxes, 0.0, None)
        iou = _iou(boxes, boxes)
        keep_n = min(post_n, n)

        def nms_body(i, carry):
            keep, sup = carry
            avail = jnp.where(sup, _NEG, top_s)
            j = jnp.argmax(avail)
            keep = keep.at[i].set(j)
            sup = sup | (iou[j] > nms_thr)
            sup = sup.at[j].set(True)
            return keep, sup
        keep, _ = jax.lax.fori_loop(
            0, keep_n, nms_body,
            (jnp.zeros((keep_n,), jnp.int32),
             jnp.zeros((n,), bool)))
        return boxes[keep], top_s[keep]
    boxes, probs = jax.vmap(one)(sc, dl)
    return {"RpnRois": [boxes], "RpnRoiProbs": [probs[..., None]],
            "RpnRoisNum": [jnp.full((b,), boxes.shape[1], jnp.int32)]}


@register_op("distribute_fpn_proposals", differentiable=False)
def _distribute_fpn_proposals(ins, attrs, ctx):
    """distribute_fpn_proposals_op.cc: route each RoI to its FPN level by
    scale.  Static-shape: per-level copies with a validity mask (rows not on
    that level are zeroed), plus RestoreIndex."""
    rois = ins["FpnRois"][0]
    min_level = attrs.get("min_level", 2)
    max_level = attrs.get("max_level", 5)
    refer_level = attrs.get("refer_level", 4)
    refer_scale = attrs.get("refer_scale", 224)
    w, h = _xyxy_wh(rois)
    scale = jnp.sqrt(jnp.clip(w * h, 1e-6, None))
    lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-6))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs = []
    for l in range(min_level, max_level + 1):
        m = (lvl == l).astype(rois.dtype)[:, None]
        outs.append(rois * m)
    return {"MultiFpnRois": outs,
            "RestoreIndex": [jnp.argsort(
                jnp.argsort(lvl, stable=True), stable=True)[:, None]
                .astype(jnp.int32)],
            "MultiLevelRoIsNum": [jnp.stack(
                [(lvl == l).sum() for l in range(min_level, max_level + 1)])
                .astype(jnp.int32)]}


@register_op("collect_fpn_proposals", differentiable=False)
def _collect_fpn_proposals(ins, attrs, ctx):
    """collect_fpn_proposals_op.cc: merge per-level RoIs, keep global top-N
    by score."""
    rois = jnp.concatenate(ins["MultiLevelRois"], axis=0)
    scores = jnp.concatenate([s.reshape(-1)
                              for s in ins["MultiLevelScores"]], axis=0)
    n = min(attrs.get("post_nms_topN", 1000), scores.shape[0])
    top_s, top_i = jax.lax.top_k(scores, n)
    return {"FpnRois": [rois[top_i]],
            "RoisNum": [jnp.asarray([n], jnp.int32)]}


# --- losses / outputs --------------------------------------------------------
@register_op("sigmoid_focal_loss", nondiff_inputs=("Label", "FgNum"))
def _sigmoid_focal_loss(ins, attrs, ctx):
    """sigmoid_focal_loss_op.cc: FL(p) = -alpha (1-p)^gamma log(p) with
    per-class one-vs-all labels (label c in [0, C]; 0 = background)."""
    x = ins["X"][0]                         # [N, C]
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)
    fg = (ins["FgNum"][0].reshape(()).astype(x.dtype)
          if ins.get("FgNum") else jnp.asarray(1.0, x.dtype))
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    c = x.shape[1]
    target = (label[:, None] == (jnp.arange(c) + 1)[None]).astype(x.dtype)
    # label -1 rows are ignored entirely (sigmoid_focal_loss_op.h:53
    # c_neg gates on g != -1)
    valid = (label[:, None] != -1).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    pt = jnp.where(target > 0, p, 1 - p)
    at = jnp.where(target > 0, alpha, 1 - alpha)
    bce = -jnp.where(target > 0, jax.nn.log_sigmoid(x),
                     jax.nn.log_sigmoid(-x))
    loss = valid * at * ((1 - pt) ** gamma) * bce / jnp.maximum(fg, 1.0)
    return {"Out": [loss]}


@register_op("retinanet_detection_output", differentiable=False)
def _retinanet_detection_output(ins, attrs, ctx):
    """retinanet_detection_output_op.cc: decode per-level cls+loc, global
    top-k with score threshold (NMS delegated to multiclass_nms budget)."""
    bboxes = jnp.concatenate([b.reshape(b.shape[0], -1, 4)
                              for b in ins["BBoxes"]], axis=1)
    scores = jnp.concatenate([s.reshape(s.shape[0], -1, s.shape[-1])
                              for s in ins["Scores"]], axis=1)
    thr = attrs.get("score_threshold", 0.05)
    keep_k = attrs.get("keep_top_k", 100)
    b = scores.shape[0]
    best_s = scores.max(-1)
    best_c = scores.argmax(-1)
    k = min(keep_k, best_s.shape[1])
    top_s, top_i = jax.lax.top_k(jnp.where(best_s > thr, best_s, _NEG), k)
    out = []
    for bi in range(b):
        cls = best_c[bi][top_i[bi]].astype(bboxes.dtype)
        box = bboxes[bi][top_i[bi]]
        out.append(jnp.concatenate(
            [cls[:, None], top_s[bi][:, None], box], axis=1))
    return {"Out": [jnp.stack(out)]}


@register_op("polygon_box_transform", differentiable=False)
def _polygon_box_transform(ins, attrs, ctx):
    """polygon_box_transform_op.cc:40-48 (EAST text detection): offsets to
    absolute quad coords, out = 4*x_grid - in on even planes, 4*y_grid - in
    on odd — plane parity is (batch*C + channel) % 2 exactly as the
    reference's flat id_n loop computes it."""
    x = ins["Input"][0]                     # [B, 8or9, H, W]
    b, c, h, w = x.shape
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    plane = (jnp.arange(b)[:, None] * c + jnp.arange(c)[None, :])
    even = (plane % 2 == 0)[:, :, None, None]
    grid = jnp.where(even, gx, gy)
    return {"Output": [grid - x]}


# --- deformable conv / grids -------------------------------------------------
@register_op("deformable_conv", nondiff_inputs=("Offset", "Mask"))
def _deformable_conv(ins, attrs, ctx):
    """deformable_conv_op.cc (v2 with modulation Mask): bilinear-sample the
    input at offset positions per kernel tap, then a plain conv contraction.
    Implemented as gather+matmul — the XLA-friendly formulation."""
    x = ins["Input"][0]                     # [B, C, H, W]
    offset = ins["Offset"][0]               # [B, 2*kh*kw*dg, H, W]
    w = ins["Filter"][0]                    # [O, C/g, kh, kw]
    mask = ins["Mask"][0] if ins.get("Mask") else None
    stride = attrs.get("strides", [1, 1])
    pad = attrs.get("paddings", [0, 0])
    b, c, h, wd = x.shape
    o, cg, kh, kw = w.shape
    oh = (h + 2 * pad[0] - kh) // stride[0] + 1
    ow = (wd + 2 * pad[1] - kw) // stride[1] + 1
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    ph, pw = xp.shape[2], xp.shape[3]
    oy = jnp.arange(oh) * stride[0]
    ox = jnp.arange(ow) * stride[1]
    cols = []
    for ki in range(kh):
        for kj in range(kw):
            t = ki * kw + kj
            dy = offset[:, 2 * t][:, :oh, :ow]
            dx = offset[:, 2 * t + 1][:, :oh, :ow]
            yy = oy[None, :, None] + ki + dy
            xx = ox[None, None, :] + kj + dx
            y0 = jnp.clip(jnp.floor(yy), 0, ph - 2).astype(jnp.int32)
            x0 = jnp.clip(jnp.floor(xx), 0, pw - 2).astype(jnp.int32)
            fy = jnp.clip(yy - y0, 0.0, 1.0)
            fx = jnp.clip(xx - x0, 0.0, 1.0)

            def gat(yi, xi):
                return jax.vmap(
                    lambda img, ys, xs: img[:, ys, xs])(xp, yi, xi)
            v = (gat(y0, x0) * ((1 - fy) * (1 - fx))[:, None]
                 + gat(y0, x0 + 1) * ((1 - fy) * fx)[:, None]
                 + gat(y0 + 1, x0) * (fy * (1 - fx))[:, None]
                 + gat(y0 + 1, x0 + 1) * (fy * fx)[:, None])
            if mask is not None:
                v = v * mask[:, t][:, None, :oh, :ow]
            cols.append(v)                  # [B, C, oh, ow]
    col = jnp.stack(cols, 2)                # [B, C, kh*kw, oh, ow]
    out = jnp.einsum("bckhw,ock->bohw", col,
                     w.reshape(o, cg, kh * kw),
                     preferred_element_type=jnp.float32)
    return {"Output": [out.astype(x.dtype)]}


@register_op("deformable_conv_v1", nondiff_inputs=("Offset",))
def _deformable_conv_v1(ins, attrs, ctx):
    ins = dict(ins)
    ins.pop("Mask", None)
    return _deformable_conv(ins, attrs, ctx)


@register_op("affine_grid")
def _affine_grid(ins, attrs, ctx):
    """affine_grid_op.cc: theta [B, 2, 3] -> sampling grid [B, H, W, 2] in
    [-1, 1] coords (align_corners semantics of the reference)."""
    theta = ins["Theta"][0]
    shape = attrs.get("output_shape", None)
    if shape is None and ins.get("OutputShape"):
        import numpy as np
        shape = [int(v) for v in np.asarray(ins["OutputShape"][0])]
    b, _, h, w = shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], -1)            # [H, W, 3]
    grid = jnp.einsum("hwk,bak->bhwa", base, theta)
    return {"Output": [grid]}
