"""Random-number ops over JAX's counter-based PRNG.

Reference: paddle/fluid/operators/{uniform_random_op,gaussian_random_op,
truncated_gaussian_random_op,randint_op,randperm_op,multinomial_op,
bernoulli_op,...}.cc (SURVEY A.1 Random).  The reference threads a mutable
Generator (framework/generator.cc); TPU-native randomness is functional: each
op instance is assigned a static `op_seed` at graph-build time and derives its
key as fold_in(step_key, op_seed) — reproducible, and identical between a
forward pass and its vjp-recomputation (registry.LoweringContext.key_for).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, wide_int


def _dtype(attrs, default="float32"):
    from ..fluid.framework import device_dtype
    d = attrs.get("dtype", default)
    return device_dtype(d) if d not in (None, -1) else default


def _shape(ins, attrs):
    if ins.get("ShapeTensor"):
        return tuple(int(d) for d in np.asarray(ins["ShapeTensor"][0]))
    return tuple(attrs["shape"])


@register_op("uniform_random", stateful_rng=True, differentiable=False)
def _uniform_random(ins, attrs, ctx):
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    out = jax.random.uniform(key, _shape(ins, attrs), dtype=jnp.float32,
                             minval=attrs.get("min", -1.0),
                             maxval=attrs.get("max", 1.0))
    return {"Out": [out.astype(_dtype(attrs))]}


@register_op("gaussian_random", stateful_rng=True, differentiable=False)
def _gaussian_random(ins, attrs, ctx):
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    out = (jax.random.normal(key, _shape(ins, attrs), dtype=jnp.float32)
           * attrs.get("std", 1.0) + attrs.get("mean", 0.0))
    return {"Out": [out.astype(_dtype(attrs))]}


@register_op("truncated_gaussian_random", stateful_rng=True, differentiable=False)
def _truncated_gaussian(ins, attrs, ctx):
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    out = jax.random.truncated_normal(key, -2.0, 2.0, tuple(attrs["shape"]),
                                      dtype=jnp.float32)
    out = out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": [out.astype(_dtype(attrs))]}


@register_op("randint", stateful_rng=True, differentiable=False)
def _randint(ins, attrs, ctx):
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    out = jax.random.randint(key, _shape(ins, attrs), attrs.get("low", 0),
                             attrs.get("high"), dtype=jnp.int32)
    return {"Out": [out.astype(_dtype(attrs, "int64"))]}


@register_op("randperm", stateful_rng=True, differentiable=False)
def _randperm(ins, attrs, ctx):
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    return {"Out": [jax.random.permutation(key, attrs["n"]).astype(
        _dtype(attrs, "int64"))]}


@register_op("bernoulli", stateful_rng=True, differentiable=False)
def _bernoulli(ins, attrs, ctx):
    x = ins["X"][0]
    key = ctx.key_for(attrs.get("op_seed", 0))
    return {"Out": [jax.random.bernoulli(key, x).astype(x.dtype)]}


@register_op("multinomial", stateful_rng=True, differentiable=False)
def _multinomial(ins, attrs, ctx):
    x = ins["X"][0]
    key = ctx.key_for(attrs.get("op_seed", 0))
    n = attrs.get("num_samples", 1)
    logits = jnp.log(jnp.clip(x, 1e-30))
    if attrs.get("replacement", False):
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=x.shape[:-1] + (n,))
    else:
        # without replacement: Gumbel-top-k — argtop of logits + gumbel
        # noise samples k distinct categories with the right law
        g = jax.random.gumbel(key, logits.shape, logits.dtype)
        _, out = jax.lax.top_k(logits + g, n)
    return {"Out": [out.astype(wide_int())]}


@register_op("sampling_id", stateful_rng=True, differentiable=False)
def _sampling_id(ins, attrs, ctx):
    x = ins["X"][0]
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    out = jax.random.categorical(key, jnp.log(jnp.clip(x, 1e-30)), axis=-1)
    return {"Out": [out.astype(wide_int())]}


@register_op("shuffle_batch", stateful_rng=True, nondiff_outputs=("ShuffleIdx",))
def _shuffle_batch(ins, attrs, ctx):
    # qingshui CTR op (operators/shuffle_batch_op.cc): permute rows
    x = ins["X"][0]
    key = ctx.key_for(attrs.get("op_seed", attrs.get("startup_seed", 0) or 0))
    idx = jax.random.permutation(key, x.shape[0])
    return {"Out": [jnp.take(x, idx, axis=0)],
            "ShuffleIdx": [idx.astype(wide_int())],
            "SeedOut": [jnp.zeros((1,), wide_int())]}


@register_op("random_crop", stateful_rng=True, differentiable=False)
def _random_crop(ins, attrs, ctx):
    x = ins["X"][0]
    shape = attrs["shape"]
    key = ctx.key_for(attrs.get("op_seed", 0))
    starts = [jax.random.randint(jax.random.fold_in(key, i), (), 0,
                                 x.shape[x.ndim - len(shape) + i] - s + 1)
              for i, s in enumerate(shape)]
    full = [0] * (x.ndim - len(shape)) + [int(s) for s in starts]
    sizes = list(x.shape[:x.ndim - len(shape)]) + list(shape)
    return {"Out": [jax.lax.dynamic_slice(x, full, sizes)]}


@register_op("seed", differentiable=False)
def _seed(ins, attrs, ctx):
    return {"Out": [jnp.asarray([attrs.get("seed", 0)], jnp.int32)]}
