"""NN-tail + fused-family op catalog: compositions XLA fuses on its own.

Reference files (SURVEY A.1): add_position_encoding_op.cc, crop_op.cc,
crop_tensor_op.cc, expand_as_op.cc, histogram_op.cc, unpool_op.cc,
segment_pool_op.cc, similarity_focus_op.cc, lstm_unit_op.cc,
reduce_ops/frobenius_norm_op.cc, fsp_op.cc, inplace_abn_op.cc,
interpolate_op.cc (+_v2), correlation_op.cc, conv_shift_op.cc covered in
misc; fused/: fused_bn_activation, fused_bn_add_activation,
fused_embedding_seq_pool, fused_fc_elementwise_layernorm, fusion_gru,
fusion_lstm, fusion_repeated_fc_relu, fusion_seqconv_eltadd_relu,
fusion_seqexpand_concat_fc, fusion_seqpool_concat, fusion_seqpool_cvm_concat,
fusion_squared_mat_sub, fusion_transpose_flatten_concat, skip_layernorm,
conv_fusion, fused_embedding_fc_lstm, multi_gru, fused_seqpool_cvm_with_pcoc;
scaled_int8fc_op.cc (qingshui), collective/c_mixallgather_op.cc.

TPU-native: each "fused" op is the straightforward composition of its
parts — XLA's fusion pass produces the same fused kernel the hand-written
CUDA did, so these exist for op-level API parity, not performance.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, get_op, wide_int


def _p(ins, slot):
    return ins[slot][0]


def _act(name, x):
    if not name or name == "identity":
        return x
    return {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
            "tanh": jnp.tanh, "gelu": jax.nn.gelu,
            "swish": jax.nn.silu, "leaky_relu": jax.nn.leaky_relu}[name](x)


# ---------------------------------------------------------------------------
# nn tail
# ---------------------------------------------------------------------------

@register_op("add_position_encoding")
def _add_position_encoding(ins, attrs, ctx):
    """add_position_encoding_op.cc: x*alpha + beta*sinusoid PE."""
    x = _p(ins, "X")                       # [B, T, D]
    alpha, beta = attrs.get("alpha", 1.0), attrs.get("beta", 1.0)
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    freq = pos / jnp.power(10000.0, 2.0 * i / d)
    pe = jnp.concatenate([jnp.sin(freq), jnp.cos(freq)], axis=1)
    return {"Out": [alpha * x + beta * pe[None].astype(x.dtype)]}


def _crop_common(x, offsets, shape):
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[slices]


@register_op("crop", nondiff_inputs=("Offsets", "Y"))
def _crop(ins, attrs, ctx):
    x = _p(ins, "X")
    shape = (list(np.shape(ins["Y"][0])) if ins.get("Y")
             else attrs.get("shape"))
    offsets = (list(np.asarray(ins["Offsets"][0]).reshape(-1))
               if ins.get("Offsets") else attrs.get("offsets",
                                                    [0] * x.ndim))
    return {"Out": [_crop_common(x, [int(o) for o in offsets],
                                 [int(s) for s in shape])]}


@register_op("crop_tensor", nondiff_inputs=("Shape", "Offsets"))
def _crop_tensor(ins, attrs, ctx):
    x = _p(ins, "X")
    shape = (list(np.asarray(ins["Shape"][0]).reshape(-1))
             if ins.get("Shape") else attrs.get("shape"))
    offsets = (list(np.asarray(ins["Offsets"][0]).reshape(-1))
               if ins.get("Offsets") else attrs.get("offsets",
                                                    [0] * x.ndim))
    shape = [x.shape[i] if int(s) == -1 else int(s)
             for i, s in enumerate(shape)]
    return {"Out": [_crop_common(x, [int(o) for o in offsets], shape)]}


@register_op("expand_as", nondiff_inputs=("target_tensor",))
def _expand_as(ins, attrs, ctx):
    x = _p(ins, "X")
    target = ins.get("target_tensor") or ins.get("Y")
    shape = np.shape(target[0])
    reps = [int(t // s) for t, s in zip(shape, x.shape)]
    return {"Out": [jnp.tile(x, reps)]}


@register_op("histogram", differentiable=False)
def _histogram(ins, attrs, ctx):
    x = _p(ins, "X").reshape(-1).astype(jnp.float32)
    bins = attrs.get("bins", 100)
    lo, hi = attrs.get("min", 0), attrs.get("max", 0)
    if lo == 0 and hi == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    hist = jnp.histogram(x, bins=bins, range=(lo, hi))[0]
    return {"Out": [hist.astype(wide_int())]}


@register_op("unpool", nondiff_inputs=("Indices",))
def _unpool(ins, attrs, ctx):
    """unpool_op.cc (max-unpooling): scatter pooled values back to the
    argmax positions."""
    x, idx = _p(ins, "X"), _p(ins, "Indices")
    n, c, h, w = x.shape
    oh, ow = attrs.get("unpooled_height", h * 2), attrs.get(
        "unpooled_width", w * 2)
    flat_idx = idx.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    out = jnp.zeros((n, c, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, i, v: o.at[i].set(v)))(out, flat_idx, vals)
    return {"Out": [out.reshape(n, c, oh, ow)]}


@register_op("segment_pool", nondiff_inputs=("SegmentIds",))
def _segment_pool(ins, attrs, ctx):
    x, seg = _p(ins, "X"), _p(ins, "SegmentIds").reshape(-1)
    n_seg = int(np.asarray(seg).max()) + 1 if not isinstance(
        seg, jax.core.Tracer) else attrs.get("num_segments",
                                             int(x.shape[0]))
    pool = attrs.get("pooltype", "SUM").upper()
    if pool == "SUM":
        out = jax.ops.segment_sum(x, seg, num_segments=n_seg)
    elif pool == "MEAN":
        s = jax.ops.segment_sum(x, seg, num_segments=n_seg)
        cnt = jax.ops.segment_sum(jnp.ones_like(x[:, :1]), seg,
                                  num_segments=n_seg)
        out = s / jnp.maximum(cnt, 1)
    elif pool == "MAX":
        out = jax.ops.segment_max(x, seg, num_segments=n_seg)
    else:
        out = jax.ops.segment_min(x, seg, num_segments=n_seg)
    return {"Out": [out]}


@register_op("similarity_focus", differentiable=False)
def _similarity_focus(ins, attrs, ctx):
    """similarity_focus_op.cc: per (axis,index) slice, mark max positions
    across channels with 1."""
    x = _p(ins, "X")                # [B, C, A, B2]
    axis = attrs.get("axis", 1)
    indexes = attrs.get("indexes", [0])
    out = jnp.zeros_like(x)
    for idx in indexes:
        sl = jnp.take(x, idx, axis=axis)          # [B, A, B2] for axis=1
        rows = jnp.max(sl, axis=-1, keepdims=True) == sl
        cols = jnp.max(sl, axis=-2, keepdims=True) == sl
        mask = (rows | cols).astype(x.dtype)      # [B, A, B2]
        out = out + jnp.expand_dims(mask, axis)
    return {"Out": [jnp.clip(out, 0.0, 1.0)]}


@register_op("lstm_unit")
def _lstm_unit(ins, attrs, ctx):
    """lstm_unit_op.h:62-73: one cell step from pre-activations; gate
    layout along the 4H axis is i, f, o, g — candidate LAST."""
    x, c_prev = _p(ins, "X"), _p(ins, "C_prev")
    forget_bias = attrs.get("forget_bias", 0.0)
    i, f, o, g = jnp.split(x, 4, axis=1)
    new_c = (c_prev * jax.nn.sigmoid(f + forget_bias)
             + jax.nn.sigmoid(i) * jnp.tanh(g))
    new_h = jnp.tanh(new_c) * jax.nn.sigmoid(o)
    return {"C": [new_c], "H": [new_h]}


@register_op("frobenius_norm")
def _frobenius_norm(ins, attrs, ctx):
    x = _p(ins, "X")
    dims = attrs.get("dim", list(range(x.ndim)))
    keep = attrs.get("keep_dim", False)
    out = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32)),
                           axis=tuple(dims), keepdims=keep))
    return {"Out": [out.astype(x.dtype)]}


@register_op("fsp")
def _fsp(ins, attrs, ctx):
    """fsp_op.cc (flow of solution procedure): Gram matrix between two
    feature maps, normalised by spatial size."""
    x, y = _p(ins, "X"), _p(ins, "Y")
    b, cx, h, w = x.shape
    cy = y.shape[1]
    xf = x.reshape(b, cx, h * w).astype(jnp.float32)
    yf = y.reshape(b, cy, h * w).astype(jnp.float32)
    out = jnp.einsum("bxs,bys->bxy", xf, yf) / (h * w)
    return {"Out": [out.astype(x.dtype)]}


@register_op("inplace_abn")
def _inplace_abn(ins, attrs, ctx):
    """inplace_abn_op.cc = batch_norm + activation, fused in-place on GPU;
    here: compose and let XLA fuse."""
    outs = get_op("batch_norm").fn(ins, attrs, ctx)
    y = outs["Y"][0]
    outs["Y"] = [_act(attrs.get("activation", ""), y)]
    return outs


def _interp_dispatch(ins, attrs, ctx):
    method = attrs.get("interp_method", "bilinear")
    target = {"bilinear": "bilinear_interp", "nearest": "nearest_interp",
              "trilinear": "trilinear_interp", "bicubic": "bicubic_interp",
              "linear": "linear_interp"}.get(method)
    from .registry import has_op
    if target is not None:
        for cand in (target + "_v2", target):
            if has_op(cand):
                return get_op(cand).fn(ins, attrs, ctx)
    raise NotImplementedError(f"interpolate method {method}")


@register_op("interpolate", nondiff_inputs=("OutSize", "SizeTensor", "Scale"))
def _interpolate(ins, attrs, ctx):
    return _interp_dispatch(ins, attrs, ctx)


@register_op("interpolate_v2", nondiff_inputs=("OutSize", "SizeTensor",
                                               "Scale"))
def _interpolate_v2(ins, attrs, ctx):
    return _interp_dispatch(ins, attrs, ctx)


@register_op("correlation")
def _correlation(ins, attrs, ctx):
    """correlation_op.cc (FlowNet): dot-product patch correlation between
    two feature maps over a displacement window."""
    a, b = _p(ins, "Input1"), _p(ins, "Input2")
    max_disp = attrs.get("max_displacement", 1)
    stride2 = attrs.get("stride2", 1)
    n, c, h, w = a.shape
    disp = list(range(-max_disp, max_disp + 1, stride2))
    outs = []
    for dy in disp:
        for dx in disp:
            shifted = jnp.roll(b, (dy, dx), axis=(2, 3))
            outs.append(jnp.mean(a * shifted, axis=1))
    return {"Output": [jnp.stack(outs, axis=1)]}


# ---------------------------------------------------------------------------
# fused family — compositions
# ---------------------------------------------------------------------------

@register_op("fused_bn_activation",
             nondiff_inputs=("Mean", "Variance"),
             nondiff_outputs=("MeanOut", "VarianceOut", "SavedMean",
                              "SavedVariance"))
def _fused_bn_activation(ins, attrs, ctx):
    outs = get_op("batch_norm").fn(ins, attrs, ctx)
    outs["Y"] = [_act(attrs.get("act_type", "relu"), outs["Y"][0])]
    return outs


@register_op("fused_bn_add_activation",
             nondiff_inputs=("Mean", "Variance"),
             nondiff_outputs=("MeanOut", "VarianceOut", "SavedMean",
                              "SavedVariance"))
def _fused_bn_add_activation(ins, attrs, ctx):
    z = _p(ins, "Z")
    outs = get_op("batch_norm").fn(
        {k: v for k, v in ins.items() if k != "Z"}, attrs, ctx)
    outs["Y"] = [_act(attrs.get("act_type", "relu"), outs["Y"][0] + z)]
    return outs


@register_op("fused_embedding_seq_pool", nondiff_inputs=("Ids",))
def _fused_embedding_seq_pool(ins, attrs, ctx):
    w, ids = _p(ins, "W"), _p(ins, "Ids")
    emb = jnp.take(w, ids.reshape(ids.shape[0], -1), axis=0)  # [B, L, D]
    if attrs.get("combiner", "sum") == "sum":
        out = jnp.sum(emb, axis=1)
    else:
        out = jnp.mean(emb, axis=1)
    return {"Out": [out]}


@register_op("fused_fc_elementwise_layernorm")
def _fused_fc_elementwise_layernorm(ins, attrs, ctx):
    x, w = _p(ins, "X"), _p(ins, "W")
    y = _p(ins, "Y")
    h = x.reshape(x.shape[0], -1) @ w
    if ins.get("Bias0"):
        h = h + ins["Bias0"][0]
    h = h + y
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    out = (h - mu) * lax.rsqrt(var + eps)
    if ins.get("Scale"):
        out = out * ins["Scale"][0]
    if ins.get("Bias1"):
        out = out + ins["Bias1"][0]
    return {"Out": [out]}


@register_op("skip_layernorm")
def _skip_layernorm(ins, attrs, ctx):
    x, y = _p(ins, "X"), _p(ins, "Y")
    h = x + y
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    out = (h - mu) * lax.rsqrt(var + eps)
    if ins.get("Scale"):
        out = out * ins["Scale"][0]
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("conv_fusion")
def _conv_fusion(ins, attrs, ctx):
    outs = get_op("conv2d").fn(
        {k: v for k, v in ins.items() if k in ("Input", "Filter")},
        attrs, ctx)
    y = outs["Output"][0]
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(1, -1, 1, 1)
    if ins.get("ResidualData"):
        y = y + ins["ResidualData"][0]
    return {"Output": [_act(attrs.get("activation", "relu"), y)]}


@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ins, attrs, ctx):
    x = _p(ins, "X").reshape(np.shape(ins["X"][0])[0], -1)
    ws, bs = list(ins["W"]), list(ins.get("Bias", []))
    for i, w in enumerate(ws):
        x = x @ w
        if i < len(bs):
            x = x + bs[i]
        x = jax.nn.relu(x)
    return {"Out": [x]}


@register_op("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ins, attrs, ctx):
    """(XY)^2 - (X^2)(Y^2), scaled (fusion_squared_mat_sub_op.cc)."""
    x, y = _p(ins, "X"), _p(ins, "Y")
    scalar = attrs.get("scalar", 1.0)
    xy = x @ y
    x2y2 = (x * x) @ (y * y)
    return {"Out": [scalar * (xy * xy - x2y2)]}


@register_op("fusion_transpose_flatten_concat")
def _fusion_transpose_flatten_concat(ins, attrs, ctx):
    axis = attrs.get("concat_axis", 1)
    trans = attrs.get("trans_axis", None)
    outs = []
    for x in ins["X"]:
        if trans:
            x = jnp.transpose(x, trans)
        outs.append(x.reshape(x.shape[0], -1))
    return {"Out": [jnp.concatenate(outs, axis=axis)]}


@register_op("fusion_seqpool_concat")
def _fusion_seqpool_concat(ins, attrs, ctx):
    pool = attrs.get("pooltype", "SUM").upper()

    def red(x):
        if pool == "AVERAGE":
            return jnp.mean(x, axis=1)
        if pool == "MAX":
            return jnp.max(x, axis=1)
        if pool == "SQRT":                 # sum / sqrt(len)
            return jnp.sum(x, axis=1) / jnp.sqrt(float(x.shape[1]))
        return jnp.sum(x, axis=1)

    outs = [red(x) if x.ndim == 3 else x for x in ins["X"]]
    return {"Out": [jnp.concatenate(outs, axis=-1)]}


@register_op("fusion_seqpool_cvm_concat")
def _fusion_seqpool_cvm_concat(ins, attrs, ctx):
    outs = [jnp.sum(x, axis=1) if x.ndim == 3 else x for x in ins["X"]]
    if not attrs.get("use_cvm", True):
        outs = [x[:, 2:] for x in outs]   # strip show/click lead columns
    return {"Out": [jnp.concatenate(outs, axis=-1)]}


@register_op("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ins, attrs, ctx):
    xs = list(ins["X"])
    ref = xs[0]
    expanded = [x if x.ndim == ref.ndim else
                jnp.broadcast_to(x[:, None], ref.shape[:2] + x.shape[1:])
                for x in xs]
    cat = jnp.concatenate(expanded, axis=-1)
    w = _p(ins, "FCWeight")
    out = cat.reshape(-1, cat.shape[-1]) @ w
    if ins.get("FCBias"):
        out = out + ins["FCBias"][0]
    out = _act(attrs.get("fc_activation", "identity"), out)
    return {"Out": [out.reshape(cat.shape[:-1] + (w.shape[1],))]}


@register_op("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ins, attrs, ctx):
    conv = get_op("sequence_conv").fn(
        {"X": ins["X"], "Filter": ins["Filter"]},
        {"contextLength": attrs.get("contextLength", 3),
         "contextStart": attrs.get("contextStart", -1),
         "contextStride": attrs.get("contextStride", 1)}, ctx)
    out = conv["Out"][0] + _p(ins, "Bias")
    return {"Out": [jax.nn.relu(out)]}


def _run_rnn(op, ins, attrs, ctx, xw_name="WeightX", hw_name="WeightH"):
    """fusion_gru/fusion_lstm: x-projection then the plain recurrent op."""
    x = _p(ins, "X")
    wx = _p(ins, xw_name)
    proj = x @ wx
    inner_ins = {"Input": [proj], "Weight": [_p(ins, hw_name)]}
    if ins.get("Bias"):
        inner_ins["Bias"] = ins["Bias"]
    if ins.get("H0"):
        inner_ins["H0"] = ins["H0"]
    if op == "lstm" and ins.get("C0"):
        inner_ins["C0"] = ins["C0"]
    return get_op(op).fn(inner_ins, attrs, ctx)


@register_op("fusion_gru")
def _fusion_gru(ins, attrs, ctx):
    outs = _run_rnn("gru", ins, attrs, ctx)
    return {"Hidden": outs.get("Hidden", outs.get("Out", []))}


@register_op("fusion_lstm")
def _fusion_lstm(ins, attrs, ctx):
    outs = _run_rnn("lstm", ins, attrs, ctx)
    return {"Hidden": outs.get("Hidden", []), "Cell": outs.get("Cell", [])}


@register_op("multi_gru")
def _multi_gru(ins, attrs, ctx):
    """Stacked (bi)GRU layers (multi_gru_op.cc) — chain the gru lowering."""
    x = _p(ins, "X")
    wxs, whs = list(ins["WeightX"]), list(ins["WeightH"])
    bs = list(ins.get("Bias", []))
    h = x
    for i, (wx, wh) in enumerate(zip(wxs, whs)):
        inner = {"Input": [h @ wx], "Weight": [wh]}
        if i < len(bs):
            inner["Bias"] = [bs[i]]
        outs = get_op("gru").fn(inner, attrs, ctx)
        h = outs.get("Hidden", outs.get("Out"))[0]
    return {"Hidden": [h]}


@register_op("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ins, attrs, ctx):
    ids, w = _p(ins, "Ids"), _p(ins, "Embeddings")
    emb = jnp.take(w, ids.reshape(ids.shape[0], -1), axis=0)
    inner = {"Input": [emb.reshape(emb.shape[0], emb.shape[1], -1)
                       if emb.ndim > 2 else emb],
             "Weight": [_p(ins, "WeightH")]}
    if ins.get("Bias"):
        inner["Bias"] = ins["Bias"]
    outs = get_op("lstm").fn(inner, attrs, ctx)
    return {"Hidden": outs.get("Hidden", []), "Cell": outs.get("Cell", [])}


@register_op("scaled_int8fc")
def _scaled_int8fc(ins, attrs, ctx):
    """qingshui scaled_int8fc: int8-quantized fc simulated in int32 math
    (bit-exact path is inference-only; training sees the dequant values)."""
    x, w = _p(ins, "Input"), _p(ins, "W")
    sx = attrs.get("input_scale", 1.0)
    sw = attrs.get("weight_scale", 1.0)
    qx = jnp.clip(jnp.round(x / sx), -127, 127)
    qw = jnp.clip(jnp.round(w / sw), -127, 127)
    out = (qx @ qw) * (sx * sw)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("fused_seqpool_cvm_with_pcoc")
def _fused_seqpool_cvm_with_pcoc(ins, attrs, ctx):
    """fused_seqpool_cvm_with_pcoc_op (qingshui): seqpool each input, keep
    show/click (+pcoc) lead columns per use_cvm."""
    outs = []
    for x in ins["X"]:
        pooled = jnp.sum(x, axis=1) if x.ndim == 3 else x
        if not attrs.get("use_cvm", True):
            pooled = pooled[:, 3:]        # show/clk/pcoc stripped
        outs.append(pooled)
    return {"Out": outs}


@register_op("c_mixallgather")
def _c_mixallgather(ins, attrs, ctx):
    """c_mixallgather_op (qingshui): concat local tensors then allgather
    over the ring (single fused collective)."""
    x = jnp.concatenate([v.reshape(-1) for v in ins["X"]])
    axis = ctx.axis_for_ring(attrs.get("ring_id", 0))
    if axis is None:
        return {"Out": [x]}
    return {"Out": [lax.all_gather(x, axis_name=axis, tiled=True)]}
