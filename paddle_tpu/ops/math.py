"""Dense math + elementwise + activation lowering rules.

Reference inventory: paddle/fluid/operators/{activation_op,matmul_op,mul_op,
elementwise/*,scale_op,clip_op,...}.cc (SURVEY §2.5, A.1).  Each CUDA kernel
there becomes a jnp expression here; XLA fuses elementwise chains into the
surrounding matmul/conv — the fusion passes of framework/ir (fc_fuse,
fuse_elewise_add_act...) are intentionally absent because the compiler
performs them (SURVEY §7 design stance).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _x(ins, slot="X", i=0):
    return ins[slot][i]


# --------------------------------------------------------------------------
# elementwise binary ops with axis-style numpy broadcasting
# (operators/elementwise/elementwise_op_function.h semantics: `axis` names the
# dim of X at which Y's shape aligns; -1 = trailing alignment)
# --------------------------------------------------------------------------
def _bcast(x, y, axis):
    if axis is None or axis == -1 or x.ndim == y.ndim:
        return x, y
    # align y's dims starting at `axis` of x
    expand = [1] * x.ndim
    for i, d in enumerate(y.shape):
        expand[axis + i] = d
    return x, y.reshape(expand)


def _ew(name, f):
    def lower(ins, attrs, ctx):
        x, y = _bcast(_x(ins), _x(ins, "Y"), attrs.get("axis", -1))
        out = f(x, y)
        scale = attrs.get("scale", None)
        if scale is not None and scale != 1.0:
            out = out * scale
        return {"Out": [out]}
    register_op(name, lower)


_ew("elementwise_add", jnp.add)
_ew("elementwise_sub", jnp.subtract)
_ew("elementwise_mul", jnp.multiply)
_ew("elementwise_div", jnp.divide)
_ew("elementwise_min", jnp.minimum)
_ew("elementwise_max", jnp.maximum)
_ew("elementwise_pow", jnp.power)
_ew("elementwise_mod", jnp.mod)


def _trunc_div(a, b):
    # elementwise_floordiv_op.h:38: trunc(a / b) — C-style division
    # toward ZERO, not python floor (differs for negative operands)
    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.integer) \
            and jnp.issubdtype(jnp.asarray(b).dtype, jnp.integer):
        return lax.div(a, b)
    return jnp.trunc(a / b)


_ew("elementwise_floordiv", _trunc_div)


@register_op("sum")  # fluid sum op: variadic add (used for grad fan-in)
def _sum(ins, attrs, ctx):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


# --------------------------------------------------------------------------
# activations (operators/activation_op.cc — the full list)
# --------------------------------------------------------------------------
def _unary(name, f, extra_out=None):
    def lower(ins, attrs, ctx):
        out = f(_x(ins), attrs)
        return {"Out": [out]}
    register_op(name, lower)


_unary("relu", lambda x, a: jax.nn.relu(x))
_unary("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_unary("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_unary("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_unary("tanh", lambda x, a: jnp.tanh(x))
_unary("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_unary("gelu", lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)))
_unary("erf", lambda x, a: lax.erf(x))
_unary("exp", lambda x, a: jnp.exp(x))
_unary("log", lambda x, a: jnp.log(x))
_unary("log2", lambda x, a: jnp.log2(x))
_unary("log10", lambda x, a: jnp.log10(x))
_unary("log1p", lambda x, a: jnp.log1p(x))
_unary("sqrt", lambda x, a: jnp.sqrt(x))
_unary("rsqrt", lambda x, a: lax.rsqrt(x))
_unary("square", lambda x, a: jnp.square(x))
_unary("abs", lambda x, a: jnp.abs(x))
_unary("ceil", lambda x, a: jnp.ceil(x))
_unary("floor", lambda x, a: jnp.floor(x))
_unary("round", lambda x, a: jnp.round(x))
_unary("reciprocal", lambda x, a: jnp.reciprocal(x))
_unary("sign", lambda x, a: jnp.sign(x))
_unary("sin", lambda x, a: jnp.sin(x))
_unary("cos", lambda x, a: jnp.cos(x))
_unary("tan", lambda x, a: jnp.tan(x))
_unary("asin", lambda x, a: jnp.arcsin(x))
_unary("acos", lambda x, a: jnp.arccos(x))
_unary("atan", lambda x, a: jnp.arctan(x))
_unary("sinh", lambda x, a: jnp.sinh(x))
_unary("cosh", lambda x, a: jnp.cosh(x))
# activation_op.h:1055-1068: log(1+exp(beta*x))/beta, linear past the
# numerical-stability threshold (the softplus v1 checkpoint attrs)
_unary("softplus", lambda x, a: jnp.where(
    a.get("beta", 1.0) * x > a.get("threshold", 20.0), x,
    jax.nn.softplus(a.get("beta", 1.0) * x) / a.get("beta", 1.0)))
_unary("softsign", lambda x, a: jax.nn.soft_sign(x))
_unary("softshrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("lambda", 0.5),
    x - jnp.sign(x) * a.get("lambda", 0.5), 0.0))
_unary("hard_shrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_unary("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_unary("hard_swish", lambda x, a: x * jnp.clip(
    x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0)) / a.get("scale", 6.0))
_unary("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_unary("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_unary("selu", lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
    x > 0, x, a.get("alpha", 1.6732632423543772) * (jnp.exp(x) - 1)))
_unary("elu", lambda x, a: jnp.where(
    x > 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)))
_unary("leaky_relu", lambda x, a: jnp.where(x > 0, x, a.get("alpha", 0.02) * x))
_unary("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
_unary("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, 0.0))
_unary("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
    a.get("scale_a", 0.67) * x))
_unary("silu", lambda x, a: jax.nn.silu(x))
_unary("logit", lambda x, a: jax.scipy.special.logit(
    jnp.clip(x, a.get("eps", 1e-6), 1 - a.get("eps", 1e-6))))


@register_op("prelu")
def _prelu(ins, attrs, ctx):
    x, alpha = _x(ins), _x(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel" and alpha.ndim < x.ndim:
        shape = [1] * x.ndim
        shape[1] = alpha.size
        alpha = alpha.reshape(shape)
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@register_op("pow")
def _pow(ins, attrs, ctx):
    f = ins.get("FactorTensor")
    factor = f[0] if f else attrs.get("factor", 1.0)
    return {"Out": [jnp.power(_x(ins), factor)]}


@register_op("scale")
def _scale(ins, attrs, ctx):
    x = _x(ins)
    s = ins.get("ScaleTensor")
    scale = s[0] if s else attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * scale + bias]}
    return {"Out": [(x + bias) * scale]}


@register_op("clip")
def _clip(ins, attrs, ctx):
    return {"Out": [jnp.clip(_x(ins), attrs.get("min"), attrs.get("max"))]}


@register_op("clip_by_norm")
def _clip_by_norm(ins, attrs, ctx):
    x = _x(ins)
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [jnp.where(norm > max_norm, x * (max_norm / norm), x)]}


# --------------------------------------------------------------------------
# matmul family — the MXU path. bf16 inputs hit the systolic array natively;
# preferred_element_type keeps fp32 accumulation (SURVEY §7: MXU guidance).
# --------------------------------------------------------------------------
def _acc_type(x):
    return jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None


@register_op("matmul")
def _matmul(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x))
    if out.dtype != x.dtype:
        out = out.astype(x.dtype)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("matmul_v2")
def _matmul_v2(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x))
    return {"Out": [out.astype(x.dtype) if out.dtype != x.dtype else out]}


@register_op("mul")  # operators/mul_op.cc: flatten then 2-D matmul
def _mul(ins, attrs, ctx):
    import numpy as np
    x, y = _x(ins), _x(ins, "Y")
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), -1))
    y2 = y.reshape((int(np.prod(ys[:yn])), -1))
    out = jnp.matmul(x2, y2, preferred_element_type=_acc_type(x))
    out = out.astype(x.dtype) if out.dtype != x.dtype else out
    return {"Out": [out.reshape(xs[:xn] + ys[yn:])]}


@register_op("bmm")
def _bmm(ins, attrs, ctx):
    out = jnp.matmul(_x(ins), _x(ins, "Y"), preferred_element_type=_acc_type(_x(ins)))
    return {"Out": [out.astype(_x(ins).dtype)]}


@register_op("dot")
def _dot(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=x.ndim > 1)]}


@register_op("mv")
def _mv(ins, attrs, ctx):
    return {"Out": [jnp.matmul(_x(ins), _x(ins, "Vec"))]}


@register_op("addmm")
def _addmm(ins, attrs, ctx):
    inp, x, y = _x(ins, "Input"), _x(ins), _x(ins, "Y")
    return {"Out": [attrs.get("Beta", 1.0) * inp +
                    attrs.get("Alpha", 1.0) * jnp.matmul(x, y)]}


@register_op("kron")
def _kron(ins, attrs, ctx):
    return {"Out": [jnp.kron(_x(ins), _x(ins, "Y"))]}


@register_op("cross")
def _cross(ins, attrs, ctx):
    return {"Out": [jnp.cross(_x(ins), _x(ins, "Y"),
                              axis=attrs.get("dim", -1))]}


@register_op("trace")
def _trace(ins, attrs, ctx):
    return {"Out": [jnp.trace(_x(ins, "Input"), offset=attrs.get("offset", 0),
                              axis1=attrs.get("axis1", 0),
                              axis2=attrs.get("axis2", 1))]}


@register_op("cholesky")
def _cholesky(ins, attrs, ctx):
    L = jnp.linalg.cholesky(_x(ins))
    if attrs.get("upper", False):
        L = jnp.swapaxes(L, -1, -2)
    return {"Out": [L]}


@register_op("inverse")
def _inverse(ins, attrs, ctx):
    return {"Output": [jnp.linalg.inv(_x(ins, "Input"))]}


@register_op("cumsum")
def _cumsum(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x, axis = x.ravel(), 0
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        pad = [(0, 0)] * out.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad)[tuple(
            slice(0, -1) if i == axis % out.ndim else slice(None)
            for i in range(out.ndim))]
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis)
    return {"Out": [out]}


@register_op("p_norm")
def _p_norm(ins, attrs, ctx):
    x = _x(ins)
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keep = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keep) ** (1.0 / p)
    return {"Out": [out]}


@register_op("l1_norm")
def _l1_norm(ins, attrs, ctx):
    return {"Out": [jnp.sum(jnp.abs(_x(ins)))]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ins, attrs, ctx):
    return {"Out": [jnp.sum(jnp.square(_x(ins))).reshape(1)]}


@register_op("squared_l2_distance")
def _squared_l2_distance(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    sub = x - y
    return {"sub_result": [sub],
            "Out": [jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                            keepdims=False).reshape(-1, 1)]}


@register_op("cos_sim")
def _cos_sim(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    return {"Out": [jnp.sum(x * y, -1, keepdims=True) / (xn * yn)],
            "XNorm": [xn], "YNorm": [yn]}


@register_op("dist")
def _dist(ins, attrs, ctx):
    x, y = _x(ins), _x(ins, "Y")
    p = attrs.get("p", 2.0)
    d = jnp.abs(x - y)
    if p == float("inf"):
        return {"Out": [jnp.max(d)]}
    if p == 0:
        return {"Out": [jnp.sum(d != 0).astype(x.dtype)]}
    return {"Out": [jnp.sum(d ** p) ** (1 / p)]}


@register_op("logsumexp")
def _logsumexp(ins, attrs, ctx):
    axis = attrs.get("axis", None)
    axis = tuple(axis) if axis else None
    return {"Out": [jax.scipy.special.logsumexp(
        _x(ins), axis=axis, keepdims=attrs.get("keepdim", False))]}


# comparisons / logical (operators/controlflow/{compare_op,logical_op}.cc)
def _cmp(name, f):
    def lower(ins, attrs, ctx):
        return {"Out": [f(_x(ins), _x(ins, "Y"))]}
    register_op(name, lower, differentiable=False)


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("logical_and", jnp.logical_and)
_cmp("logical_or", jnp.logical_or)
_cmp("logical_xor", jnp.logical_xor)
register_op("logical_not",
            lambda ins, attrs, ctx: {"Out": [jnp.logical_not(_x(ins))]},
            differentiable=False)


@register_op("isfinite", differentiable=False)
def _isfinite(ins, attrs, ctx):
    # fluid isfinite: scalar "all finite" over the (possibly multi-)input
    flags = [jnp.all(jnp.isfinite(x)) for x in ins["X"]]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return {"Out": [out]}


register_op("isfinite_v2", lambda ins, a, c: {"Out": [jnp.isfinite(_x(ins))]},
            differentiable=False)
register_op("isinf_v2", lambda ins, a, c: {"Out": [jnp.isinf(_x(ins))]},
            differentiable=False)
register_op("isnan_v2", lambda ins, a, c: {"Out": [jnp.isnan(_x(ins))]},
            differentiable=False)


@register_op("allclose", differentiable=False)
def _allclose(ins, attrs, ctx):
    return {"Out": [jnp.allclose(_x(ins, "Input"), _x(ins, "Other"),
                                 rtol=float(attrs.get("rtol", 1e-5)),
                                 atol=float(attrs.get("atol", 1e-8)),
                                 equal_nan=attrs.get("equal_nan", False))]}
