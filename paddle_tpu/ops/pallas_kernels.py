"""Pallas TPU kernels for the ops where XLA fusion leaves perf on the table.

Two hot spots (measured with tools/mfu_sweep.py on BERT-base, v5e):

* flash attention — at seq>=256 XLA materialises the [B, H, T, T] score
  tensor; the pallas kernel streams K/V blocks through VMEM (SURVEY §7
  step 3: "Pallas kernels only where XLA fusion falls short, e.g. fused
  attention").  Wraps jax's production TPU kernel.
* fused dropout — the jax.random path costs ~15ms/step on BERT-base
  (sweep case `nodrop`): per-element uniforms + a bool mask residual both
  round-trip HBM.  Here the mask is derived from the on-core hardware PRNG
  (pltpu.prng_random_bits) and the backward pass RE-SEEDS the same PRNG to
  regenerate it — zero mask bytes written, zero residuals saved.

Everything degrades gracefully: CPU/interpret backends take the jnp path in
the callers (ops/attention.py, ops/nn_ops.py gate on backend).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_tpu", "fused_dropout_tpu",
           "fused_dropout_add_tpu", "fused_act_dropout_tpu"]


# ---------------------------------------------------------------------------
# flash attention: thin wrapper over jax's production pallas kernel
# ---------------------------------------------------------------------------

def flash_attention_tpu(q, k, v, scale=None, causal=False):
    """q/k/v: [B, H, T, D].  Falls back by raising ImportError-like None
    handling in the caller if shapes are unsupported."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _fa(q, k, v, causal=causal, sm_scale=float(scale))


# ---------------------------------------------------------------------------
# fused dropout with mask regeneration in backward
# ---------------------------------------------------------------------------

def _pick_block_rows(m: int, n: int) -> int:
    """Largest power-of-two row count that divides m and keeps a block
    under ~2MB of VMEM at 4B/elem."""
    cap = max(1, (2 << 20) // (n * 4))
    bm = 1
    while bm * 2 <= cap and m % (bm * 2) == 0:
        bm *= 2
    return bm


def _dropout_kernel(seed_ref, x_ref, o_ref, *, threshold, scale):
    # distinct stream per grid block: hardware PRNG seeded from (seed, block)
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    x = x_ref[:]
    o_ref[:] = jnp.where(keep, x * x.dtype.type(scale),
                         x.dtype.type(0.0))


def _dropout_mask_kernel(seed_ref, o_ref, *, threshold):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(o_ref.shape), jnp.uint32)
    o_ref[:] = (bits >= jnp.uint32(threshold)).astype(jnp.uint8)


def _run_dropout(x2d, seed, threshold, scale):
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_dropout_kernel, threshold=threshold, scale=scale),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d)


def _threshold_for(rate: float) -> int:
    # P(bits >= threshold) == 1 - rate over uint32
    return min(int(rate * 4294967296.0), 4294967295)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_dropout(x2d, seed, rate, upscale):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(x2d, seed, _threshold_for(rate), scale)


def _fused_dropout_fwd(x2d, seed, rate, upscale):
    return _fused_dropout(x2d, seed, rate, upscale), seed


def _fused_dropout_bwd(rate, upscale, seed, g):
    # the SAME seed regenerates the SAME mask — no residual mask in HBM
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(g, seed, _threshold_for(rate), scale), None


_fused_dropout.defvjp(_fused_dropout_fwd, _fused_dropout_bwd)


def _seed_from_key(key):
    return jax.random.bits(key, (1,), "uint32").astype(jnp.int32)


def fused_dropout_supported(x) -> bool:
    """Static shape check: last dim lane-aligned, total a multiple of it."""
    if x.ndim == 0 or x.size == 0:
        return False
    n = x.shape[-1]
    return n % 128 == 0 and (x.size // n) >= 1


# ---------------------------------------------------------------------------
# dropout fused with its elementwise neighbours: residual add / activation.
#
# The round-3 sweep showed ~13 MFU points between `nodrop` (55.3%) and
# baseline (42.7%) BERT: each pallas dropout call is an opaque boundary, so
# the residual add AFTER it and the gelu BEFORE it each cost a full extra
# HBM pass of the activation tensor.  Pulling those neighbours INTO the
# dropout kernel removes the boundary; backward regenerates the mask from
# the same on-core PRNG seed (no residual bytes), and the activation
# derivative is recomputed from the pre-activation x the matmul backward
# already keeps live.
# ---------------------------------------------------------------------------

def _dropout_add_kernel(seed_ref, x_ref, r_ref, o_ref, *, threshold, scale):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    x = x_ref[:]
    o_ref[:] = jnp.where(keep, x * x.dtype.type(scale),
                         x.dtype.type(0.0)) + r_ref[:]


def _run_dropout_add(x2d, r2d, seed, threshold, scale):
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_dropout_add_kernel, threshold=threshold,
                          scale=scale),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0)),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d, r2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_dropout_add(x2d, r2d, seed, rate, upscale):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout_add(x2d, r2d, seed, _threshold_for(rate), scale)


def _fused_dropout_add_fwd(x2d, r2d, seed, rate, upscale):
    return _fused_dropout_add(x2d, r2d, seed, rate, upscale), seed


def _fused_dropout_add_bwd(rate, upscale, seed, g):
    # d/dx: same regenerated mask applied to g; d/dresidual: g unchanged
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(g, seed, _threshold_for(rate), scale), g, None


_fused_dropout_add.defvjp(_fused_dropout_add_fwd, _fused_dropout_add_bwd)


def fused_dropout_add_tpu(x, residual, key, rate, upscale_in_train):
    """out = dropout(x) + residual in one kernel pass; backward
    regenerates the mask and passes the residual cotangent through."""
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    out = _fused_dropout_add(x.reshape(-1, n), residual.reshape(-1, n),
                             seed, float(rate), bool(upscale_in_train))
    return out.reshape(shape)


def _erf(x):
    """In-kernel erf: Abramowitz & Stegun 7.1.26 (|err| <= 1.5e-7).
    lax.erf has no Mosaic/Pallas-TPU lowering (KernelType.TC rejects it);
    this uses only mul/add/exp, all of which lower."""
    a1, a2, a3, a4, a5 = (0.254829592, -0.284496736, 1.421413741,
                          -1.453152027, 1.061405429)
    p = 0.3275911
    s = jnp.sign(x)
    ax = jnp.abs(x)
    t = 1.0 / (1.0 + p * ax)
    poly = ((((a5 * t + a4) * t + a3) * t + a2) * t + a1) * t
    return s * (1.0 - poly * jnp.exp(-ax * ax))


def _act_fns(act):
    import math
    if act == "relu":
        return (lambda x: jnp.maximum(x, x.dtype.type(0.0)),
                lambda x: (x > 0).astype(x.dtype))
    if act == "gelu":                   # erf form (paddle default)
        c = 1.0 / math.sqrt(2.0)
        cpdf = 1.0 / math.sqrt(2.0 * math.pi)

        def f(x):
            xf = x.astype(jnp.float32)
            return (0.5 * xf * (1.0 + _erf(xf * c))).astype(x.dtype)

        def df(x):
            xf = x.astype(jnp.float32)
            phi = 0.5 * (1.0 + _erf(xf * c))
            return (phi + xf * cpdf * jnp.exp(-0.5 * xf * xf)) \
                .astype(x.dtype)
        return f, df
    raise ValueError(f"fused_act_dropout: unsupported act '{act}'")


def _act_dropout_kernel(seed_ref, x_ref, o_ref, *, threshold, scale, act):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    f, _ = _act_fns(act)
    a = f(x_ref[:])
    o_ref[:] = jnp.where(keep, a * a.dtype.type(scale), a.dtype.type(0.0))


def _act_dropout_bwd_kernel(seed_ref, x_ref, g_ref, o_ref, *, threshold,
                            scale, act):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    _, df = _act_fns(act)
    g = g_ref[:]
    o_ref[:] = jnp.where(keep, g * g.dtype.type(scale),
                         g.dtype.type(0.0)) * df(x_ref[:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_act_dropout(x2d, seed, rate, upscale, act):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_act_dropout_kernel,
                          threshold=_threshold_for(rate), scale=scale,
                          act=act),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d)


def _fused_act_dropout_fwd(x2d, seed, rate, upscale, act):
    # residuals: pre-activation x (a matmul output the AD graph already
    # holds) + the seed; the mask itself is never materialised
    return _fused_act_dropout(x2d, seed, rate, upscale, act), (x2d, seed)


def _fused_act_dropout_bwd(rate, upscale, act, res, g):
    x2d, seed = res
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    dx = pl.pallas_call(
        functools.partial(_act_dropout_bwd_kernel,
                          threshold=_threshold_for(rate), scale=scale,
                          act=act),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0)),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d, g)
    return dx, None


_fused_act_dropout.defvjp(_fused_act_dropout_fwd, _fused_act_dropout_bwd)


def fused_act_dropout_tpu(x, key, rate, upscale_in_train, act):
    """out = dropout(act(x)) in one kernel; backward fuses act'(x) with
    the regenerated mask (one kernel, no saved mask/activation)."""
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    out = _fused_act_dropout(x.reshape(-1, n), seed, float(rate),
                             bool(upscale_in_train), act)
    return out.reshape(shape)


def fused_dropout_tpu(x, key, rate, upscale_in_train):
    """Dropout with on-core PRNG mask, regenerated in backward.

    Returns (out, mask_fn) where mask_fn() materialises the uint8 keep-mask
    with a second kernel from the same seed — called only if the consumer
    actually fetches the Mask output, so XLA DCEs it otherwise.
    """
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    x2d = x.reshape(-1, n)
    out = _fused_dropout(x2d, seed, float(rate), bool(upscale_in_train))

    def mask_fn():
        m = x2d.shape[0]
        bm = _pick_block_rows(m, n)
        mask = pl.pallas_call(
            functools.partial(_dropout_mask_kernel,
                              threshold=_threshold_for(float(rate))),
            grid=(m // bm,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        )(seed)
        return mask.reshape(shape)

    return out.reshape(shape), mask_fn
