"""Pallas TPU kernels for the ops where XLA fusion leaves perf on the table.

Two hot spots (measured with tools/mfu_sweep.py on BERT-base, v5e):

* flash attention — at seq>=256 XLA materialises the [B, H, T, T] score
  tensor; the pallas kernel streams K/V blocks through VMEM (SURVEY §7
  step 3: "Pallas kernels only where XLA fusion falls short, e.g. fused
  attention").  Wraps jax's production TPU kernel.
* fused dropout — the jax.random path costs ~15ms/step on BERT-base
  (sweep case `nodrop`): per-element uniforms + a bool mask residual both
  round-trip HBM.  Here the mask is derived from the on-core hardware PRNG
  (pltpu.prng_random_bits) and the backward pass RE-SEEDS the same PRNG to
  regenerate it — zero mask bytes written, zero residuals saved.

Everything degrades gracefully: CPU/interpret backends take the jnp path in
the callers (ops/attention.py, ops/nn_ops.py gate on backend).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_tpu", "fused_dropout_tpu"]


# ---------------------------------------------------------------------------
# flash attention: thin wrapper over jax's production pallas kernel
# ---------------------------------------------------------------------------

def flash_attention_tpu(q, k, v, scale=None, causal=False):
    """q/k/v: [B, H, T, D].  Falls back by raising ImportError-like None
    handling in the caller if shapes are unsupported."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _fa(q, k, v, causal=causal, sm_scale=float(scale))


# ---------------------------------------------------------------------------
# fused dropout with mask regeneration in backward
# ---------------------------------------------------------------------------

def _pick_block_rows(m: int, n: int) -> int:
    """Largest power-of-two row count that divides m and keeps a block
    under ~2MB of VMEM at 4B/elem."""
    cap = max(1, (2 << 20) // (n * 4))
    bm = 1
    while bm * 2 <= cap and m % (bm * 2) == 0:
        bm *= 2
    return bm


def _dropout_kernel(seed_ref, x_ref, o_ref, *, threshold, scale):
    # distinct stream per grid block: hardware PRNG seeded from (seed, block)
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    x = x_ref[:]
    o_ref[:] = jnp.where(keep, x * x.dtype.type(scale),
                         x.dtype.type(0.0))


def _dropout_mask_kernel(seed_ref, o_ref, *, threshold):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(o_ref.shape), jnp.uint32)
    o_ref[:] = (bits >= jnp.uint32(threshold)).astype(jnp.uint8)


def _run_dropout(x2d, seed, threshold, scale):
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_dropout_kernel, threshold=threshold, scale=scale),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d)


def _threshold_for(rate: float) -> int:
    # P(bits >= threshold) == 1 - rate over uint32
    return min(int(rate * 4294967296.0), 4294967295)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_dropout(x2d, seed, rate, upscale):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(x2d, seed, _threshold_for(rate), scale)


def _fused_dropout_fwd(x2d, seed, rate, upscale):
    return _fused_dropout(x2d, seed, rate, upscale), seed


def _fused_dropout_bwd(rate, upscale, seed, g):
    # the SAME seed regenerates the SAME mask — no residual mask in HBM
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(g, seed, _threshold_for(rate), scale), None


_fused_dropout.defvjp(_fused_dropout_fwd, _fused_dropout_bwd)


def _seed_from_key(key):
    return jax.random.bits(key, (1,), "uint32").astype(jnp.int32)


def fused_dropout_supported(x) -> bool:
    """Static shape check: last dim lane-aligned, total a multiple of it."""
    if x.ndim == 0 or x.size == 0:
        return False
    n = x.shape[-1]
    return n % 128 == 0 and (x.size // n) >= 1


def fused_dropout_tpu(x, key, rate, upscale_in_train):
    """Dropout with on-core PRNG mask, regenerated in backward.

    Returns (out, mask_fn) where mask_fn() materialises the uint8 keep-mask
    with a second kernel from the same seed — called only if the consumer
    actually fetches the Mask output, so XLA DCEs it otherwise.
    """
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    x2d = x.reshape(-1, n)
    out = _fused_dropout(x2d, seed, float(rate), bool(upscale_in_train))

    def mask_fn():
        m = x2d.shape[0]
        bm = _pick_block_rows(m, n)
        mask = pl.pallas_call(
            functools.partial(_dropout_mask_kernel,
                              threshold=_threshold_for(float(rate))),
            grid=(m // bm,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        )(seed)
        return mask.reshape(shape)

    return out.reshape(shape), mask_fn
