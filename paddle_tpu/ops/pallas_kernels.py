"""Pallas TPU kernels for the ops where XLA fusion leaves perf on the table.

Two hot spots (measured with tools/mfu_sweep.py on BERT-base, v5e):

* flash attention — at seq>=256 XLA materialises the [B, H, T, T] score
  tensor; the pallas kernel streams K/V blocks through VMEM (SURVEY §7
  step 3: "Pallas kernels only where XLA fusion falls short, e.g. fused
  attention").  Wraps jax's production TPU kernel.
* fused dropout — the jax.random path costs ~15ms/step on BERT-base
  (sweep case `nodrop`): per-element uniforms + a bool mask residual both
  round-trip HBM.  Here the mask is derived from the on-core hardware PRNG
  (pltpu.prng_random_bits) and the backward pass RE-SEEDS the same PRNG to
  regenerate it — zero mask bytes written, zero residuals saved.

Everything degrades gracefully: CPU/interpret backends take the jnp path in
the callers (ops/attention.py, ops/nn_ops.py gate on backend).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_tpu", "fused_dropout_tpu",
           "fused_dropout_add_tpu", "fused_act_dropout_tpu",
           "fused_embedding_pool_tpu", "embedding_pool_grad_tpu",
           "fused_embedding_pool_stream_tpu",
           "embedding_pool_grad_stream_tpu",
           "fused_embedding_pool_supported",
           "fused_adam_tpu", "fused_momentum_tpu",
           "paged_flash_attention_tpu", "paged_attention_supported"]


# ---------------------------------------------------------------------------
# flash attention: thin wrapper over jax's production pallas kernel
# ---------------------------------------------------------------------------

def flash_attention_tpu(q, k, v, scale=None, causal=False, ab=None):
    """q/k/v: [B, H, T, D]; ``ab`` an optional additive bias already
    broadcast to [B, H, Tq, Tk] (the kernel's attention-bias argument —
    how a BERT padding mask rides the Pallas path).  Falls back by
    raising ImportError-like None handling in the caller if shapes are
    unsupported."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _fa(q, k, v, ab=ab, causal=causal, sm_scale=float(scale))


# ---------------------------------------------------------------------------
# paged flash attention: decode-step attention over a block-paged KV pool.
#
# The decode plane (serving/decode.py) keeps K/V in fixed-size pages of a
# device-resident pool; a slot's logical KV window is the pool rows named by
# its page table.  The dense decode kernel would need the [B, max_len, d]
# caches materialised per slot — here each grid step walks ITS page-table row
# (SMEM), streams one page of pool rows at a time through VMEM, and folds
# them into an online-softmax accumulator, so the gathered [B, max_len, d]
# tensor never exists.  Positions >= the slot's length mask to -1e30 before
# the running max, matching the XLA fallback's masked-softmax exactly-0.0
# contract (ops/attention.py paged_attention).
# ---------------------------------------------------------------------------

_PAGED_VMEM_BYTES = 8 << 20   # both pools ride as whole VMEM blocks; bigger
                              # pools take the XLA take/reshape fallback


def paged_attention_supported(q, k_pool, idx) -> bool:
    """Static gate for the Pallas paged path: lane-aligned head dim, flat
    2-d pools small enough to hold as one VMEM block each, and a
    per-position index row per batch entry."""
    if q.ndim != 2 or k_pool.ndim != 2 or idx.ndim != 2:
        return False
    d = q.shape[-1]
    if d != k_pool.shape[-1] or d % 128 != 0 or idx.shape[1] == 0:
        return False
    return 2 * k_pool.size * k_pool.dtype.itemsize <= _PAGED_VMEM_BYTES


def _paged_attn_kernel(idx_ref, len_ref, q_ref, kp_ref, vp_ref, o_ref, *,
                       n_blocks, page_size, scale):
    d = o_ref.shape[-1]
    q = q_ref[:]                                    # [1, d]
    length = len_ref[0, 0]

    def body(j, carry):
        m, l, acc = carry
        base = idx_ref[0, j * page_size]            # page rows contiguous
        k = pl.load(kp_ref, (pl.dslice(base, page_size), pl.dslice(0, d)))
        v = pl.load(vp_ref, (pl.dslice(base, page_size), pl.dslice(0, d)))
        s = jax.lax.dot_general(                    # [1, page_size]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(pos < length, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)                      # masked -> exactly 0.0
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((1, 1), jnp.float32)
    acc0 = jnp.zeros((1, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[:] = (acc / l).astype(o_ref.dtype)


def paged_flash_attention_tpu(q, k_pool, v_pool, idx, lengths, scale,
                              page_size=1):
    """q: [B, d] one query row per decode slot; k_pool/v_pool: [R, d] flat
    page pools (R = n_pages * page_size); idx: [B, S] int32 pool-row index
    per logical position (page-contiguous in runs of ``page_size``);
    lengths: [B, 1] int32 valid-position counts.  Returns [B, d]."""
    b, s = idx.shape
    r, d = k_pool.shape
    if s % page_size != 0:
        raise ValueError(f"seq window {s} not a multiple of page_size "
                         f"{page_size}")
    return pl.pallas_call(
        functools.partial(_paged_attn_kernel, n_blocks=s // page_size,
                          page_size=page_size, scale=float(scale)),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, s), lambda i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, 1), lambda i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, d), lambda i: (i, 0)),
                  pl.BlockSpec((r, d), lambda i: (0, 0)),
                  pl.BlockSpec((r, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, d), q.dtype),
    )(idx.astype(jnp.int32), lengths.astype(jnp.int32).reshape(b, 1),
      q, k_pool, v_pool)


# ---------------------------------------------------------------------------
# fused dropout with mask regeneration in backward
# ---------------------------------------------------------------------------

def _pick_block_rows(m: int, n: int) -> int:
    """Largest power-of-two row count that divides m and keeps a block
    under ~2MB of VMEM at 4B/elem."""
    cap = max(1, (2 << 20) // (n * 4))
    bm = 1
    while bm * 2 <= cap and m % (bm * 2) == 0:
        bm *= 2
    return bm


def _dropout_kernel(seed_ref, x_ref, o_ref, *, threshold, scale):
    # distinct stream per grid block: hardware PRNG seeded from (seed, block)
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    x = x_ref[:]
    o_ref[:] = jnp.where(keep, x * x.dtype.type(scale),
                         x.dtype.type(0.0))


def _dropout_mask_kernel(seed_ref, o_ref, *, threshold):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(o_ref.shape), jnp.uint32)
    o_ref[:] = (bits >= jnp.uint32(threshold)).astype(jnp.uint8)


def _run_dropout(x2d, seed, threshold, scale):
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_dropout_kernel, threshold=threshold, scale=scale),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d)


def _threshold_for(rate: float) -> int:
    # P(bits >= threshold) == 1 - rate over uint32
    return min(int(rate * 4294967296.0), 4294967295)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_dropout(x2d, seed, rate, upscale):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(x2d, seed, _threshold_for(rate), scale)


def _fused_dropout_fwd(x2d, seed, rate, upscale):
    return _fused_dropout(x2d, seed, rate, upscale), seed


def _fused_dropout_bwd(rate, upscale, seed, g):
    # the SAME seed regenerates the SAME mask — no residual mask in HBM
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(g, seed, _threshold_for(rate), scale), None


_fused_dropout.defvjp(_fused_dropout_fwd, _fused_dropout_bwd)


def _seed_from_key(key):
    return jax.random.bits(key, (1,), "uint32").astype(jnp.int32)


def fused_dropout_supported(x) -> bool:
    """Static shape check: last dim lane-aligned, total a multiple of it."""
    if x.ndim == 0 or x.size == 0:
        return False
    n = x.shape[-1]
    return n % 128 == 0 and (x.size // n) >= 1


# ---------------------------------------------------------------------------
# dropout fused with its elementwise neighbours: residual add / activation.
#
# The round-3 sweep showed ~13 MFU points between `nodrop` (55.3%) and
# baseline (42.7%) BERT: each pallas dropout call is an opaque boundary, so
# the residual add AFTER it and the gelu BEFORE it each cost a full extra
# HBM pass of the activation tensor.  Pulling those neighbours INTO the
# dropout kernel removes the boundary; backward regenerates the mask from
# the same on-core PRNG seed (no residual bytes), and the activation
# derivative is recomputed from the pre-activation x the matmul backward
# already keeps live.
# ---------------------------------------------------------------------------

def _dropout_add_kernel(seed_ref, x_ref, r_ref, o_ref, *, threshold, scale):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    x = x_ref[:]
    o_ref[:] = jnp.where(keep, x * x.dtype.type(scale),
                         x.dtype.type(0.0)) + r_ref[:]


def _run_dropout_add(x2d, r2d, seed, threshold, scale):
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_dropout_add_kernel, threshold=threshold,
                          scale=scale),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0)),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d, r2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_dropout_add(x2d, r2d, seed, rate, upscale):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout_add(x2d, r2d, seed, _threshold_for(rate), scale)


def _fused_dropout_add_fwd(x2d, r2d, seed, rate, upscale):
    return _fused_dropout_add(x2d, r2d, seed, rate, upscale), seed


def _fused_dropout_add_bwd(rate, upscale, seed, g):
    # d/dx: same regenerated mask applied to g; d/dresidual: g unchanged
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return _run_dropout(g, seed, _threshold_for(rate), scale), g, None


_fused_dropout_add.defvjp(_fused_dropout_add_fwd, _fused_dropout_add_bwd)


def fused_dropout_add_tpu(x, residual, key, rate, upscale_in_train):
    """out = dropout(x) + residual in one kernel pass; backward
    regenerates the mask and passes the residual cotangent through."""
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    out = _fused_dropout_add(x.reshape(-1, n), residual.reshape(-1, n),
                             seed, float(rate), bool(upscale_in_train))
    return out.reshape(shape)


def _erf(x):
    """In-kernel erf: Abramowitz & Stegun 7.1.26 (|err| <= 1.5e-7).
    lax.erf has no Mosaic/Pallas-TPU lowering (KernelType.TC rejects it);
    this uses only mul/add/exp, all of which lower."""
    a1, a2, a3, a4, a5 = (0.254829592, -0.284496736, 1.421413741,
                          -1.453152027, 1.061405429)
    p = 0.3275911
    s = jnp.sign(x)
    ax = jnp.abs(x)
    t = 1.0 / (1.0 + p * ax)
    poly = ((((a5 * t + a4) * t + a3) * t + a2) * t + a1) * t
    return s * (1.0 - poly * jnp.exp(-ax * ax))


def _act_fns(act):
    import math
    if act == "relu":
        return (lambda x: jnp.maximum(x, x.dtype.type(0.0)),
                lambda x: (x > 0).astype(x.dtype))
    if act == "gelu":                   # erf form (paddle default)
        c = 1.0 / math.sqrt(2.0)
        cpdf = 1.0 / math.sqrt(2.0 * math.pi)

        def f(x):
            xf = x.astype(jnp.float32)
            return (0.5 * xf * (1.0 + _erf(xf * c))).astype(x.dtype)

        def df(x):
            xf = x.astype(jnp.float32)
            phi = 0.5 * (1.0 + _erf(xf * c))
            return (phi + xf * cpdf * jnp.exp(-0.5 * xf * xf)) \
                .astype(x.dtype)
        return f, df
    raise ValueError(f"fused_act_dropout: unsupported act '{act}'")


def _act_dropout_kernel(seed_ref, x_ref, o_ref, *, threshold, scale, act):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    f, _ = _act_fns(act)
    a = f(x_ref[:])
    o_ref[:] = jnp.where(keep, a * a.dtype.type(scale), a.dtype.type(0.0))


def _act_dropout_bwd_kernel(seed_ref, x_ref, g_ref, o_ref, *, threshold,
                            scale, act):
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    _, df = _act_fns(act)
    g = g_ref[:]
    o_ref[:] = jnp.where(keep, g * g.dtype.type(scale),
                         g.dtype.type(0.0)) * df(x_ref[:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_act_dropout(x2d, seed, rate, upscale, act):
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    return pl.pallas_call(
        functools.partial(_act_dropout_kernel,
                          threshold=_threshold_for(rate), scale=scale,
                          act=act),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d)


def _fused_act_dropout_fwd(x2d, seed, rate, upscale, act):
    # residuals: pre-activation x (a matmul output the AD graph already
    # holds) + the seed; the mask itself is never materialised
    return _fused_act_dropout(x2d, seed, rate, upscale, act), (x2d, seed)


def _fused_act_dropout_bwd(rate, upscale, act, res, g):
    x2d, seed = res
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    m, n = x2d.shape
    bm = _pick_block_rows(m, n)
    dx = pl.pallas_call(
        functools.partial(_act_dropout_bwd_kernel,
                          threshold=_threshold_for(rate), scale=scale,
                          act=act),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((bm, n), lambda i: (i, 0)),
                  pl.BlockSpec((bm, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2d.dtype),
    )(seed, x2d, g)
    return dx, None


_fused_act_dropout.defvjp(_fused_act_dropout_fwd, _fused_act_dropout_bwd)


def fused_act_dropout_tpu(x, key, rate, upscale_in_train, act):
    """out = dropout(act(x)) in one kernel; backward fuses act'(x) with
    the regenerated mask (one kernel, no saved mask/activation)."""
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    out = _fused_act_dropout(x.reshape(-1, n), seed, float(rate),
                             bool(upscale_in_train), act)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# fused CTR embedding: gather + pool forward, weighted scatter-add backward.
#
# The kernel-tier pass (fluid/passes/kernel_tier.py fuse_sparse_embedding)
# rewrites lookup_table(+sequence_pool) chains onto the fused_embedding_pool
# op; on TPU its lowering lands here.  The naive chain materialises the
# [B, S, D] gathered tensor in HBM just to collapse it one op later — here
# each batch row streams its S table rows through VMEM and accumulates the
# pooled [1, D] result in registers, so the intermediate never exists.  The
# backward is the PaddleBox fused gradient: a weighted scatter-add
# (segment-sum) straight into the dW buffer, one pass, no [B, S, D]
# cotangent.  TPU grid steps run sequentially, so the read-modify-write
# scatter is race-free by construction.
# ---------------------------------------------------------------------------

_EMB_VMEM_BYTES = 4 << 20     # the table block must fit VMEM; bigger tables
                              # take the XLA take/segment_sum fallback


def fused_embedding_pool_supported(w, ids) -> bool:
    """Static gate for the pallas path: lane-aligned row dim and 2-d ids.
    Tables that fit one VMEM block take the whole-table kernels below;
    bigger tables take the streaming variants (grid over row blocks) —
    the old ≤4MB whole-table ceiling is no longer a gate."""
    if w.ndim != 2 or ids.ndim != 2 or ids.shape[1] == 0:
        return False
    return w.shape[1] % 128 == 0


def _emb_whole_table_ok(w) -> bool:
    v, d = w.shape
    return v * d * w.dtype.itemsize <= _EMB_VMEM_BYTES


def _emb_stream_block_rows(d, itemsize) -> int:
    """Largest fp32-sublane-aligned row count whose [block_rows, d] block
    fits the VMEM budget."""
    return max(8, (_EMB_VMEM_BYTES // (d * itemsize)) // 8 * 8)


def _gather_pool_kernel(ids_ref, wgt_ref, w_ref, o_ref, *, n_ids):
    d = o_ref.shape[-1]

    def body(j, acc):
        idx = ids_ref[0, j]
        row = pl.load(w_ref, (pl.dslice(idx, 1), pl.dslice(0, d)))
        return acc + row * wgt_ref[0, j]

    o_ref[:] = jax.lax.fori_loop(
        0, n_ids, body, jnp.zeros((1, d), w_ref.dtype))


def fused_embedding_pool_tpu(w, ids, wgt):
    """out[i] = sum_j w[ids[i, j]] * wgt[i, j] — gather and pool in one
    kernel.  ``wgt`` carries the pooling semantics (0 for padding_idx /
    beyond-length positions, 1/len for mean pooling).  Tables beyond the
    VMEM block budget take the streaming variant."""
    if not _emb_whole_table_ok(w):
        return fused_embedding_pool_stream_tpu(w, ids, wgt)
    b, s = ids.shape
    v, d = w.shape
    return pl.pallas_call(
        functools.partial(_gather_pool_kernel, n_ids=s),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, s), lambda i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, s), lambda i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((v, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, d), w.dtype),
    )(ids.astype(jnp.int32), wgt.astype(w.dtype), w)


def _gather_pool_stream_kernel(ids_ref, wgt_ref, w_ref, o_ref, *, n_ids,
                               block_rows):
    """Streaming forward: grid (batch, row_blocks), one [block_rows, d]
    table slab resident per step.  Each step folds the ids that land in
    its slab into the pooled row; out-of-slab positions contribute an
    exact 0 (weight masked), so out[i] = sum over slabs of partials —
    the pooled sum regrouped by slab (sum pooling reassociated; each
    term is still w[id] * wgt computed once)."""
    k = pl.program_id(1)
    d = o_ref.shape[-1]
    base = k * block_rows

    @pl.when(k == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    def body(j, acc):
        local = ids_ref[0, j] - base
        in_blk = jnp.logical_and(local >= 0, local < block_rows)
        row = pl.load(w_ref, (pl.dslice(jnp.where(in_blk, local, 0), 1),
                              pl.dslice(0, d)))
        wj = jnp.where(in_blk, wgt_ref[0, j],
                       jnp.zeros((), w_ref.dtype))
        return acc + row * wj

    o_ref[:] += jax.lax.fori_loop(
        0, n_ids, body, jnp.zeros((1, d), w_ref.dtype))


def fused_embedding_pool_stream_tpu(w, ids, wgt, block_rows=None):
    """Streaming gather+pool for tables bigger than one VMEM block: the
    table streams through VMEM as [block_rows, d] slabs (row-block grid
    axis, innermost so each output row accumulates over consecutive
    steps), ids/weights ride in SMEM.  HBM-size tables never hit the old
    ≤4MB whole-table ceiling."""
    b, s = ids.shape
    v, d = w.shape
    br = int(block_rows or _emb_stream_block_rows(d, w.dtype.itemsize))
    vp = -(-v // br) * br
    if vp != v:                  # pad to a whole number of slabs; padding
        w = jnp.pad(w, ((0, vp - v), (0, 0)))      # rows are never indexed
    return pl.pallas_call(
        functools.partial(_gather_pool_stream_kernel, n_ids=s,
                          block_rows=br),
        grid=(b, vp // br),
        in_specs=[pl.BlockSpec((1, s), lambda i, k: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, s), lambda i, k: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((br, d), lambda i, k: (k, 0))],
        out_specs=pl.BlockSpec((1, d), lambda i, k: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, d), w.dtype),
    )(ids.astype(jnp.int32), wgt.astype(w.dtype), w)


def _scatter_grad_kernel(ids_ref, wgt_ref, g_ref, o_ref, *, n_ids):
    d = o_ref.shape[-1]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    def body(j, _):
        idx = ids_ref[0, j]
        cur = pl.load(o_ref, (pl.dslice(idx, 1), pl.dslice(0, d)))
        pl.store(o_ref, (pl.dslice(idx, 1), pl.dslice(0, d)),
                 cur + g_ref[:] * wgt_ref[0, j])
        return 0

    jax.lax.fori_loop(0, n_ids, body, 0)


def embedding_pool_grad_tpu(g, ids, wgt, vocab):
    """dW[ids[i, j]] += g[i] * wgt[i, j]: the fused gradient scatter-add.
    The whole dW buffer is the (sequentially-gridded) output block, so the
    accumulation never materialises per-position cotangent rows.  dW
    buffers beyond the VMEM block budget take the streaming variant."""
    b, s = ids.shape
    d = g.shape[-1]
    if vocab * d * g.dtype.itemsize > _EMB_VMEM_BYTES:
        return embedding_pool_grad_stream_tpu(g, ids, wgt, vocab)
    return pl.pallas_call(
        functools.partial(_scatter_grad_kernel, n_ids=s),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, s), lambda i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, s), lambda i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, d), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((vocab, d), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((vocab, d), g.dtype),
    )(ids.astype(jnp.int32), wgt.astype(g.dtype), g)


def _scatter_grad_stream_kernel(ids_ref, wgt_ref, g_ref, o_ref, *, n_ids,
                                block_rows):
    """Streaming backward: grid (row_blocks, batch) — row-block axis
    OUTERMOST so each [block_rows, d] dW slab stays resident while every
    batch row scatters into it (consecutive revisits, the canonical
    accumulation shape).  For any given table row the contributions
    land in the same (i, j) order as the whole-table kernel, so the two
    paths are bit-identical, not just close."""
    k = pl.program_id(0)
    i = pl.program_id(1)
    d = o_ref.shape[-1]
    base = k * block_rows

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    def body(j, _):
        local = ids_ref[0, j] - base
        in_blk = jnp.logical_and(local >= 0, local < block_rows)
        safe = jnp.where(in_blk, local, 0)
        cur = pl.load(o_ref, (pl.dslice(safe, 1), pl.dslice(0, d)))
        wj = jnp.where(in_blk, wgt_ref[0, j], jnp.zeros((), g_ref.dtype))
        # out-of-slab ids write row 0 back unchanged (wj == 0)
        pl.store(o_ref, (pl.dslice(safe, 1), pl.dslice(0, d)),
                 cur + g_ref[:] * wj)
        return 0

    jax.lax.fori_loop(0, n_ids, body, 0)


def embedding_pool_grad_stream_tpu(g, ids, wgt, vocab, block_rows=None):
    """Streaming scatter-add gradient for vocabularies whose dW exceeds
    one VMEM block: dW is built slab by slab ([block_rows, d] output
    grid axis), each slab swept once over the batch."""
    b, s = ids.shape
    d = g.shape[-1]
    br = int(block_rows or _emb_stream_block_rows(d, g.dtype.itemsize))
    vp = -(-vocab // br) * br
    dw = pl.pallas_call(
        functools.partial(_scatter_grad_stream_kernel, n_ids=s,
                          block_rows=br),
        grid=(vp // br, b),
        in_specs=[pl.BlockSpec((1, s), lambda k, i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, s), lambda k, i: (i, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, d), lambda k, i: (i, 0))],
        out_specs=pl.BlockSpec((br, d), lambda k, i: (k, 0)),
        out_shape=jax.ShapeDtypeStruct((vp, d), g.dtype),
    )(ids.astype(jnp.int32), wgt.astype(g.dtype), g)
    return dw[:vocab] if vp != vocab else dw


# ---------------------------------------------------------------------------
# bucketed optimizer updates: one elementwise kernel over a flattened
# same-(dtype, family, PartitionSpec) parameter bucket (fuse_optimizer pass).
# The math is element-for-element identical to the per-param update ops —
# concatenation changes layout, never values — so the rewrite bit-compares
# against N separate launches.  lr_t rides in as a per-element tensor
# because Adam's bias correction is a per-PARAM scalar (each param owns its
# beta-pow accumulators); broadcasting it outside the kernel keeps the
# kernel a pure 5-in/3-out elementwise map.
# ---------------------------------------------------------------------------

def _fused_adam_kernel(p_ref, g_ref, m_ref, v_ref, lrt_ref,
                       po_ref, mo_ref, vo_ref, *, beta1, beta2, eps):
    g = g_ref[:]
    m_new = beta1 * m_ref[:] + (1.0 - beta1) * g
    v_new = beta2 * v_ref[:] + (1.0 - beta2) * jnp.square(g)
    po_ref[:] = p_ref[:] - lrt_ref[:] * m_new / (jnp.sqrt(v_new) + eps)
    mo_ref[:] = m_new
    vo_ref[:] = v_new


def fused_adam_tpu(p2d, g2d, m2d, v2d, lrt2d, beta1, beta2, eps):
    """(p, m, v) updated over a padded [rows, lanes] bucket in one launch."""
    m, n = p2d.shape
    bm = _pick_block_rows(m, n)
    spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    outs = pl.pallas_call(
        functools.partial(_fused_adam_kernel, beta1=float(beta1),
                          beta2=float(beta2), eps=float(eps)),
        grid=(m // bm,),
        in_specs=[spec] * 5,
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((m, n), p2d.dtype)] * 3,
    )(p2d, g2d, m2d, v2d, lrt2d)
    return outs


def _fused_momentum_kernel(lr_ref, p_ref, g_ref, v_ref, po_ref, vo_ref, *,
                           mu, use_nesterov, l2_decay):
    g = g_ref[:]
    p = p_ref[:]
    if l2_decay:
        g = g + p.dtype.type(l2_decay) * p
    v_new = p.dtype.type(mu) * v_ref[:] + g
    lr = lr_ref[0]
    if use_nesterov:
        po_ref[:] = p - lr * (g + p.dtype.type(mu) * v_new)
    else:
        po_ref[:] = p - lr * v_new
    vo_ref[:] = v_new


def fused_momentum_tpu(p2d, g2d, v2d, lr, mu, use_nesterov, l2_decay):
    m, n = p2d.shape
    bm = _pick_block_rows(m, n)
    spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    return pl.pallas_call(
        functools.partial(_fused_momentum_kernel, mu=float(mu),
                          use_nesterov=bool(use_nesterov),
                          l2_decay=float(l2_decay)),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [spec] * 3,
        out_specs=[spec] * 2,
        out_shape=[jax.ShapeDtypeStruct((m, n), p2d.dtype)] * 2,
    )(lr.reshape(1).astype(p2d.dtype), p2d, g2d, v2d)


def fused_dropout_tpu(x, key, rate, upscale_in_train):
    """Dropout with on-core PRNG mask, regenerated in backward.

    Returns (out, mask_fn) where mask_fn() materialises the uint8 keep-mask
    with a second kernel from the same seed — called only if the consumer
    actually fetches the Mask output, so XLA DCEs it otherwise.
    """
    seed = _seed_from_key(key)
    shape = x.shape
    n = shape[-1]
    x2d = x.reshape(-1, n)
    out = _fused_dropout(x2d, seed, float(rate), bool(upscale_in_train))

    def mask_fn():
        m = x2d.shape[0]
        bm = _pick_block_rows(m, n)
        mask = pl.pallas_call(
            functools.partial(_dropout_mask_kernel,
                              threshold=_threshold_for(float(rate))),
            grid=(m // bm,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        )(seed)
        return mask.reshape(shape)

    return out.reshape(shape), mask_fn
