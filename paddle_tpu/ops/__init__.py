"""Op lowering library — importing this package registers every op.

The registry (registry.py) is the analog of the reference's static-init
REGISTER_OPERATOR tables (paddle/fluid/framework/op_registry.h).
"""
from . import registry
from .registry import register_op, get_op, has_op, all_ops, LoweringContext

from . import math            # noqa: F401  elementwise/activation/matmul
from . import manipulation    # noqa: F401  reshape/gather/creation
from . import reduction       # noqa: F401  reductions/topk/sort
from . import nn_ops          # noqa: F401  conv/pool/norm/dropout
from . import loss_ops        # noqa: F401  losses/metrics
from . import random_ops      # noqa: F401  RNG ops
from . import optimizer_ops   # noqa: F401  optimizer updates + AMP
from . import collective_ops  # noqa: F401  ICI collectives
from . import attention       # noqa: F401  fused attention (Pallas/XLA)
from . import ctr_ops         # noqa: F401  CTR/ads ops (qingshui family)
from . import quant_ops       # noqa: F401  fake-quant / dequant (QAT, PTQ)
from . import rnn_ops         # noqa: F401  lstm/gru/cudnn_lstm scans
from . import nlp_ops         # noqa: F401  CRF/CTC/beam-search/NCE
from . import detection_ops   # noqa: F401  RoI/anchor/proposal/deformable
from . import misc_ops        # noqa: F401  optimizer variants + stragglers
from . import sequence_extra  # noqa: F401  sequence_conv/pad/slice/...
from . import plumbing_ops    # noqa: F401  tensor arrays/LoD/queues/save-load
from . import fused_extra_ops # noqa: F401  nn tail + fused compositions
from . import catalog_tail_ops # noqa: F401  fc/py_func/rnn/detection tail

# stamp per-op exclusion reasons onto non-differentiable registrations
# (test_op_grads_auto.py enforces full coverage of the audit)
from .nondiff_reasons import apply_reasons as _apply_nondiff_reasons
_apply_nondiff_reasons()

def builtin_ops():
    """The framework's op catalog: everything registered except user
    custom-op plugins, which load_op_library marks OpDef.custom and the
    catalog/grad-audit sweeps exclude."""
    from .registry import _OP_REGISTRY
    return frozenset(t for t, d in _OP_REGISTRY.items() if not d.custom)
