"""Sequence ops beyond the core set in fluid/layers/sequence_lod.py.

Reference (SURVEY §2.5 `sequence_ops/` 6.2K LoC): sequence_conv_op.cc,
sequence_expand_as_op.cc, sequence_pad_op.cc, sequence_unpad_op.cc,
sequence_slice_op.cc, sequence_erase_op.cc, sequence_enumerate_op.cc,
sequence_scatter_op.cc.

Padded-batch convention (see sequence_lod.py): [B, T, D] + Length [B].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, wide_int


def _mask(length, t):
    return jnp.arange(t)[None, :] < length.reshape(-1, 1)


@register_op("sequence_conv", nondiff_inputs=("Length", "PaddingData"))
def _sequence_conv(ins, attrs, ctx):
    """sequence_conv_op.cc: context window of contextLength rows starting at
    contextStart, contracted with Filter [ctx*D, OutD]."""
    x = ins["X"][0]                           # [B, T, D]
    filt = ins["Filter"][0]
    start = attrs.get("contextStart", -1)
    clen = attrs.get("contextLength", 3)
    b, t, d = x.shape
    cols = []
    for k in range(clen):
        off = start + k
        if off < 0:
            pad = jnp.zeros((b, min(-off, t), d), x.dtype)
            piece = jnp.concatenate([pad, x[:, :t + off]], axis=1) \
                if t + off > 0 else jnp.zeros_like(x)
        elif off > 0:
            pad = jnp.zeros((b, min(off, t), d), x.dtype)
            piece = jnp.concatenate([x[:, off:], pad], axis=1)
        else:
            piece = x
        cols.append(piece)
    ctx_rows = jnp.concatenate(cols, axis=-1)   # [B, T, ctx*D]
    if ins.get("Length"):
        m = _mask(ins["Length"][0], t).astype(x.dtype)[..., None]
        ctx_rows = ctx_rows * m
    return {"Out": [ctx_rows @ filt]}


@register_op("sequence_expand_as", nondiff_inputs=("Y", "Length"))
def _sequence_expand_as(ins, attrs, ctx):
    """sequence_expand_as_op.cc padded analog: each row of X [B, D] is
    broadcast over Y's time axis [B, T, ...]."""
    x, y = ins["X"][0], ins["Y"][0]
    t = y.shape[1]
    if x.ndim == 2:
        out = jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[1]))
    else:
        out = jnp.broadcast_to(x[:, :1], x.shape[:1] + (t,) + x.shape[2:])
    return {"Out": [out]}


@register_op("sequence_pad", nondiff_inputs=("PadValue", "Length"))
def _sequence_pad(ins, attrs, ctx):
    """sequence_pad_op.cc: already-padded layout makes this a copy +
    padded_length trim/extend with PadValue."""
    x = ins["X"][0]
    if ins.get("PadValue"):
        pv = ins["PadValue"][0]
        # scalar OR one-time-step shaped (sequence_pad_op.cc supports
        # both); a step-shaped value broadcasts over batch and time
        pad_value = pv.reshape(()) if pv.size == 1 else pv
    else:
        pad_value = 0.0
    padded_len = attrs.get("padded_length", -1)
    t = x.shape[1]
    length = (ins["Length"][0].astype(jnp.int32).reshape(-1)
              if ins.get("Length") else jnp.full((x.shape[0],), t))
    target = t if padded_len < 0 else padded_len
    if target > t:
        fill = jnp.full((x.shape[0], target - t) + x.shape[2:], pad_value,
                        x.dtype)
        x = jnp.concatenate([x, fill], axis=1)
    else:
        x = x[:, :target]
    m = _mask(length, target)
    shape = m.shape + (1,) * (x.ndim - 2)
    out = jnp.where(m.reshape(shape), x, pad_value)
    return {"Out": [out], "Length": [length.astype(wide_int())]}


@register_op("sequence_unpad", nondiff_inputs=("Length",))
def _sequence_unpad(ins, attrs, ctx):
    """sequence_unpad_op.cc: padded layout keeps the tensor; padding zeroed
    (ragged outputs are masks, not LoD)."""
    x = ins["X"][0]
    length = ins["Length"][0].astype(jnp.int32).reshape(-1)
    m = _mask(length, x.shape[1])
    return {"Out": [jnp.where(m.reshape(m.shape + (1,) * (x.ndim - 2)),
                              x, 0.0)]}


@register_op("sequence_slice", nondiff_inputs=("Offset", "Length"))
def _sequence_slice(ins, attrs, ctx):
    """sequence_slice_op.cc: per-sequence [offset, offset+length) slice,
    left-aligned into the padded output."""
    x = ins["X"][0]
    off = ins["Offset"][0].astype(jnp.int32).reshape(-1)
    ln = ins["Length"][0].astype(jnp.int32).reshape(-1)
    b, t = x.shape[:2]
    idx = off[:, None] + jnp.arange(t)[None, :]
    idx = jnp.clip(idx, 0, t - 1)
    g = jnp.take_along_axis(x, idx.reshape(b, t, *(1,) * (x.ndim - 2)),
                            axis=1)
    m = _mask(ln, t)
    return {"Out": [jnp.where(m.reshape(m.shape + (1,) * (x.ndim - 2)),
                              g, 0.0)]}


@register_op("sequence_erase", differentiable=False)
def _sequence_erase(ins, attrs, ctx):
    """sequence_erase_op.cc: drop tokens in `tokens`, left-compact, pad 0."""
    x = ins["X"][0].astype(jnp.int32)
    tokens = jnp.asarray(attrs.get("tokens", []), jnp.int32)
    keep = ~(x[..., None] == tokens[None, None, :]).any(-1) \
        if tokens.size else jnp.ones_like(x, bool)
    order = jnp.argsort(~keep, axis=1, stable=True)
    vals = jnp.take_along_axis(jnp.where(keep, x, 0), order, axis=1)
    lens = keep.sum(axis=1)
    vals = jnp.where(jnp.arange(x.shape[1])[None] < lens[:, None], vals, 0)
    return {"Out": [vals.astype(wide_int())],
            "Length": [lens.astype(wide_int())]}


@register_op("sequence_enumerate", differentiable=False)
def _sequence_enumerate(ins, attrs, ctx):
    """sequence_enumerate_op.cc: win_len-gram sliding windows, pad_value
    beyond the end."""
    x = ins["X"][0].astype(jnp.int32)
    win = attrs.get("win_size", 2)
    pad = attrs.get("pad_value", 0)
    b, t = x.shape[:2]
    xe = jnp.concatenate(
        [x, jnp.full((b, win - 1), pad, x.dtype)], axis=1)
    out = jnp.stack([xe[:, k:k + t] for k in range(win)], axis=-1)
    return {"Out": [out.astype(wide_int())]}


@register_op("sequence_scatter", nondiff_inputs=("Ids",))
def _sequence_scatter(ins, attrs, ctx):
    """sequence_scatter_op.cc: scatter-add Updates rows into X at Ids along
    the flattened batch-time axis."""
    x = ins["X"][0]
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    upd = ins["Updates"][0].reshape(ids.shape[0], -1)
    flat = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x.reshape(-1, 1)
    out = flat.at[ids].add(upd.astype(flat.dtype))
    return {"Out": [out.reshape(x.shape)]}
