"""Operator registry: the TPU-native analog of fluid's op/kernel registry.

Reference design: paddle/fluid/framework/op_registry.h:256-304 registers an
OperatorBase subclass plus per-device kernels per op type, and a GradOpDescMaker
(grad_op_desc_maker.h) that emits grad OpDescs.  Here an op is a *pure JAX
lowering rule* `fn(inputs, attrs, ctx) -> outputs`; the whole block is compiled
by XLA (executor.py), so there is no per-device kernel dispatch — XLA is the
kernel library.  Gradients come from one generic `jax.vjp`-based grad lowering
(see backward.py), replacing 676 hand-written GradOpMakers; ops may still
register a custom grad when vjp semantics are wrong (e.g. straight-through).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

# inputs/outputs are Dict[slot_name, List[jax.Array]] mirroring OpDesc's named
# variadic slots (framework.proto:74 `OpDesc.Var { parameter, arguments }`).
LoweringFn = Callable[..., Dict[str, Any]]


def wide_int():
    """The dtype for index/length/id outputs the reference declares int64.

    An EXPLICIT choice, not a silent truncation: int64 when jax x64 mode is
    on (FLAGS_enable_x64), else int32 — requesting jnp.int64 with x64 off
    would produce int32 anyway, plus a per-call TracerWarning.  True 64-bit
    id paths (feasigns) are guarded separately: the executor refuses
    silently-truncating int64 feeds (executor.py check_feed_width), the
    assign_value lowering rejects over-range int64 constants, and the PS
    tier keeps ids host-side in real int64.  Single source of truth for the
    64->32 policy is framework.device_dtype.
    """
    import jax.numpy as jnp
    from ..fluid.framework import device_dtype
    return jnp.int64 if device_dtype("int64") == "int64" else jnp.int32


@dataclasses.dataclass
class OpDef:
    type: str
    fn: LoweringFn                       # fn(ins, attrs, ctx) -> outs
    # slots that are never differentiated (int indices, seeds, masks...)
    nondiff_inputs: Sequence[str] = ()
    # outputs that carry no cotangent (int outputs, saved state)
    nondiff_outputs: Sequence[str] = ()
    differentiable: bool = True          # False: treated as leaf (optimizer ops)
    # why a differentiable=False op is excluded from the grad sweep
    # (populated from ops/nondiff_reasons.py; test_op_grads_auto enforces
    # that every non-differentiable op carries one)
    nondiff_reason: Optional[str] = None
    stateful_rng: bool = False           # needs a PRNG key (dropout, *_random)
    custom_grad: Optional[Callable] = None  # (ins, outs, out_grads, attrs, ctx) -> in_grads
    # optional shape/dtype inference for IR bookkeeping (advisory; XLA retraces)
    infer: Optional[Callable] = None
    # True for user plugin ops (load_op_library) — outside the framework's
    # catalog/grad-audit contract
    custom: bool = False


_OP_REGISTRY: Dict[str, OpDef] = {}


def register_op(type: str, fn: LoweringFn = None, **kwargs):
    """Register a lowering rule. Usable as decorator or direct call."""
    def deco(f):
        if type in _OP_REGISTRY:
            raise ValueError(f"op '{type}' already registered")
        _OP_REGISTRY[type] = OpDef(type=type, fn=f, **kwargs)
        return f
    if fn is not None:
        return deco(fn)
    return deco


def get_op(type: str) -> OpDef:
    if type not in _OP_REGISTRY:
        raise NotImplementedError(
            f"op '{type}' has no TPU lowering rule registered "
            f"({len(_OP_REGISTRY)} ops available)")
    return _OP_REGISTRY[type]


def has_op(type: str) -> bool:
    return type in _OP_REGISTRY


def all_ops() -> List[str]:
    return sorted(_OP_REGISTRY)


class LoweringContext:
    """Per-compilation context handed to lowering rules.

    Carries the PRNG base key (random ops fold in their static `op_seed` attr
    so forward and vjp-recomputed forward see identical randomness), the mesh
    axis registry for collective ops (parallel/mesh.py), and mode flags.
    """

    def __init__(self, base_key=None, mesh_axes=None, is_test=False):
        self.base_key = base_key
        self.mesh_axes = mesh_axes or {}   # ring_id -> mesh axis name(s)
        self.is_test = is_test
        self.p2p = {}                      # ring_id -> in-flight send_v2 value
        # shape bucketing (fluid/compile_cache.py): when the executor pads
        # feeds up to a bucket edge, batch_padded is the static padded
        # leading dim and batch_valid the traced true batch size; batch
        # reductions consult batch_mask() to stay padding-invariant
        self.batch_valid = None
        self.batch_padded = None
        # per-op IR hint set by run_block_ops: False when the op's primary
        # input is a persistable var (parameter/state — its rows are never
        # the batch, even if dim 0 aliases the bucket size), True when the
        # IR marks it batch-major (-1 leading dim), None when unknown
        self.cur_op_batch_major = None

    def batch_mask(self, dim0):
        """Row-validity mask (bool[dim0]) when ``dim0`` is the bucketed
        batch axis under shape bucketing, else None.  The IR hint
        (cur_op_batch_major) vetoes masking for persistable inputs; for
        unknown provenance the dim0-equality heuristic applies — pick
        bucket edges disjoint from model dims if that ever aliases
        (docs/performance.md)."""
        if self.batch_valid is None or self.batch_padded != dim0 \
                or self.cur_op_batch_major is False:
            return None
        import jax.numpy as jnp
        return jnp.arange(int(dim0)) < self.batch_valid

    def key_for(self, op_seed: int):
        import jax
        if self.base_key is None:
            import jax.random as jr
            return jr.PRNGKey(int(op_seed))
        return jax.random.fold_in(self.base_key, int(op_seed))

    def axis_for_ring(self, ring_id: int):
        return self.mesh_axes.get(int(ring_id), None)
