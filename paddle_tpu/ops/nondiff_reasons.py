"""Per-op exclusion reasons for the grad sweep.

Reference bar: op_test.py:1324 check_grad runs on nearly every op; ops it
does NOT run on are excluded for a stated structural reason (int outputs,
optimizer updates, RNG).  This catalog records that reason for every
`differentiable=False` lowering so the sweep's accounting test
(test_op_grads_auto.py) can enforce: an op is either finite-difference
checked, explicitly SKIPped with a reason, or non-differentiable with a
recorded category — nothing slips through silently.

Categories, not freeform strings: each op maps to one of the structural
reasons below, which keeps the audit greppable and a new op forced into a
conscious choice.
"""
from __future__ import annotations

from .registry import _OP_REGISTRY

CATEGORIES = {
    "optimizer": "parameter-update rule: consumes grads, produces new "
                 "state; has no cotangent of its own (reference excludes "
                 "all optimizer ops from check_grad)",
    "int_output": "integer/boolean/index outputs only — the map is "
                  "piecewise constant, d/dx == 0 everywhere it exists",
    "rng": "output is a random sample; no deterministic input->output "
           "map to differentiate (seeds are not differentiable)",
    "metric": "evaluation metric (counts/ratios over comparisons): "
              "piecewise-constant by construction",
    "comm": "communication/process plumbing: init handles, barriers, "
            "queue/stream sync; moves bytes, computes nothing",
    "plumbing": "graph/scope/IO plumbing (save/load, arrays, lod "
                "bookkeeping, var lifecycle): no numeric surface",
    "constant": "materialises a constant/shape-derived tensor from attrs; "
                "no tensor input to differentiate",
    "detection_post": "detection post-processing (NMS, anchor/proposal "
                      "generation, target assignment): argmax/threshold "
                      "selection logic, piecewise-constant outputs",
    "quant_int": "integer quantize/dequantize storage transform; the "
                 "trainable STE variants (fake_quantize_*) are separate "
                 "ops handled by the sweep's SKIPS with STE reasons",
    "sparse_tier": "host-side sparse-table storage op (pull/push/init/"
                   "save): gradient flows through the paired device-side "
                   "lookup op, not the storage plane",
    "grad_plumbing": "the generic grad op itself — it IS the derivative",
    "selection": "discrete search/decode (beam search, decoding): index "
                 "outputs drive the result",
}

# op -> category key
REASONS = {
    # -- optimizer updates ---------------------------------------------------
    **{op: "optimizer" for op in (
        "sgd", "momentum", "adam", "adamw", "adamax", "adagrad", "adadelta",
        "decayed_adagrad", "rmsprop", "ftrl", "lamb", "lars_momentum",
        "dgc_momentum", "dpsgd", "proximal_adagrad", "proximal_gd",
        "localsgd_select", "average_accumulates", "check_finite_and_unscale",
        "update_loss_scaling", "lookup_sparse_table_fuse_adam",
        "lookup_sparse_table_fuse_sgd",
        "fused_adam", "fused_lamb", "fused_momentum")},
    # -- integer / boolean / index outputs ----------------------------------
    **{op: "int_output" for op in (
        "equal", "equal_all", "not_equal", "less_than", "less_equal",
        "greater_than", "greater_equal", "allclose", "isfinite",
        "isfinite_v2", "isinf_v2", "isnan_v2", "logical_and", "logical_or",
        "logical_not", "logical_xor", "arg_max", "arg_min", "reduce_all",
        "reduce_any", "shape", "size", "rank", "one_hot", "one_hot_v2",
        "where_index", "unique", "unique_with_counts", "shard_index",
        "masked_select", "sequence_mask", "sequence_enumerate",
        "sequence_erase", "histogram", "similarity_focus", "hash",
        "filter_by_instag", "tdm_child", "edit_distance", "ctc_align",
        "chunk_eval", "crf_decoding", "gather_tree", "is_empty",
        "split_ids", "merge_ids")},
    # -- RNG samplers --------------------------------------------------------
    **{op: "rng" for op in (
        "uniform_random", "gaussian_random", "truncated_gaussian_random",
        "randint", "randperm", "bernoulli", "multinomial", "sampling_id",
        "random_crop", "seed", "gaussian_random_batch_size_like",
        "uniform_random_batch_size_like", "tdm_sampler")},
    # -- metrics -------------------------------------------------------------
    **{op: "metric" for op in (
        "accuracy", "auc", "precision_recall", "mean_iou", "detection_map",
        "positive_negative_pair")},
    # -- communication / process plumbing ------------------------------------
    **{op: "comm" for op in (
        "barrier", "c_allreduce_coalesced", "c_comm_init",
        "c_comm_init_all", "shard_constraint",
        "c_comm_init_multitrainer", "c_gen_nccl_id", "gen_nccl_id",
        "c_sync_calc_stream", "c_sync_comm_stream", "send_v2", "recv_v2",
        "partial_send", "enqueue", "dequeue", "queue_generator")},
    # -- graph / scope / IO plumbing -----------------------------------------
    **{op: "plumbing" for op in (
        "assert", "save", "load", "save_combine", "load_combine",
        "delete_var", "fake_init", "coalesce_tensor", "slice_multi_tensor",
        "write_to_array", "read_from_array", "array_to_lod_tensor",
        "lod_tensor_to_array", "tensor_array_to_tensor",
        "lod_array_length", "lod_rank_table", "max_sequence_len",
        "reorder_lod_tensor_by_rank", "split_selected_rows", "py_func",
        "recurrent", "store_q_value", "push_dense")},
    # -- constant materialisers ----------------------------------------------
    **{op: "constant" for op in (
        "fill_constant", "fill_constant_batch_size_like", "fill",
        "assign_value", "eye", "diag", "diag_v2", "linspace", "range",
        "empty")},
    # -- detection post-processing -------------------------------------------
    **{op: "detection_post" for op in (
        "multiclass_nms", "multiclass_nms2", "matrix_nms", "locality_aware_nms", "prior_box",
        "density_prior_box", "anchor_generator", "bipartite_match",
        "generate_proposals", "generate_proposals_v2",
        "generate_proposal_labels", "generate_mask_labels",
        "mine_hard_examples", "rpn_target_assign", "target_assign",
        "collect_fpn_proposals", "distribute_fpn_proposals",
        "retinanet_detection_output", "polygon_box_transform")},
    # -- integer quant storage ----------------------------------------------
    **{op: "quant_int" for op in (
        "quantize", "dequantize", "requantize", "dequantize_abs_max",
        "dequantize_log")},
    # -- host sparse-table tier ----------------------------------------------
    **{op: "sparse_tier" for op in (
        "distributed_lookup_table", "lookup_sparse_table_init",
        "lookup_sparse_table_read", "lookup_sparse_table_write",
        "lookup_sparse_table_grad_split", "lookup_sparse_table_merge",
        "push_box_sparse", "pull_box_extended_sparse", "pull_sparse_v2")},
    # -- discrete search / decode -------------------------------------------
    **{op: "selection" for op in ("beam_search", "beam_search_decode")},
    # -- autodiff internals --------------------------------------------------
    "generic_grad": "grad_plumbing",
}


def apply_reasons():
    """Stamp nondiff_reason onto every registered non-differentiable op.
    Unknown ops are left unstamped — the sweep's accounting test fails on
    them, forcing a conscious category choice for new ops."""
    for op, cat in REASONS.items():
        d = _OP_REGISTRY.get(op)
        if d is not None and not d.differentiable:
            d.nondiff_reason = f"{cat}: {CATEGORIES[cat]}"
