"""Collective communication ops — ICI/XLA collectives replace NCCL rings.

Reference: paddle/fluid/operators/collective/ (SURVEY §2.5): c_allreduce_{sum,
max,min,prod}, c_allgather, c_reducescatter, c_broadcast, c_reduce_*,
send_v2/recv_v2, barrier, plus bootstrap ops c_gen_nccl_id/c_comm_init.  The
reference pattern `ring_id -> NCCLCommContext::Instance().Get(rid)` becomes
`ring_id -> mesh axis name` via LoweringContext.mesh_axes (registered by
parallel/mesh.py).  Under shard_map over a jax.sharding.Mesh these lower to
lax.psum/all_gather/ppermute on ICI; outside any mesh they are identity
(single-replica), mirroring how a 1-GPU NCCL ring degenerates.

Bootstrap ops (c_gen_nccl_id, c_comm_init*, c_sync_*_stream) are no-ops: XLA
programs are globally scheduled and jax.distributed.initialize is the
gen_nccl_id analog (SURVEY §5 comm-backend note).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..fluid import trace
from .registry import register_op


def axis_size(axis_name):
    """lax.axis_size across jax versions: 0.4.x lacks it; psum of the
    literal 1 is the portable spelling (statically folded to the axis
    size, no collective launched)."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def _axis(ctx, attrs):
    return ctx.axis_for_ring(attrs.get("ring_id", 0))


def _annotate(op_type, fn):
    """Observability-plane comm annotation: spans (cat="comm") carry the
    ring -> mesh-axis resolution so a timeline shows WHICH collective on
    WHICH axis, nested inside the generic per-op dispatch span.  At
    trace/lowering time only (XLA owns the device schedule); one boolean
    when the plane is off."""
    def lower(ins, attrs, ctx):
        if not trace.enabled():
            return fn(ins, attrs, ctx)
        t0 = trace.now()
        out = fn(ins, attrs, ctx)
        trace.complete(op_type, t0, cat="comm",
                       args={"ring_id": int(attrs.get("ring_id", 0)),
                             "axis": _axis(ctx, attrs)})
        return out
    lower.__name__ = f"comm_{op_type}"
    return lower


def register_comm_op(type, fn=None, **kwargs):
    """register_op for data-moving collectives: same contract, comm-span
    annotated (bootstrap no-ops stay unannotated)."""
    if fn is not None:
        return register_op(type, _annotate(type, fn), **kwargs)

    def deco(f):
        register_op(type, _annotate(type, f), **kwargs)
        return f
    return deco


def _note_dispatched(n: int = 1):
    """The other half of the implied-vs-dispatched split
    (parallel/sharding.py): a collective that lowers to a REAL psum/
    pmean launch counts here, once per compile (trace time).  The
    sharding plane's ``shard_collectives`` rewrite counts into
    ``sharding.collectives_implied`` instead — a sharded executable
    gates on this counter staying at zero."""
    trace.metrics().counter("sharding.collectives_dispatched").inc(n)


def _allreduce(reducer):
    def lower(ins, attrs, ctx):
        x = ins["X"][0]
        axis = _axis(ctx, attrs)
        if axis is None:
            return {"Out": [x]}
        _note_dispatched()
        return {"Out": [reducer(x, axis_name=axis)]}
    return lower


@register_comm_op("c_allreduce_coalesced", differentiable=False)
def _c_allreduce_coalesced(ins, attrs, ctx):
    """Bucketed gradient all-reduce (fuse_all_reduce_op_pass +
    coalesce_tensor analog), emitted by the coalesce_allreduce graph pass:
    N small per-grad launches become ONE flattened psum/pmean over the
    concatenated bucket, then the slices go back to their own shapes and
    dtypes.  Mixed dtypes ride in the promoted dtype and are cast back —
    same-or-better precision than per-tensor reduction."""
    xs = list(ins["X"])
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": xs}
    _note_dispatched(len(xs))
    reducer = lax.pmean if attrs.get("reduce", "sum") == "avg" else lax.psum
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    red = reducer(flat, axis_name=axis)
    outs, off = [], 0
    for x in xs:
        n = int(x.size)
        outs.append(red[off:off + n].reshape(x.shape).astype(x.dtype))
        off += n
    return {"Out": outs}


register_comm_op("c_allreduce_sum", _allreduce(lax.psum))
register_comm_op("c_allreduce_max", _allreduce(lax.pmax))
register_comm_op("c_allreduce_min", _allreduce(lax.pmin))
register_comm_op("c_allreduce_prod", _allreduce(
    lambda x, axis_name: jnp.exp(lax.psum(jnp.log(x), axis_name=axis_name))))
register_comm_op("allreduce", _allreduce(lax.psum))  # legacy operators/nccl era
register_comm_op("c_allreduce_avg", _allreduce(lax.pmean))


@register_comm_op("c_allgather")
def _c_allgather(ins, attrs, ctx):
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    g = lax.all_gather(x, axis_name=axis)           # (n, ...) leading axis
    return {"Out": [g.reshape((-1,) + x.shape[1:])]}


@register_comm_op("c_reducescatter")
def _c_reducescatter(ins, attrs, ctx):
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    return {"Out": [lax.psum_scatter(x, axis_name=axis, tiled=True)]}


@register_comm_op("c_broadcast")
def _c_broadcast(ins, attrs, ctx):
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    root = attrs.get("root", 0)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": [lax.psum(masked, axis_name=axis)]}


def _c_reduce(reducer):
    # result only meaningful on root; we produce it everywhere (SPMD)
    def lower(ins, attrs, ctx):
        x = ins["X"][0]
        axis = _axis(ctx, attrs)
        if axis is None:
            return {"Out": [x]}
        return {"Out": [reducer(x, axis_name=axis)]}
    return lower


register_comm_op("c_reduce_sum", _c_reduce(lax.psum))
register_comm_op("c_reduce_max", _c_reduce(lax.pmax))
register_comm_op("c_reduce_min", _c_reduce(lax.pmin))
register_comm_op("c_reduce_prod", _c_reduce(
    lambda x, axis_name: jnp.exp(lax.psum(jnp.log(x), axis_name=axis_name))))


@register_comm_op("c_scatter")
def _c_scatter(ins, attrs, ctx):
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    n = axis_size(axis)
    idx = lax.axis_index(axis)
    chunks = x.reshape((n, -1) + x.shape[1:])
    return {"Out": [lax.dynamic_index_in_dim(chunks, idx, keepdims=False)]}


@register_comm_op("c_concat")
def _c_concat(ins, attrs, ctx):
    # tensor-parallel all-gather along last dim (model-parallel fc output)
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    return {"Out": [lax.all_gather(x, axis_name=axis, axis=x.ndim - 1,
                                   tiled=True)]}


@register_comm_op("c_split")
def _c_split(ins, attrs, ctx):
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    n = axis_size(axis)
    idx = lax.axis_index(axis)
    step = x.shape[-1] // n
    return {"Out": [lax.dynamic_slice_in_dim(x, idx * step, step, x.ndim - 1)]}


@register_op("c_identity")
def _c_identity(ins, attrs, ctx):
    # TP forward-identity/backward-allreduce boundary op
    return {"Out": [ins["X"][0]]}


@register_op("shard_constraint", differentiable=False)
def _shard_constraint(ins, attrs, ctx):
    """PartitionSpec-implied communication (parallel/sharding.py): the
    ``shard_collectives`` pass rewrites ring-id allreduce ops into this
    marker.  Under a sharded compile (``ctx.mesh`` set by the executor's
    plan path) each value is pinned to the attr's spec — replicated ``[]``
    for a rewritten gradient allreduce — and GSPMD inserts the reduce the
    constraint implies; with no live mesh it is identity, so the
    rewritten program still runs unsharded (the per-op fallback)."""
    xs = list(ins["X"])
    mesh = getattr(ctx, "mesh", None)
    if mesh is None:
        return {"Out": xs}
    from jax.sharding import NamedSharding, PartitionSpec
    spec = PartitionSpec(*(attrs.get("spec") or ()))
    sh = NamedSharding(mesh, spec)
    return {"Out": [lax.with_sharding_constraint(x, sh) for x in xs]}


@register_comm_op("send_v2", differentiable=False)
def _send_v2(ins, attrs, ctx):
    """p2p pipeline send (reference: operators/collective/send_v2_op.cc).

    SPMD model: every rank executes both sides of the pair, so send stores
    its value in the compilation-scoped mailbox and the matching recv_v2
    applies the ring ppermute — together they are exactly the NCCL
    ncclSend/ncclRecv pair, but scheduled by XLA.  The pipeline composite
    path (parallel/pipeline.py) threads boundaries natively and doesn't
    need these ops."""
    ctx.p2p[int(attrs.get("ring_id", 0))] = ins["X"][0]
    return {}


@register_comm_op("recv_v2", differentiable=False)
def _recv_v2(ins, attrs, ctx):
    ring = int(attrs.get("ring_id", 0))
    if ring not in ctx.p2p:
        raise ValueError(
            f"recv_v2(ring_id={ring}) has no matching send_v2 earlier in "
            f"the block — p2p ops must be paired (send stores, recv shifts)")
    x = ctx.p2p.pop(ring)   # consume: a second recv needs its own send
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    n = axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return {"Out": [lax.ppermute(x, axis, perm)]}


@register_comm_op("partial_send", differentiable=False)
def _partial_send(ins, attrs, ctx):
    return {}


@register_comm_op("c_ppermute")
def _c_ppermute(ins, attrs, ctx):
    """Native ring shift (no reference analog — exposed for ring attention
    and pipeline p2p).  attrs: shift (+1 = to next rank)."""
    x = ins["X"][0]
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    n = axis_size(axis)
    shift = attrs.get("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": [lax.ppermute(x, axis, perm)]}


@register_comm_op("barrier", differentiable=False)
def _barrier(ins, attrs, ctx):
    x = ins["X"][0] if ins.get("X") else jnp.zeros((1,), jnp.float32)
    axis = _axis(ctx, attrs)
    if axis is None:
        return {"Out": [x]}
    # a psum over a zero token is a full synchronisation point
    return {"Out": [x + lax.psum(jnp.zeros_like(x), axis_name=axis) * 0]}


@register_op("c_sync_calc_stream", differentiable=False)
def _sync_calc(ins, attrs, ctx):
    return {"Out": [ins["X"][0]]}


@register_op("c_sync_comm_stream", differentiable=False)
def _sync_comm(ins, attrs, ctx):
    return {"Out": list(ins["X"])}


for _t in ("c_gen_nccl_id", "c_comm_init", "c_comm_init_all",
           "c_comm_init_multitrainer", "gen_nccl_id"):
    register_op(_t, lambda ins, attrs, ctx: {}, differentiable=False)


@register_op("c_embedding", nondiff_inputs=("Ids",))
def _c_embedding(ins, attrs, ctx):
    """Vocab-sharded (tensor-parallel) embedding: each rank owns rows
    [start_index, start_index + local_vocab); out-of-range ids contribute
    zeros which the following c_allreduce_sum fills in."""
    w, ids = ins["W"][0], ins["Ids"][0].astype(jnp.int32)
    start = attrs.get("start_index", 0)
    local = ids - start
    valid = (local >= 0) & (local < w.shape[0])
    out = jnp.take(w, jnp.clip(local, 0, w.shape[0] - 1), axis=0)
    return {"Out": [jnp.where(valid[..., None], out, 0.0)]}
