"""Tensor manipulation + creation lowering rules.

Reference: paddle/fluid/operators/{reshape_op,transpose_op,concat_op,split_op,
slice_op,gather_op,scatter_op,stack_op,expand_op,...}.cc (SURVEY A.1
"Tensor manipulation" group).  Gather/scatter over int indices keep indices in
the nondiff slot so the generic vjp grad never differentiates them.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, wide_int


def _x(ins, slot="X", i=0):
    return ins[slot][i]


@register_op("reshape2", nondiff_inputs=("Shape", "ShapeTensor"))
def _reshape2(ins, attrs, ctx):
    x = _x(ins)
    if ins.get("Shape"):
        shape = [int(s) for s in np.asarray(ins["Shape"][0])]
    else:
        shape = list(attrs["shape"])
    # fluid semantics: 0 means copy input dim at that position
    shape = [x.shape[i] if d == 0 else d for i, d in enumerate(shape)]
    return {"Out": [x.reshape(shape)], "XShape": [jnp.zeros((0,), x.dtype)]}


register_op("reshape", lambda ins, a, c:
            {"Out": [_x(ins).reshape([_x(ins).shape[i] if d == 0 else d
                                      for i, d in enumerate(a["shape"])])]})


@register_op("transpose2")
def _transpose2(ins, attrs, ctx):
    x = _x(ins)
    return {"Out": [jnp.transpose(x, attrs["axis"])],
            "XShape": [jnp.zeros((0,), x.dtype)]}


register_op("transpose", lambda ins, a, c:
            {"Out": [jnp.transpose(_x(ins), a["axis"])]})


@register_op("flatten2")
def _flatten2(ins, attrs, ctx):
    x = _x(ins)
    ax = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    return {"Out": [x.reshape((lead, -1))], "XShape": [jnp.zeros((0,), x.dtype)]}


register_op("flatten", lambda ins, a, c: {"Out": [
    _x(ins).reshape((int(np.prod(_x(ins).shape[:a.get("axis", 1)])) or 1, -1))]})


@register_op("flatten_contiguous_range")
def _flatten_range(ins, attrs, ctx):
    x = _x(ins)
    start, stop = attrs.get("start_axis", 1), attrs.get("stop_axis", -1)
    nd = x.ndim
    start, stop = start % nd, stop % nd
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return {"Out": [x.reshape(shape)], "XShape": [jnp.zeros((0,), x.dtype)]}


@register_op("squeeze2")
def _squeeze2(ins, attrs, ctx):
    x = _x(ins)
    axes = attrs.get("axes", [])
    axes = [a % x.ndim for a in axes] or [i for i, d in enumerate(x.shape) if d == 1]
    out = x.reshape([d for i, d in enumerate(x.shape)
                     if not (i in axes and d == 1)])
    return {"Out": [out], "XShape": [jnp.zeros((0,), x.dtype)]}


register_op("squeeze", lambda ins, a, c: {"Out": [jnp.squeeze(
    _x(ins), tuple(a.get("axes")) if a.get("axes") else None)]})


@register_op("unsqueeze2")
def _unsqueeze2(ins, attrs, ctx):
    x = _x(ins)
    out = x
    for ax in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, ax if ax >= 0 else ax + out.ndim + 1)
    return {"Out": [out], "XShape": [jnp.zeros((0,), x.dtype)]}


register_op("unsqueeze", lambda ins, a, c: {"Out": [
    jnp.expand_dims(_x(ins), tuple(a["axes"]))]})


@register_op("concat")
def _concat(ins, attrs, ctx):
    axis = ins["AxisTensor"][0] if ins.get("AxisTensor") else attrs.get("axis", 0)
    return {"Out": [jnp.concatenate(ins["X"], axis=int(axis))]}


@register_op("split")
def _split(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        total, neg = 0, -1
        sections = list(sections)
        for i, s in enumerate(sections):
            if s < 0:
                neg = i
            else:
                total += s
        if neg >= 0:
            sections[neg] = x.shape[axis] - total
        idx = np.cumsum(sections[:-1])
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": outs}


@register_op("stack")
def _stack(ins, attrs, ctx):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    n = attrs.get("num", x.shape[axis])
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis)]}


@register_op("unbind")
def _unbind(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    return {"Out": [jnp.squeeze(s, axis)
                    for s in jnp.split(x, x.shape[axis], axis=axis)]}


@register_op("slice", nondiff_inputs=("StartsTensor", "EndsTensor"))
def _slice(ins, attrs, ctx):
    x = _x(ins, "Input")
    axes = attrs["axes"]
    starts = list(attrs.get("starts", []))
    ends = list(attrs.get("ends", []))
    slices = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        dim = x.shape[ax]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        slices[ax] = slice(s, e)
    out = x[tuple(slices)]
    for ax in sorted(attrs.get("decrease_axis", []) or [], reverse=True):
        out = jnp.squeeze(out, ax)
    return {"Out": [out]}


@register_op("strided_slice")
def _strided_slice(ins, attrs, ctx):
    x = _x(ins, "Input")
    slices = [slice(None)] * x.ndim
    for ax, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                            attrs["strides"]):
        slices[ax] = slice(s, e, st)
    return {"Out": [x[tuple(slices)]]}


@register_op("gather", nondiff_inputs=("Index",))
def _gather(ins, attrs, ctx):
    x, idx = _x(ins), _x(ins, "Index")
    axis = int(attrs.get("axis", 0))
    return {"Out": [jnp.take(x, idx.astype(jnp.int32), axis=axis)]}


@register_op("gather_nd", nondiff_inputs=("Index",))
def _gather_nd(ins, attrs, ctx):
    x, idx = _x(ins), _x(ins, "Index")
    k = idx.shape[-1]
    out = x[tuple(jnp.moveaxis(idx, -1, 0).astype(jnp.int32))]
    return {"Out": [out]}


@register_op("scatter", nondiff_inputs=("Ids",))
def _scatter(ins, attrs, ctx):
    x, ids, upd = _x(ins), _x(ins, "Ids"), _x(ins, "Updates")
    ids = ids.astype(jnp.int32).reshape(-1)
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(upd)]}
    return {"Out": [x.at[ids].set(0.).at[ids].add(upd)]}


@register_op("scatter_nd_add", nondiff_inputs=("Index",))
def _scatter_nd_add(ins, attrs, ctx):
    x, idx, upd = _x(ins), _x(ins, "Index"), _x(ins, "Updates")
    return {"Out": [x.at[tuple(jnp.moveaxis(idx, -1, 0).astype(jnp.int32))]
                    .add(upd)]}


@register_op("index_select", nondiff_inputs=("Index",))
def _index_select(ins, attrs, ctx):
    return {"Out": [jnp.take(_x(ins), _x(ins, "Index").astype(jnp.int32),
                             axis=attrs.get("dim", 0))]}


@register_op("index_sample", nondiff_inputs=("Index",))
def _index_sample(ins, attrs, ctx):
    x, idx = _x(ins), _x(ins, "Index").astype(jnp.int32)
    return {"Out": [jnp.take_along_axis(x, idx, axis=1)]}


@register_op("masked_select", differentiable=False)
def _masked_select(ins, attrs, ctx):
    # dynamic output shape — only usable outside jit (dygraph eager path)
    return {"Y": [_x(ins)[_x(ins, "Mask").astype(bool)]]}


@register_op("where", nondiff_inputs=("Condition",))
def _where(ins, attrs, ctx):
    return {"Out": [jnp.where(_x(ins, "Condition").astype(bool),
                              _x(ins), _x(ins, "Y"))]}


register_op("where_index", lambda ins, a, c:
            {"Out": [jnp.argwhere(_x(ins, "Condition"))]},
            differentiable=False)


@register_op("expand")
def _expand(ins, attrs, ctx):
    x = _x(ins)
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_v2")
def _expand_v2(ins, attrs, ctx):
    x = _x(ins)
    shape = list(attrs["shape"])
    # -1 keeps input dim; leading new dims broadcast
    nd = len(shape)
    xs = (1,) * (nd - x.ndim) + x.shape
    shape = [xs[i] if d == -1 else d for i, d in enumerate(shape)]
    return {"Out": [jnp.broadcast_to(x.reshape(xs), shape)]}


@register_op("expand_as_v2")
def _expand_as(ins, attrs, ctx):
    x = _x(ins)
    shape = attrs.get("target_shape") or ins["Y"][0].shape
    xs = (1,) * (len(shape) - x.ndim) + x.shape
    return {"Out": [jnp.broadcast_to(x.reshape(xs), shape)]}


@register_op("tile")
def _tile(ins, attrs, ctx):
    return {"Out": [jnp.tile(_x(ins), attrs["repeat_times"])]}


@register_op("flip")
def _flip(ins, attrs, ctx):
    return {"Out": [jnp.flip(_x(ins), tuple(attrs["axis"]))]}


@register_op("roll")
def _roll(ins, attrs, ctx):
    axis = attrs.get("axis", None)
    return {"Out": [jnp.roll(_x(ins), attrs["shifts"],
                             tuple(axis) if axis else None)]}


@register_op("reverse")
def _reverse(ins, attrs, ctx):
    return {"Out": [jnp.flip(_x(ins), tuple(attrs["axis"]))]}


@register_op("pad")
def _pad(ins, attrs, ctx):
    x = _x(ins)
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}


@register_op("pad2d")
def _pad2d(ins, attrs, ctx):
    x = _x(ins)
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pads = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": [jnp.pad(x, pads, mode=jmode)]}


@register_op("pad3d")
def _pad3d(ins, attrs, ctx):
    x = _x(ins)
    p = attrs["paddings"]  # [left, right, top, bottom, front, back]
    fmt = attrs.get("data_format", "NCDHW")
    if fmt == "NCDHW":
        pads = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    else:
        pads = [(0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]), (0, 0)]
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("value", 0.0))]}
    return {"Out": [jnp.pad(x, pads, mode={"reflect": "reflect",
                                           "replicate": "edge",
                                           "circular": "wrap"}[mode])]}


@register_op("cast")
def _cast(ins, attrs, ctx):
    from ..fluid.framework import device_dtype
    return {"Out": [_x(ins).astype(device_dtype(attrs["out_dtype"]))]}


@register_op("fill_constant", differentiable=False)
def _fill_constant(ins, attrs, ctx):
    from ..fluid.framework import device_dtype
    shape = attrs.get("shape", [])
    if ins.get("ShapeTensor"):
        shape = [int(d) for d in np.asarray(ins["ShapeTensor"][0])]
    dtype = device_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dtype)]}


@register_op("fill_any_like")
def _fill_any_like(ins, attrs, ctx):
    from ..fluid.framework import device_dtype
    dt = attrs.get("dtype", None)
    x = _x(ins)
    dtype = device_dtype(dt) if dt not in (None, -1) else x.dtype
    return {"Out": [jnp.full_like(x, attrs.get("value", 0.0), dtype=dtype)]}


register_op("fill_zeros_like", lambda ins, a, c:
            {"Out": [jnp.zeros_like(_x(ins))]})


@register_op("assign")
def _assign(ins, attrs, ctx):
    return {"Out": [_x(ins)]}


@register_op("assign_value", differentiable=False)
def _assign_value(ins, attrs, ctx):
    from ..fluid.framework import device_dtype
    dtype = device_dtype(attrs.get("dtype", "float32"))
    for key in ("fp32_values", "int32_values", "int64_values", "bool_values"):
        if attrs.get(key):
            vals = attrs[key]
            break
    else:
        vals = []
    arr = np.array(vals).reshape(attrs["shape"])
    if dtype == "int32" and arr.dtype == np.int64 and arr.size \
            and (arr.max() > np.iinfo(np.int32).max
                 or arr.min() < np.iinfo(np.int32).min):
        # same contract as the executor's feed guard: 64-bit ids must not
        # wrap silently when x64 is off
        raise ValueError(
            "assign_value carries int64 constants exceeding int32 range "
            "and x64 is off; enable FLAGS_enable_x64 to keep them exact")
    return {"Out": [jnp.asarray(arr, dtype=dtype)]}


register_op("shape", lambda ins, a, c:
            {"Out": [jnp.asarray(ins["Input"][0].shape, jnp.int32)]},
            differentiable=False)
register_op("size", lambda ins, a, c:
            {"Out": [jnp.asarray(ins["Input"][0].size, wide_int())]},
            differentiable=False)
register_op("rank", lambda ins, a, c:
            {"Out": [jnp.asarray(ins["Input"][0].ndim, jnp.int32)]},
            differentiable=False)


@register_op("eye", differentiable=False)
def _eye(ins, attrs, ctx):
    from ..fluid.framework import device_dtype
    n = attrs["num_rows"]
    m = attrs.get("num_columns", n)
    return {"Out": [jnp.eye(n, m if m > 0 else n,
                            dtype=device_dtype(attrs.get("dtype", "float32")))]}


@register_op("linspace", differentiable=False)
def _linspace(ins, attrs, ctx):
    start, stop, num = ins["Start"][0], ins["Stop"][0], ins["Num"][0]
    from ..fluid.framework import device_dtype
    dtype = device_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.linspace(start.reshape(()), stop.reshape(()),
                                 int(num), dtype=dtype)]}


@register_op("range", differentiable=False)
def _range(ins, attrs, ctx):
    s, e, st = ins["Start"][0], ins["End"][0], ins["Step"][0]
    return {"Out": [jnp.arange(s.reshape(()), e.reshape(()), st.reshape(()))]}


@register_op("increment")
def _increment(ins, attrs, ctx):
    return {"Out": [_x(ins) + attrs.get("step", 1.0)]}


@register_op("one_hot", nondiff_inputs=("X",), differentiable=False)
def _one_hot(ins, attrs, ctx):
    x = _x(ins).astype(jnp.int32)
    depth = attrs["depth"]
    out = jax.nn.one_hot(x.reshape(x.shape[:-1]) if x.shape[-1] == 1 else x,
                         depth, dtype=jnp.float32)
    return {"Out": [out]}


register_op("one_hot_v2", lambda ins, a, c: {"Out": [
    jax.nn.one_hot(_x(ins).astype(jnp.int32), a["depth"], dtype=jnp.float32)]},
    differentiable=False)


@register_op("diag_v2", differentiable=False)
def _diag_v2(ins, attrs, ctx):
    x = _x(ins)
    k = attrs.get("offset", 0)
    out = jnp.diag(x, k=k)
    pad = attrs.get("padding_value", 0)
    if x.ndim == 1 and pad:
        # off-diagonal fill (tensor/creation.py diag padding_value)
        mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=k)
        out = jnp.where(mask, out, jnp.asarray(pad, out.dtype))
    return {"Out": [out]}


@register_op("diag_embed")
def _diag_embed(ins, attrs, ctx):
    x = _x(ins, "Input")
    return {"Out": [jnp.apply_along_axis(jnp.diag, -1, x)] if x.ndim > 1
            else [jnp.diag(x, k=attrs.get("offset", 0))]}


@register_op("meshgrid")
def _meshgrid(ins, attrs, ctx):
    return {"Out": list(jnp.meshgrid(*ins["X"], indexing="ij"))}


@register_op("tril_triu")
def _tril_triu(ins, attrs, ctx):
    x = _x(ins)
    k = attrs.get("diagonal", 0)
    f = jnp.tril if attrs.get("lower", True) else jnp.triu
    return {"Out": [f(x, k)]}


@register_op("unique_with_counts", differentiable=False)
def _unique_with_counts(ins, attrs, ctx):
    x = _x(ins)
    u, idx, counts = np.unique(np.asarray(x), return_inverse=True,
                               return_counts=True)
    return {"Out": [jnp.asarray(u)], "Index": [jnp.asarray(idx)],
            "Count": [jnp.asarray(counts)]}


@register_op("shard_index", differentiable=False)
def _shard_index(ins, attrs, ctx):
    x = _x(ins)
    index_num, nshards = attrs["index_num"], attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore = attrs.get("ignore_value", -1)
    size = (index_num + nshards - 1) // nshards
    mask = (x // size) == shard_id
    return {"Out": [jnp.where(mask, x % size, ignore)]}


@register_op("lookup_table_v2", nondiff_inputs=("Ids",))
def _lookup_table_v2(ins, attrs, ctx):
    """Embedding (operators/lookup_table_v2_op).  SelectedRows sparse grad
    becomes a dense vjp-scatter; XLA turns one-hot matmul / take into an
    efficient dynamic-gather on TPU."""
    w, ids = _x(ins, "W"), _x(ins, "Ids").astype(jnp.int32)
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": [out]}


@register_op("lookup_table", nondiff_inputs=("Ids",))
def _lookup_table(ins, attrs, ctx):
    w, ids = _x(ins, "W"), _x(ins, "Ids").astype(jnp.int32)
    ids = ids.reshape(ids.shape[:-1]) if ids.shape[-1] == 1 else ids
    out = jnp.take(w, ids, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": [out]}


@register_op("space_to_depth")
def _space_to_depth(ins, attrs, ctx):
    x = _x(ins)
    b = attrs["blocksize"]
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return {"Out": [x.reshape(n, c * b * b, h // b, w // b)]}


@register_op("pixel_shuffle")
def _pixel_shuffle(ins, attrs, ctx):
    x = _x(ins)
    r = attrs["upscale_factor"]
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return {"Out": [x.reshape(n, c // (r * r), h * r, w * r)]}


@register_op("unfold")
def _unfold(ins, attrs, ctx):
    x = _x(ins)
    ks = attrs["kernel_sizes"]
    st = attrs.get("strides", [1, 1])
    pd = attrs.get("paddings", [0, 0, 0, 0])
    dl = attrs.get("dilations", [1, 1])
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (pd[0], pd[2] if len(pd) > 2 else pd[0]),
                    (pd[1], pd[3] if len(pd) > 3 else pd[1])])
    patches = jax.lax.conv_general_dilated_patches(
        x, ks, st, "VALID", rhs_dilation=dl,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n2, ckk, oh, ow = patches.shape
    return {"Y": [patches.reshape(n2, ckk, oh * ow)]}


@register_op("fill_constant_batch_size_like", differentiable=False)
def _fill_constant_bsl(ins, attrs, ctx):
    from ..fluid.framework import device_dtype
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[attrs.get("input_dim_idx", 0)]
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0),
                             dtype=device_dtype(attrs.get("dtype", "float32")))]}
