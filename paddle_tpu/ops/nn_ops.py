"""NN ops: conv/pool/norm/softmax/dropout/interpolate lowering rules.

Reference: paddle/fluid/operators/{conv_op,conv_cudnn_op,pool_op,batch_norm_op,
layer_norm_op,group_norm_op,instance_norm_op,softmax_op,dropout_op,
interpolate_op,...}.cc|cu (SURVEY §2.5).  Convs lower to
lax.conv_general_dilated which XLA tiles onto the MXU; there is no cuDNN-style
algo search — the compiler picks the schedule.  batch_norm keeps fluid's
running-stat update semantics by emitting the updated moving stats as extra
outputs that the executor writes back to the scope (the analog of fluid's
in-place MeanOut/VarianceOut aliasing).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _x(ins, slot="X", i=0):
    return ins[slot][i]


def _conv_pad(padding, algorithm, ndim_sp):
    if algorithm == "SAME":
        return "SAME"
    if algorithm == "VALID":
        return "VALID"
    p = list(padding)
    if len(p) == ndim_sp:
        return [(pi, pi) for pi in p]
    if len(p) == 2 * ndim_sp:
        return [(p[2 * i], p[2 * i + 1]) for i in range(ndim_sp)]
    return [(p[0], p[0])] * ndim_sp


@register_op("conv2d")
def _conv2d(ins, attrs, ctx):
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    fmt = attrs.get("data_format", "NCHW")
    if fmt in ("NCHW", "AnyLayout"):
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        dn = ("NHWC", "OIHW", "NHWC")
    groups = attrs.get("groups", 1)
    out = lax.conv_general_dilated(
        x, w,
        window_strides=attrs.get("strides", [1, 1]),
        padding=_conv_pad(attrs.get("paddings", [0, 0]),
                          attrs.get("padding_algorithm", "EXPLICIT"), 2),
        rhs_dilation=attrs.get("dilations", [1, 1]),
        dimension_numbers=dn,
        feature_group_count=groups)
    # no preferred_element_type: XLA already accumulates bf16 convs in f32
    # on the MXU, and conv_general_dilated's transpose rule rejects mixed
    # operand dtypes when the cotangent arrives in the accumulation type
    return {"Output": [out.astype(x.dtype)]}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ins, attrs, ctx):
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    fmt = attrs.get("data_format", "NCHW")
    nhwc = fmt == "NHWC"
    groups = attrs.get("groups", x.shape[-1] if nhwc else x.shape[1])
    dn = ("NHWC", "OIHW", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    out = lax.conv_general_dilated(
        x, w,
        window_strides=attrs.get("strides", [1, 1]),
        padding=_conv_pad(attrs.get("paddings", [0, 0]),
                          attrs.get("padding_algorithm", "EXPLICIT"), 2),
        rhs_dilation=attrs.get("dilations", [1, 1]),
        dimension_numbers=dn,
        feature_group_count=groups)
    return {"Output": [out.astype(x.dtype)]}


@register_op("conv2d_transpose")
def _conv2d_transpose(ins, attrs, ctx):
    """Transposed conv (conv2d_transpose_op.cc) as a dilated conv: fluid
    filter layout (C_in, C_out/groups, kh, kw) maps directly onto IOHW with
    the kernel spatially flipped, lhs_dilation = strides, and padding
    (k_eff - 1 - p) — the exact adjoint of the conv2d lowering (verified by
    <conv(x,w), y> == <x, convT(y,w)> in test_op_grads_auto)."""
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    s = list(attrs.get("strides", [1, 1]))
    d = list(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    kh = (w.shape[2] - 1) * d[0] + 1
    kw = (w.shape[3] - 1) * d[1] + 1
    p = list(attrs.get("paddings", [0, 0]))
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    if algo == "VALID":
        p = [0, 0, 0, 0]
    elif algo == "SAME":
        # out == in * stride exactly: total crop per dim = k_eff - s,
        # remainder on the high side (may be negative when k < s)
        p = [(kh - s[0]) // 2, (kh - s[0]) - (kh - s[0]) // 2,
             (kw - s[1]) // 2, (kw - s[1]) - (kw - s[1]) // 2]
    if len(p) == 2:                    # symmetric [ph, pw]
        p = [p[0], p[0], p[1], p[1]]
    # default out = (in-1)*s - (p_lo+p_hi) + k_eff; output_size (absolute)
    # or output_padding (extra) add rows on the high edge for stride > 1
    extra = [0, 0]
    osize = attrs.get("output_size")
    opad = attrs.get("output_padding")
    if osize:
        dh = (x.shape[2] - 1) * s[0] - p[0] - p[1] + kh
        dw = (x.shape[3] - 1) * s[1] - p[2] - p[3] + kw
        extra = [int(osize[0]) - dh, int(osize[1]) - dw]
    elif opad:
        extra = [int(opad[0]), int(opad[1])]
    pad = [(kh - 1 - p[0], kh - 1 - p[1] + extra[0]),
           (kw - 1 - p[2], kw - 1 - p[3] + extra[1])]
    if groups > 1:
        # (Cin, Cout/g, kh, kw) -> grouped IOHW expects I = Cin/g per group
        # with O totalling Cout: split, run per group, concat (XLA fuses)
        xs = jnp.split(x, groups, axis=1)
        ws = jnp.split(w, groups, axis=0)
        outs = [lax.conv_general_dilated(
            xi, jnp.flip(wi, (2, 3)), window_strides=(1, 1), padding=pad,
            lhs_dilation=s, rhs_dilation=d,
            dimension_numbers=("NCHW", "IOHW", "NCHW"))
            for xi, wi in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=1)
    else:
        out = lax.conv_general_dilated(
            x, jnp.flip(w, (2, 3)), window_strides=(1, 1), padding=pad,
            lhs_dilation=s, rhs_dilation=d,
            dimension_numbers=("NCHW", "IOHW", "NCHW"))
    return {"Output": [out.astype(x.dtype)]}


@register_op("conv3d")
def _conv3d(ins, attrs, ctx):
    x, w = _x(ins, "Input"), _x(ins, "Filter")
    out = lax.conv_general_dilated(
        x, w, attrs.get("strides", [1, 1, 1]),
        _conv_pad(attrs.get("paddings", [0, 0, 0]),
                  attrs.get("padding_algorithm", "EXPLICIT"), 3),
        rhs_dilation=attrs.get("dilations", [1, 1, 1]),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1))
    return {"Output": [out.astype(x.dtype)]}


@register_op("pool2d")
def _pool2d(ins, attrs, ctx):
    x = _x(ins)
    ptype = attrs.get("pooling_type", "max")
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    if attrs.get("global_pooling", False):
        axis = (1, 2) if nhwc else (2, 3)
        out = (jnp.max(x, axis, keepdims=True) if ptype == "max"
               else jnp.mean(x, axis, keepdims=True))
        return {"Out": [out]}
    ks = attrs["ksize"]
    st = attrs.get("strides", ks)
    pd = attrs.get("paddings", [0, 0])
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    sp_pads = ([(pd[0], pd[1]), (pd[2], pd[3])] if len(pd) == 4
               else [(pd[0], pd[0]), (pd[1], pd[1])])
    if algo == "SAME":
        pads = "SAME"
    elif nhwc:
        pads = [(0, 0)] + sp_pads + [(0, 0)]
    else:
        pads = [(0, 0), (0, 0)] + sp_pads
    if nhwc:
        dims, strides = (1, ks[0], ks[1], 1), (1, st[0], st[1], 1)
    else:
        dims, strides = (1, 1, ks[0], ks[1]), (1, 1, st[0], st[1])
    if ptype == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if attrs.get("exclusive", True) and pads != "SAME" and any(
                p != (0, 0) for p in (pads if isinstance(pads, list) else [])):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
            out = summed / counts
        else:
            out = summed / (ks[0] * ks[1])
    return {"Out": [out]}


@register_op("adaptive_pool2d")
def _adaptive_pool2d(ins, attrs, ctx):
    x = _x(ins)
    oh, ow = attrs["ksize"] if "ksize" in attrs else attrs["output_size"]
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, c = x.shape
    else:
        n, c, h, w = x.shape
    # adaptive pooling with uniform bins (exact when divisible; fluid common case)
    assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible dims"
    if nhwc:
        x = x.reshape(n, oh, h // oh, ow, w // ow, c)
        red = (2, 4)
    else:
        x = x.reshape(n, c, oh, h // oh, ow, w // ow)
        red = (3, 5)
    if attrs.get("pooling_type", "avg") == "avg":
        return {"Out": [x.mean(axis=red)]}
    return {"Out": [x.max(axis=red)]}


@register_op("softmax")
def _softmax(ins, attrs, ctx):
    return {"Out": [jax.nn.softmax(_x(ins), axis=attrs.get("axis", -1))]}


@register_op("log_softmax")
def _log_softmax(ins, attrs, ctx):
    return {"Out": [jax.nn.log_softmax(_x(ins), axis=attrs.get("axis", -1))]}


@register_op("dropout", stateful_rng=True, nondiff_outputs=("Mask",))
def _dropout(ins, attrs, ctx):
    x = _x(ins)
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": [out], "Mask": [jnp.ones_like(x, dtype=jnp.uint8)]}
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    if p <= 0.0:
        return {"Out": [x], "Mask": [jnp.ones_like(x, dtype=jnp.uint8)]}
    if p >= 1.0:        # everything dropped; also guards 1/(1-p) below
        return {"Out": [jnp.zeros_like(x)],
                "Mask": [jnp.zeros_like(x, dtype=jnp.uint8)]}
    # TPU: pallas fused kernel — on-core PRNG mask, regenerated (not saved)
    # in backward.  Measured ~15ms/step on BERT-base vs the bernoulli path
    # (mask bytes + uniforms stop round-tripping HBM).
    if jax.default_backend() == "tpu":
        from .pallas_kernels import fused_dropout_supported, fused_dropout_tpu
        if fused_dropout_supported(x):
            out, mask_fn = fused_dropout_tpu(
                x, key, p, upscale_in_train=(impl == "upscale_in_train"))
            # mask comes from a second kernel re-running the same PRNG
            # stream; under jit XLA DCEs it unless Mask is actually fetched
            return {"Out": [out], "Mask": [mask_fn()]}
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        out = jnp.where(keep, x, 0.0).astype(x.dtype)
    return {"Out": [out], "Mask": [keep.astype(jnp.uint8)]}


def _dropout_common(attrs, ctx):
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    upscale = (attrs.get("dropout_implementation", "upscale_in_train")
               == "upscale_in_train")
    return p, is_test, upscale


@register_op("fused_dropout_add", stateful_rng=True)
def _fused_dropout_add_op(ins, attrs, ctx):
    """out = dropout(X) + Residual, one fused kernel on TPU (the residual
    add no longer costs an HBM pass at the pallas boundary); backward
    regenerates the mask.  No reference op of this exact shape — it exists
    because pallas calls are opaque to XLA fusion; the reference's
    analogous fusion tier is operators/fused/fused_dropout_helper.h."""
    x, r = _x(ins), _x(ins, "Residual")
    p, is_test, upscale = _dropout_common(attrs, ctx)
    if p <= 0.0:
        return {"Out": [x + r]}
    if is_test:
        return {"Out": [(x if upscale else x * (1.0 - p)) + r]}
    if p >= 1.0:
        return {"Out": [r]}
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    if jax.default_backend() == "tpu":
        from .pallas_kernels import (fused_dropout_add_tpu,
                                     fused_dropout_supported)
        if fused_dropout_supported(x) and x.shape == r.shape:
            return {"Out": [fused_dropout_add_tpu(x, r, key, p, upscale)]}
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    scale = 1.0 / (1.0 - p) if upscale else 1.0
    return {"Out": [(jnp.where(keep, x * scale, 0.0).astype(x.dtype)
                     + r)]}


@register_op("fused_act_dropout", stateful_rng=True)
def _fused_act_dropout_op(ins, attrs, ctx):
    """out = dropout(act(X)) — the MLP mid-epilogue — fused so the
    activation does not cost its own HBM pass next to the pallas dropout;
    backward fuses act'(x) with the regenerated mask."""
    x = _x(ins)
    act = attrs.get("act", "gelu")
    p, is_test, upscale = _dropout_common(attrs, ctx)
    act_jnp = {"gelu": lambda v: jax.nn.gelu(v, approximate=False),
               "relu": jax.nn.relu}[act]
    if is_test or p <= 0.0:
        a = act_jnp(x)
        return {"Out": [a if upscale or p <= 0.0 else a * (1.0 - p)]}
    if p >= 1.0:
        return {"Out": [jnp.zeros_like(x)]}
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0) or 0))
    if jax.default_backend() == "tpu":
        from .pallas_kernels import (fused_act_dropout_tpu,
                                     fused_dropout_supported)
        if fused_dropout_supported(x):
            return {"Out": [fused_act_dropout_tpu(x, key, p, upscale,
                                                  act)]}
    a = act_jnp(x)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    scale = 1.0 / (1.0 - p) if upscale else 1.0
    return {"Out": [jnp.where(keep, a * scale, 0.0).astype(x.dtype)]}


def _masked_batch_stats(xf, ctx, red_axes):
    """Batch-norm mean/variance over the VALID rows only (shape bucketing:
    executor pads the leading batch dim — zero-padded rows must not drag
    the statistics, or padded-step training diverges from the unpadded
    run).  Returns (mean, var) or None when masking does not apply."""
    from .reduction import masked_batch_reduce
    m = masked_batch_reduce(xf, ctx, red_axes, mean=True)
    if m is None:
        return None
    msq = masked_batch_reduce(jnp.square(xf), ctx, red_axes, mean=True)
    return m, msq - jnp.square(m)


@register_op("batch_norm",
             nondiff_inputs=("Mean", "Variance"),
             nondiff_outputs=("MeanOut", "VarianceOut", "SavedMean",
                              "SavedVariance"))
def _batch_norm(ins, attrs, ctx):
    x = _x(ins)
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    mean, var = _x(ins, "Mean"), _x(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    fmt = attrs.get("data_layout", "NCHW")
    is_test = (attrs.get("is_test", False) or ctx.is_test
               or attrs.get("use_global_stats", False))
    ch_axis = 1 if fmt == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    if is_test:
        m, v = mean, var
        mean_out, var_out = mean, var
    else:
        xf = x.astype(jnp.float32)
        stats = _masked_batch_stats(xf, ctx, red_axes)
        if stats is not None:
            m, v = stats
        else:
            m = jnp.mean(xf, axis=red_axes)
            v = jnp.var(xf, axis=red_axes)
        mean_out = momentum * mean + (1 - momentum) * m
        var_out = momentum * var + (1 - momentum) * v
    inv = lax.rsqrt(v.astype(jnp.float32) + eps)
    out = ((x.astype(jnp.float32) - m.reshape(shape)) * inv.reshape(shape)
           * scale.reshape(shape) + bias.reshape(shape)).astype(x.dtype)
    return {"Y": [out], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [m], "SavedVariance": [inv]}


@register_op("sync_batch_norm",
             nondiff_inputs=("Mean", "Variance"),
             nondiff_outputs=("MeanOut", "VarianceOut", "SavedMean",
                              "SavedVariance"))
def _sync_batch_norm(ins, attrs, ctx):
    """Cross-replica batch norm (operators/sync_batch_norm_op.cu).  Stats are
    psum-reduced over the data-parallel mesh axis when running under
    shard_map; falls back to local stats otherwise."""
    x = _x(ins)
    scale, bias = _x(ins, "Scale"), _x(ins, "Bias")
    mean, var = _x(ins, "Mean"), _x(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    ch_axis = 1
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    axis_name = ctx.axis_for_ring(attrs.get("ring_id", 0)) or ctx.mesh_axes.get("dp")
    if is_test:
        m, v = mean, var
        mean_out, var_out = mean, var
    else:
        xf = x.astype(jnp.float32)
        stats = None if axis_name is not None else \
            _masked_batch_stats(xf, ctx, red_axes)
        if stats is not None:
            m, v = stats
        else:
            m = jnp.mean(xf, axis=red_axes)
            msq = jnp.mean(jnp.square(xf), axis=red_axes)
            if axis_name is not None:
                m = lax.pmean(m, axis_name)
                msq = lax.pmean(msq, axis_name)
            v = msq - jnp.square(m)
        mean_out = momentum * mean + (1 - momentum) * m
        var_out = momentum * var + (1 - momentum) * v
    inv = lax.rsqrt(v + eps)
    out = ((x.astype(jnp.float32) - m.reshape(shape)) * inv.reshape(shape)
           * scale.reshape(shape) + bias.reshape(shape)).astype(x.dtype)
    return {"Y": [out], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [m], "SavedVariance": [inv]}


@register_op("layer_norm", nondiff_outputs=("Mean", "Variance"))
def _layer_norm(ins, attrs, ctx):
    x = _x(ins)
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - m) * lax.rsqrt(v + eps)
    norm_shape = x.shape[begin:]
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(norm_shape)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(norm_shape)
    return {"Y": [out.astype(x.dtype)],
            "Mean": [m.reshape(x.shape[:begin])],
            "Variance": [v.reshape(x.shape[:begin])]}


@register_op("instance_norm", nondiff_outputs=("SavedMean", "SavedVariance"))
def _instance_norm(ins, attrs, ctx):
    x = _x(ins)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)     # f32 stats with bf16 I/O (AMP-gray norm)
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - m) * lax.rsqrt(v + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(shape)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(shape)
    return {"Y": [out.astype(x.dtype)], "SavedMean": [jnp.squeeze(m)],
            "SavedVariance": [jnp.squeeze(lax.rsqrt(v + eps))]}


@register_op("group_norm", nondiff_outputs=("Mean", "Variance"))
def _group_norm(ins, attrs, ctx):
    x = _x(ins)
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[:2]
    xg = x.astype(jnp.float32).reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))     # f32 stats with bf16 I/O (AMP-gray)
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - m) * lax.rsqrt(v + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(shape)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(shape)
    return {"Y": [out.astype(x.dtype)], "Mean": [m.reshape(n, g)],
            "Variance": [v.reshape(n, g)]}


# data_norm (CTR summary-stat normalization) lives in ctr_ops.py: the full
# semantics — persistable stat accumulation, slot show-gating, decay — are
# CTR machinery, not a norm-family variant.


@register_op("l2_normalize")
def _l2_normalize(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


register_op("norm", lambda ins, a, c: _l2_normalize(ins, a, c))


@register_op("lrn")
def _lrn(ins, attrs, ctx):
    x = _x(ins)
    n = attrs.get("n", 5)
    k, alpha, beta = attrs.get("k", 2.0), attrs.get("alpha", 1e-4), attrs.get("beta", 0.75)
    sq = jnp.square(x)
    pad = n // 2
    sq_p = jnp.pad(sq, [(0, 0), (pad, pad), (0, 0), (0, 0)])
    acc = sum(sq_p[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


@register_op("maxout")
def _maxout(ins, attrs, ctx):
    x = _x(ins)
    g = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": [x.reshape(n, c // g, g, h, w).max(axis=2)]}


def _interp_ratio(i, o, align_corners):
    # interpolate_op.h:895-904
    if o <= 1:
        return 0.0
    return (i - 1) / (o - 1) if align_corners else i / o


def _interp_axis_idx(r, o, i, align_flag):
    """Per-axis (lo, hi, frac) source indices for linear interpolation —
    the BilinearInterpolation/TrilinearInterpolation index math."""
    k = jnp.arange(o, dtype=jnp.float32)
    src = r * (k + 0.5) - 0.5 if align_flag else r * k
    lo = jnp.maximum(jnp.floor(src).astype(jnp.int32), 0)
    hi = jnp.minimum(lo + 1, i - 1)
    frac = (jnp.maximum(src, 0.0) - lo) if align_flag else r * k - lo
    return lo, hi, frac


def _interp(ins, attrs, ctx, method):
    x = _x(ins)
    nhwc = attrs.get("data_layout", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, c = x.shape
    else:
        n, c, h, w = x.shape
    oh = attrs.get("out_h", -1)
    ow = attrs.get("out_w", -1)
    if ins.get("OutSize"):
        sz = np.asarray(ins["OutSize"][0])
        oh, ow = int(sz[0]), int(sz[1])
    elif oh <= 0:
        scale = attrs.get("scale", 1.0)
        sh, sw = ((scale[0], scale[1])
                  if isinstance(scale, (list, tuple)) else (scale, scale))
        oh, ow = int(h * sh), int(w * sw)
    xt = x if nhwc else jnp.transpose(x, (0, 2, 3, 1))
    align_corners = attrs.get("align_corners", False)
    align_mode = attrs.get("align_mode", 1)

    def ratio(i, o):
        return _interp_ratio(i, o, align_corners)

    rh, rw = ratio(h, oh), ratio(w, ow)
    if method == "nearest":
        # interpolate_op.h:96-101: trunc(ratio*k + 0.5) with corners,
        # trunc(ratio*k) origin-aligned otherwise — NOT half-pixel
        off = 0.5 if align_corners else 0.0
        iy = jnp.clip((rh * jnp.arange(oh) + off).astype(jnp.int32),
                      0, h - 1)
        ix = jnp.clip((rw * jnp.arange(ow) + off).astype(jnp.int32),
                      0, w - 1)
        out = xt[:, iy][:, :, ix]
    elif method == "bilinear":
        # interpolate_op.h BilinearInterpolation: three alignment modes
        align_flag = (align_mode == 0 and not align_corners)
        y0, y1, fy = _interp_axis_idx(rh, oh, h, align_flag)
        x0, x1, fx = _interp_axis_idx(rw, ow, w, align_flag)
        fy = fy[None, :, None, None]
        fx = fx[None, None, :, None]
        g = lambda yy, xx: xt[:, yy][:, :, xx]
        out = ((1 - fy) * (1 - fx) * g(y0, x0)
               + (1 - fy) * fx * g(y0, x1)
               + fy * (1 - fx) * g(y1, x0)
               + fy * fx * g(y1, x1))
    elif method == "bicubic":
        # interpolate_op.h BicubicInterpolation: Keys kernel A=-0.75,
        # src = ratio*k (corners) or ratio*(k+0.5)-0.5; 4 taps per axis
        # clamped into range
        def cubic_weights(r, o):
            k = jnp.arange(o, dtype=jnp.float32)
            src = r * k if align_corners else r * (k + 0.5) - 0.5
            base = jnp.floor(src).astype(jnp.int32)
            t = src - base
            A = -0.75

            def cc1(v):
                return ((A + 2) * v - (A + 3)) * v * v + 1

            def cc2(v):
                return ((A * v - 5 * A) * v + 8 * A) * v - 4 * A
            w4 = jnp.stack([cc2(t + 1.0), cc1(t), cc1(1.0 - t),
                            cc2(2.0 - t)])            # [4, o]
            return base, w4

        by, wy = cubic_weights(rh, oh)
        bx, wx = cubic_weights(rw, ow)
        out = 0.0
        for i in range(4):
            yy = jnp.clip(by + (i - 1), 0, h - 1)
            row = 0.0
            for j in range(4):
                xx = jnp.clip(bx + (j - 1), 0, w - 1)
                row = row + wx[j][None, None, :, None] \
                    * xt[:, yy][:, :, xx]
            out = out + wy[i][None, :, None, None] * row
    else:
        # every registered 2D method has a reference-exact branch above;
        # a half-pixel jax.image fallback here would silently diverge
        raise ValueError(f"unsupported interpolation method {method!r}")
    out = out.astype(x.dtype)
    return {"Out": [out if nhwc else jnp.transpose(out, (0, 3, 1, 2))]}


def _trilinear_interp(ins, attrs, ctx):
    """interpolate_op.h TrilinearInterpolation: 5D NCDHW/NDHWC with the
    same three alignment modes as bilinear, over d/h/w."""
    x = _x(ins)
    ndhwc = attrs.get("data_layout", "NCDHW") == "NDHWC"
    if ndhwc:
        n, d, h, w, c = x.shape
    else:
        n, c, d, h, w = x.shape
    od = attrs.get("out_d", -1)
    oh = attrs.get("out_h", -1)
    ow = attrs.get("out_w", -1)
    if ins.get("OutSize"):
        sz = np.asarray(ins["OutSize"][0])
        od, oh, ow = int(sz[0]), int(sz[1]), int(sz[2])
    elif od <= 0:
        scale = attrs.get("scale", 1.0)
        sd, sh, sw = (tuple(scale[:3]) if isinstance(scale, (list, tuple))
                      else (scale, scale, scale))
        od, oh, ow = int(d * sd), int(h * sh), int(w * sw)
    align_corners = attrs.get("align_corners", False)
    align_mode = attrs.get("align_mode", 1)
    align_flag = (align_mode == 0 and not align_corners)

    xt = x if ndhwc else jnp.transpose(x, (0, 2, 3, 4, 1))  # N D H W C
    d0, d1, fd = _interp_axis_idx(_interp_ratio(d, od, align_corners),
                                  od, d, align_flag)
    y0, y1, fy = _interp_axis_idx(_interp_ratio(h, oh, align_corners),
                                  oh, h, align_flag)
    x0, x1, fx = _interp_axis_idx(_interp_ratio(w, ow, align_corners),
                                  ow, w, align_flag)
    fd = fd[None, :, None, None, None]
    fy = fy[None, None, :, None, None]
    fx = fx[None, None, None, :, None]
    g = lambda dd, yy, xx: xt[:, dd][:, :, yy][:, :, :, xx]
    out = 0.0
    for wd, dd in ((1 - fd, d0), (fd, d1)):
        for wh, yy in ((1 - fy, y0), (fy, y1)):
            for ww, xx in ((1 - fx, x0), (fx, x1)):
                out = out + wd * wh * ww * g(dd, yy, xx)
    out = out.astype(x.dtype)
    return {"Out": [out if ndhwc else jnp.transpose(out,
                                                    (0, 4, 1, 2, 3))]}


register_op("nearest_interp", lambda ins, a, c: _interp(ins, a, c, "nearest"),
            nondiff_inputs=("OutSize",))
register_op("bilinear_interp", lambda ins, a, c: _interp(ins, a, c, "bilinear"),
            nondiff_inputs=("OutSize",))
register_op("bicubic_interp", lambda ins, a, c: _interp(ins, a, c, "bicubic"),
            nondiff_inputs=("OutSize",))
register_op("trilinear_interp", _trilinear_interp,
            nondiff_inputs=("OutSize",))


@register_op("grid_sampler")
def _grid_sampler(ins, attrs, ctx):
    x, grid = _x(ins), _x(ins, "Grid")
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx, wy = gx - x0, gy - y0
    def sample(yy, xx):
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        return jax.vmap(lambda img, Y, X: img[:, Y, X])(x, yy, xx)
    v00, v01 = sample(y0, x0), sample(y0, x1)
    v10, v11 = sample(y1, x0), sample(y1, x1)
    wx = wx[:, None]
    wy = wy[:, None]
    out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
           + v10 * (1 - wx) * wy + v11 * wx * wy)
    return {"Output": [out]}


@register_op("affine_channel")
def _affine_channel(ins, attrs, ctx):
    x, s, b = _x(ins), _x(ins, "Scale"), _x(ins, "Bias")
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    return {"Out": [x * s.reshape(shape) + b.reshape(shape)]}


@register_op("temporal_shift")
def _temporal_shift(ins, attrs, ctx):
    x = _x(ins)
    t = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // t
    x = x.reshape(n, t, c, h, w)
    c1 = int(c * ratio)
    fwd = jnp.pad(x[:, 1:, :c1], [(0, 0), (0, 1), (0, 0), (0, 0), (0, 0)])
    bwd = jnp.pad(x[:, :-1, c1:2 * c1], [(0, 0), (1, 0), (0, 0), (0, 0), (0, 0)])
    out = jnp.concatenate([fwd, bwd, x[:, :, 2 * c1:]], axis=2)
    return {"Out": [out.reshape(nt, c, h, w)]}
