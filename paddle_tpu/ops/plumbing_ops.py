"""Framework-plumbing ops: tensor arrays, LoD legacy, selected-rows, print/
assert, queues, save/load, memcpy, coalesce — the op-catalog tail that keeps
old fluid programs executable (SURVEY A.1 "Framework plumbing ops").

Reference files: operators/tensor_array_read_write_op.cc (write/read),
array_to_lod_tensor_op.cc, lod_tensor_to_array_op.cc, lod_array_length_op.cc,
max_sequence_len_op.cc, shrink_rnn_memory_op.cc, rnn_memory_helper_op.cc,
split_lod_tensor_op.cc, merge_lod_tensor_op.cc, tensor_array_to_tensor_op.cc,
reorder_lod_tensor_by_rank_op.cc, print_op.cc, assert_op.cc, is_empty_op.cc,
empty_op.cc, fill_op.cc, save_op.cc, load_op.cc, save_combine_op.cc,
load_combine_op.cc, queue_generator_op.cc, enqueue_op.cc, dequeue_op.cc,
coalesce_tensor_op.cc, memcpy_op.cc, merge/split_selected_rows_op.cc,
get_tensor_from_selected_rows_op.cc, uniform_random_batch_size_like_op.cc,
crop_op.cc, crop_tensor_op.cc, expand_as_op.cc, histogram_op.cc,
is_empty_op.cc, slice_multi_tensor (qingshui), fill_op.cc.

TPU-native notes:
* A LoDTensorArray is a Python list in the executor env; array indices must
  resolve statically — the executor constant-folds fill_constant/increment
  chains at the IR level (run_block_ops const_env) and passes the folded
  value via the __index__ attr, and eager/dygraph indices are concrete.
  Dynamic-length recurrence belongs to lax.scan-backed rnn ops instead.
* SelectedRows never exists as a runtime type (grads are dense), so the
  selected-rows ops are dense-semantics equivalents.
* save/load run host-side through io_callback/pure_callback (ordered) —
  the XLA program stays pure while the effect happens on the host.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, wide_int


def _p(ins, slot):
    return ins[slot][0]


def _concrete_index(i, opname, attrs=None):
    if attrs is not None and "__index__" in attrs:
        return int(attrs["__index__"])   # executor constant-folded it
    try:
        return int(np.asarray(i).reshape(-1)[0])
    except Exception as e:                   # noqa: BLE001 — re-raise typed
        raise TypeError(
            f"{opname}: tensor-array index must be trace-time constant "
            f"(the executor folds fill_constant/increment chains; values "
            f"derived from feeds are not static — use the lax.scan-backed "
            f"rnn ops for dynamic recurrence)") from e


# ---------------------------------------------------------------------------
# tensor arrays
# ---------------------------------------------------------------------------

@register_op("write_to_array", differentiable=False)
def _write_to_array(ins, attrs, ctx):
    x, i = _p(ins, "X"), _p(ins, "I")
    arr = list(ins["Array"][0]) if ins.get("Array") else []
    idx = _concrete_index(i, "write_to_array", attrs)
    while len(arr) <= idx:
        arr.append(None)
    arr[idx] = x
    return {"Out": [arr]}


@register_op("read_from_array", differentiable=False)
def _read_from_array(ins, attrs, ctx):
    arr, i = _p(ins, "X"), _p(ins, "I")
    return {"Out": [arr[_concrete_index(i, "read_from_array", attrs)]]}


@register_op("lod_array_length", differentiable=False)
def _lod_array_length(ins, attrs, ctx):
    return {"Out": [jnp.asarray([len(_p(ins, "X"))], wide_int())]}


@register_op("array_to_lod_tensor", differentiable=False)
def _array_to_lod_tensor(ins, attrs, ctx):
    arr = [a for a in _p(ins, "X") if a is not None]
    stacked = jnp.concatenate([jnp.atleast_1d(a) for a in arr], axis=0)
    return {"Out": [stacked]}


@register_op("lod_tensor_to_array", differentiable=False)
def _lod_tensor_to_array(ins, attrs, ctx):
    """Padded-layout reinterpretation: split rows into per-step entries."""
    x = _p(ins, "X")
    return {"Out": [[x[i] for i in range(x.shape[0])]]}


@register_op("tensor_array_to_tensor", differentiable=False)
def _tensor_array_to_tensor(ins, attrs, ctx):
    arr = [a for a in _p(ins, "X") if a is not None]
    axis = attrs.get("axis", 0)
    if attrs.get("use_stack", False):
        out = jnp.stack(arr, axis=axis)
    else:
        out = jnp.concatenate([jnp.atleast_1d(a) for a in arr], axis=axis)
    idx = jnp.asarray([np.shape(a)[axis] if np.ndim(a) else 1
                       for a in arr], wide_int())
    return {"Out": [out], "OutIndex": [idx]}


# ---------------------------------------------------------------------------
# LoD legacy (padded-layout equivalents)
# ---------------------------------------------------------------------------

@register_op("lod_rank_table", differentiable=False)
def _lod_rank_table(ins, attrs, ctx):
    """Rank table in padded layout: every row has the full length; the
    table is (lengths desc, original indices)."""
    x = _p(ins, "X")
    n = x.shape[0]
    t = x.shape[1] if x.ndim > 1 else 1
    return {"Out": [{"lengths": jnp.full((n,), t, wide_int()),
                     "index": jnp.arange(n, dtype=wide_int())}]}


@register_op("max_sequence_len", differentiable=False)
def _max_sequence_len(ins, attrs, ctx):
    table = _p(ins, "RankTable")
    return {"Out": [jnp.max(table["lengths"]).reshape(1)]}


@register_op("reorder_lod_tensor_by_rank", differentiable=False)
def _reorder_lod_tensor_by_rank(ins, attrs, ctx):
    x, table = _p(ins, "X"), _p(ins, "RankTable")
    return {"Out": [jnp.take(x, table["index"], axis=0)]}


@register_op("shrink_rnn_memory", nondiff_inputs=("I", "RankTable"))
def _shrink_rnn_memory(ins, attrs, ctx):
    """Keep the first k rows still active at step I (rows sorted by
    descending length in the rank table)."""
    x, i = _p(ins, "X"), _p(ins, "I")
    table = _p(ins, "RankTable")
    step = _concrete_index(i, "shrink_rnn_memory", attrs)
    active = int(np.asarray(jnp.sum(table["lengths"] > step)))
    return {"Out": [x[:max(active, 1)]]}


@register_op("split_lod_tensor", nondiff_inputs=("Mask",))
def _split_lod_tensor(ins, attrs, ctx):
    """XLA-friendly IfElse split: both branches get the full batch with
    non-selected rows zeroed (dynamic row counts don't compile)."""
    x, mask = _p(ins, "X"), _p(ins, "Mask")
    m = mask.reshape(-1).astype(bool)
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mb = m.reshape(shape)
    return {"OutTrue": [jnp.where(mb, x, 0)],
            "OutFalse": [jnp.where(mb, 0, x)]}


@register_op("merge_lod_tensor", nondiff_inputs=("Mask",))
def _merge_lod_tensor(ins, attrs, ctx):
    true_v, false_v = _p(ins, "InTrue"), _p(ins, "InFalse")
    mask = _p(ins, "Mask").reshape(-1).astype(bool)
    shape = (true_v.shape[0],) + (1,) * (true_v.ndim - 1)
    return {"Out": [jnp.where(mask.reshape(shape), true_v, false_v)]}


@register_op("rnn_memory_helper")
def _rnn_memory_helper(ins, attrs, ctx):
    return {"Out": [_p(ins, "X")]}


# ---------------------------------------------------------------------------
# print / assert / emptiness
# ---------------------------------------------------------------------------

@register_op("print")
def _print(ins, attrs, ctx):
    x = _p(ins, "In")
    msg = attrs.get("message", "")
    first_n = attrs.get("summarize", 20)
    jax.debug.print(msg + " {x}", x=x.reshape(-1)[:max(first_n, 1)])
    return {"Out": [x]}


@register_op("assert", differentiable=False)
def _assert(ins, attrs, ctx):
    cond = _p(ins, "Cond")
    try:
        ok = bool(np.asarray(cond).reshape(-1)[0])
        if not ok:
            raise AssertionError(
                f"Assert op failed: {attrs.get('summarize', '')}")
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        from jax.experimental import checkify
        checkify.check(jnp.all(cond), "Assert op failed")
    return {}


@register_op("is_empty", differentiable=False)
def _is_empty(ins, attrs, ctx):
    x = _p(ins, "X")
    return {"Out": [jnp.asarray(int(np.prod(np.shape(x))) == 0)]}


def _np_dtype(d):
    from ..fluid.framework import device_dtype
    return device_dtype(d)


@register_op("empty", differentiable=False)
def _empty(ins, attrs, ctx):
    shape = attrs.get("shape", [])
    return {"Out": [jnp.zeros(shape,
                              _np_dtype(attrs.get("dtype", "float32")))]}


@register_op("fill", differentiable=False)
def _fill(ins, attrs, ctx):
    vals = np.asarray(attrs.get("value", []), _np_dtype(
        attrs.get("dtype", "float32")))
    return {"Out": [jnp.asarray(vals).reshape(attrs.get("shape",
                                                        list(vals.shape)))]}


@register_op("delete_var", differentiable=False)
def _delete_var(ins, attrs, ctx):
    return {}       # lifetime is XLA's concern; nothing to free by hand


# ---------------------------------------------------------------------------
# save / load (host side-effects behind io/pure callbacks)
# ---------------------------------------------------------------------------

def _save_host(path):
    def save(*arrays):
        import os
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 *[np.asarray(a) for a in arrays])
        return np.zeros((), np.int32)
    return save


@register_op("save", differentiable=False)
def _save(ins, attrs, ctx):
    from jax.experimental import io_callback
    x = _p(ins, "X")
    io_callback(_save_host(attrs["file_path"]),
                jax.ShapeDtypeStruct((), jnp.int32), x, ordered=True)
    return {}


@register_op("save_combine", differentiable=False)
def _save_combine(ins, attrs, ctx):
    from jax.experimental import io_callback
    xs = list(ins["X"])
    io_callback(_save_host(attrs["file_path"]),
                jax.ShapeDtypeStruct((), jnp.int32), *xs, ordered=True)
    return {}


def _load_host(path, idx=0):
    def load():
        f = np.load(path if path.endswith(".npz") else path + ".npz")
        return f[f.files[idx]]
    return load


@register_op("load", differentiable=False)
def _load(ins, attrs, ctx):
    from jax.experimental import io_callback
    path = attrs["file_path"]
    probe = _load_host(path)()    # trace-time read gives shape/dtype ONLY;
    # the value is re-read per execution (a cached executable must see
    # files written by save ops since compilation, like the reference)
    out = io_callback(_load_host(path),
                      jax.ShapeDtypeStruct(probe.shape, probe.dtype),
                      ordered=True)
    return {"Out": [out]}


@register_op("load_combine", differentiable=False)
def _load_combine(ins, attrs, ctx):
    from jax.experimental import io_callback
    path = attrs["file_path"]
    f = np.load(path if path.endswith(".npz") else path + ".npz")
    outs = []
    for i, k in enumerate(f.files):
        outs.append(io_callback(
            _load_host(path, i),
            jax.ShapeDtypeStruct(f[k].shape, f[k].dtype), ordered=True))
    return {"Out": outs}


# ---------------------------------------------------------------------------
# queues (pipeline section plumbing) — host-side registry
# ---------------------------------------------------------------------------

_QUEUES = {}


@register_op("queue_generator", differentiable=False)
def _queue_generator(ins, attrs, ctx):
    import queue as _q
    for name in attrs.get("names", []):
        _QUEUES.setdefault(name, _q.Queue(
            maxsize=attrs.get("capacity", 64)))
    return {}


@register_op("enqueue", differentiable=False)
def _enqueue(ins, attrs, ctx):
    from jax.experimental import io_callback
    x = _p(ins, "X")
    name = attrs["queue_name"]

    def put(a):
        _QUEUES[name].put(np.asarray(a))
        return np.zeros((), np.int32)

    io_callback(put, jax.ShapeDtypeStruct((), jnp.int32), x, ordered=True)
    return {}


@register_op("dequeue", differentiable=False)
def _dequeue(ins, attrs, ctx):
    from jax.experimental import io_callback
    name = attrs["queue_name"]
    shape = tuple(attrs["shape"])
    dtype = _np_dtype(attrs.get("dtype", "float32"))

    def get():
        return _QUEUES[name].get().astype(dtype)

    # io_callback(ordered): a consuming pop must never be CSE'd with a
    # sibling dequeue or dropped by DCE (pure_callback allows both)
    out = io_callback(get, jax.ShapeDtypeStruct(shape, dtype), ordered=True)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# memcpy / coalesce / selected-rows (dense equivalents)
# ---------------------------------------------------------------------------

@register_op("memcpy")
def _memcpy(ins, attrs, ctx):
    return {"Out": [_p(ins, "X")]}


@register_op("memcpy_h2d")
def _memcpy_h2d(ins, attrs, ctx):
    return {"Out": [_p(ins, "X")]}


@register_op("memcpy_d2h")
def _memcpy_d2h(ins, attrs, ctx):
    return {"Out": [_p(ins, "X")]}


@register_op("coalesce_tensor", differentiable=False)
def _coalesce_tensor(ins, attrs, ctx):
    """Grad-fusion buffer (coalesce_tensor_op.cc): flatten+concat into one
    fused buffer; outputs alias the originals (XLA fuses the transfers)."""
    xs = list(ins["Input"])
    flat = jnp.concatenate([x.reshape(-1).astype(jnp.float32) for x in xs]) \
        if xs else jnp.zeros((0,), jnp.float32)
    return {"Output": xs, "FusedOutput": [flat]}


@register_op("merge_selected_rows")
def _merge_selected_rows(ins, attrs, ctx):
    return {"Out": [_p(ins, "X")]}     # dense grads arrive pre-merged


@register_op("get_tensor_from_selected_rows")
def _get_tensor_from_selected_rows(ins, attrs, ctx):
    return {"Out": [_p(ins, "X")]}


@register_op("split_selected_rows", differentiable=False)
def _split_selected_rows(ins, attrs, ctx):
    x = _p(ins, "X")
    sections = attrs.get("height_sections", [])
    if not sections:
        n = attrs.get("num", 1)
        sections = [x.shape[0] // n] * n
    outs, start = [], 0
    for s in sections:
        outs.append(x[start:start + s])
        start += s
    return {"Out": outs}


@register_op("slice_multi_tensor", differentiable=False)
def _slice_multi_tensor(ins, attrs, ctx):
    xs = list(ins["X"])
    start = attrs.get("begin", 0)
    end = attrs.get("end", None)
    return {"Out": [x[start:end] for x in xs]}


@register_op("split_ids", differentiable=False)
def _split_ids(ins, attrs, ctx):
    """Partition ids by id %% n over PS shards (split_ids_op.cc)."""
    ids = _p(ins, "Ids").reshape(-1)
    n = int(attrs.get("num", 1)) or 1
    outs = []
    for s in range(n):
        sel = jnp.nonzero(ids % n == s, size=ids.shape[0], fill_value=-1)[0]
        outs.append(jnp.where(sel >= 0, ids[jnp.clip(sel, 0, None)], -1))
    return {"Out": outs}


@register_op("fake_init", differentiable=False)
def _fake_init(ins, attrs, ctx):
    shape = attrs.get("shape", [1])
    return {"Out": [jnp.zeros(shape, jnp.float32)]}


@register_op("uniform_random_batch_size_like", differentiable=False,
             stateful_rng=True)
def _uniform_random_batch_size_like(ins, attrs, ctx):
    x = _p(ins, "Input")
    shape = list(attrs.get("shape", list(x.shape)))
    shape[attrs.get("input_dim_idx", 0)] = x.shape[
        attrs.get("input_dim_idx", 0)]
    key = ctx.key_for(attrs.get("op_seed", attrs.get("seed", 0)))
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    return {"Out": [jax.random.uniform(
        key, tuple(shape), jnp.float32, lo, hi)]}


# ---------------------------------------------------------------------------
# distributed lookups over the PS tier (distributed_ops/)
# ---------------------------------------------------------------------------

_SPARSE_TABLES = {}


def _get_table(name, dim, optimizer="sgd", lr=1.0):
    from ..distributed.ps.table import CommonSparseTable, Initializer
    if name not in _SPARSE_TABLES:
        _SPARSE_TABLES[name] = CommonSparseTable(
            dim, optimizer, lr, initializer=Initializer("zeros"))
    return _SPARSE_TABLES[name]


@register_op("lookup_sparse_table_init", differentiable=False)
def _lookup_sparse_table_init(ins, attrs, ctx):
    _get_table(attrs["table_name"], attrs.get("dim", attrs.get("embedding_dim", 8)),
               attrs.get("optimizer", "sgd"), attrs.get("lr", 1.0))
    return {}


@register_op("lookup_sparse_table_read", differentiable=False)
def _lookup_sparse_table_read(ins, attrs, ctx):
    ids = _p(ins, "Ids")
    name = attrs["table_name"]
    dim = attrs["dim"]

    def pull(i):
        return _get_table(name, dim).pull(np.asarray(i).reshape(-1)).astype(
            np.float32)

    flat = ids.reshape(-1)
    out = jax.pure_callback(
        pull, jax.ShapeDtypeStruct((flat.shape[0], dim), jnp.float32), flat)
    return {"Out": [out]}


@register_op("lookup_sparse_table_write", differentiable=False)
def _lookup_sparse_table_write(ins, attrs, ctx):
    from jax.experimental import io_callback
    ids, vals = _p(ins, "Ids"), _p(ins, "Value")
    name = attrs["table_name"]
    dim = int(vals.shape[-1])

    def write(i, v):
        t = _get_table(name, dim)
        i = np.asarray(i).reshape(-1)
        v = np.asarray(v).reshape(len(i), -1)
        cur = t.pull(i)
        t.push_delta(i, v - cur)       # write == set: delta from current
        return np.zeros((), np.int32)

    io_callback(write, jax.ShapeDtypeStruct((), jnp.int32),
                ids.reshape(-1), vals, ordered=True)
    return {}


@register_op("distributed_lookup_table", differentiable=False)
def _distributed_lookup_table(ins, attrs, ctx):
    """Pull embedding rows from the PS tier (distributed_lookup_table_op.cc)
    — in-process table here; the RPC plane serves the multi-process case
    (distributed/ps/rpc.py)."""
    ids = _p(ins, "Ids")
    name = attrs.get("table_name", attrs.get("table_names", ["emb"])[0]
                     if attrs.get("table_names") else "emb")
    dim = attrs.get("dim", attrs.get("emb_dim", 8))

    def pull(i):
        return _get_table(name, dim).pull(
            np.asarray(i).reshape(-1)).astype(np.float32)

    flat = ids.reshape(-1)
    rows = jax.pure_callback(
        pull, jax.ShapeDtypeStruct((flat.shape[0], dim), jnp.float32), flat)
    return {"Outputs": [rows.reshape(tuple(ids.shape) + (dim,))],
            "Out": [rows.reshape(tuple(ids.shape) + (dim,))]}
