"""Quantization ops (QAT fake-quant + PTQ dequant family).

Reference (SURVEY §A.1 "Quantization"): operators/fake_quantize_op.{cc,cu}
(fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
fake_quantize_range_abs_max, fake_quantize_moving_average_abs_max,
fake_quantize_dequantize_*), operators/fake_dequantize_op.cc
(fake_dequantize_max_abs, fake_channel_wise_dequantize_max_abs),
operators/dequantize_log_op.cc, operators/dequantize_abs_max_op.cc.

All fake-quant ops use straight-through gradients (the reference registers
FakeQuantGradMaker passing dY through), expressed here as a custom_grad that
forwards the cotangent — XLA folds the round/clip chain into one fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _bnt(attrs):
    # bin count: 2^(bit_length-1) - 1  (127 for int8)
    return float((1 << (attrs.get("bit_length", 8) - 1)) - 1)


def _quant(x, scale, bnt):
    s = jnp.maximum(scale, 1e-9)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * bnt)


def _dequant(q, scale, bnt):
    return q * scale / bnt


def _st_grad(slot_in="X", slot_out="Out"):
    def grad(ins, outs, out_grads, attrs, ctx):
        g = out_grads.get(slot_out)
        x = ins[slot_in][0]
        if g is None:
            g = jnp.zeros_like(x)
        return {slot_in: [g.astype(x.dtype)]}
    return grad


@register_op("fake_quantize_abs_max", nondiff_outputs=("OutScale",),
             custom_grad=_st_grad())
def _fake_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"][0]
    bnt = _bnt(attrs)
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_quant(x, scale, bnt)], "OutScale": [scale.reshape(1)]}


@register_op("fake_quantize_dequantize_abs_max", nondiff_outputs=("OutScale",),
             custom_grad=_st_grad())
def _fake_qdq_abs_max(ins, attrs, ctx):
    x = ins["X"][0]
    bnt = _bnt(attrs)
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_dequant(_quant(x, scale, bnt), scale, bnt)],
            "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_abs_max",
             nondiff_outputs=("OutScale",), custom_grad=_st_grad())
def _fake_cw_quant(ins, attrs, ctx):
    x = ins["X"][0]
    bnt = _bnt(attrs)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red)
    shape = [1] * x.ndim
    shape[axis] = -1
    return {"Out": [_quant(x, scale.reshape(shape), bnt)],
            "OutScale": [scale]}


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             nondiff_outputs=("OutScale",), custom_grad=_st_grad())
def _fake_cw_qdq(ins, attrs, ctx):
    """Channel-wise quant->dequant in one op: consumers see float-scale
    weights (the QAT training path; quantize-only codes are serving-side)."""
    x = ins["X"][0]
    bnt = _bnt(attrs)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red)
    shape = [1] * x.ndim
    shape[axis] = -1
    s = scale.reshape(shape)
    return {"Out": [_dequant(_quant(x, s, bnt), s, bnt)],
            "OutScale": [scale]}


@register_op("fake_quantize_range_abs_max",
             nondiff_inputs=("InScale", "Iter"),
             nondiff_outputs=("OutScale", "OutScales"),
             custom_grad=_st_grad())
def _fake_quant_range(ins, attrs, ctx):
    """Training-time scale tracked over a sliding window of abs-max values
    (fake_quantize_op.cc FakeQuantizeRangeAbsMaxKernel): in inference
    (is_test) the recorded InScale is used as-is."""
    x = ins["X"][0]
    bnt = _bnt(attrs)
    in_scale = ins["InScale"][0].reshape(())
    if attrs.get("is_test", False) or ctx.is_test:
        scale = in_scale
    else:
        scale = jnp.maximum(jnp.max(jnp.abs(x)), in_scale)
    return {"Out": [_quant(x, scale, bnt)],
            "OutScale": [scale.reshape(1)],
            "OutScales": [scale.reshape(1)]}


@register_op("fake_quantize_moving_average_abs_max",
             nondiff_inputs=("InScale", "InAccum", "InState"),
             nondiff_outputs=("OutScale", "OutAccum", "OutState"),
             custom_grad=_st_grad())
def _fake_quant_moving(ins, attrs, ctx):
    x = ins["X"][0]
    bnt = _bnt(attrs)
    rate = attrs.get("moving_rate", 0.9)
    in_scale = ins["InScale"][0].reshape(())
    if attrs.get("is_test", False) or ctx.is_test:
        scale = in_scale
        accum = in_scale
        state = jnp.ones(())
    else:
        cur = jnp.max(jnp.abs(x))
        in_accum = (ins["InAccum"][0].reshape(())
                    if ins.get("InAccum") else in_scale)
        in_state = (ins["InState"][0].reshape(())
                    if ins.get("InState") else jnp.ones(()))
        state = rate * in_state + 1.0
        accum = rate * in_accum + cur
        scale = accum / state
    return {"Out": [_quant(x, scale, bnt)],
            "OutScale": [scale.reshape(1)],
            "OutAccum": [accum.reshape(1)],
            "OutState": [state.reshape(1)]}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             nondiff_inputs=("InScale", "InAccum", "InState"),
             nondiff_outputs=("OutScale", "OutAccum", "OutState"),
             custom_grad=_st_grad())
def _fake_qdq_moving(ins, attrs, ctx):
    outs = _fake_quant_moving(ins, attrs, ctx)
    bnt = _bnt(attrs)
    scale = outs["OutScale"][0].reshape(())
    outs["Out"] = [_dequant(outs["Out"][0], scale, bnt)]
    return outs


@register_op("fake_dequantize_max_abs", nondiff_inputs=("Scale",))
def _fake_dequantize_max_abs(ins, attrs, ctx):
    x, scale = ins["X"][0], ins["Scale"][0].reshape(())
    max_range = attrs.get("max_range", 127.0)
    return {"Out": [x.astype(jnp.float32) * scale / max_range]}


@register_op("fake_channel_wise_dequantize_max_abs",
             nondiff_inputs=("Scales",))
def _fake_cw_dequant(ins, attrs, ctx):
    x = ins["X"][0].astype(jnp.float32)
    scales = ins["Scales"]
    quant_bits = attrs.get("quant_bits", [8])
    axis = attrs.get("quant_axis", 0)
    shape = [1] * x.ndim
    shape[axis] = -1
    out = x * scales[0].reshape(shape) / float((1 << (quant_bits[0] - 1)) - 1)
    if len(scales) > 1:
        out = out * scales[1].reshape(()) / float(
            (1 << (quant_bits[1] - 1)) - 1)
    return {"Out": [out]}


@register_op("dequantize_abs_max", nondiff_inputs=("Scale",),
             differentiable=False)
def _dequantize_abs_max(ins, attrs, ctx):
    x, scale = ins["X"][0], ins["Scale"][0].reshape(())
    return {"Out": [x.astype(jnp.float32) * scale / attrs.get("max_range",
                                                              127.0)]}


@register_op("dequantize_log", nondiff_inputs=("Dict",),
             differentiable=False)
def _dequantize_log(ins, attrs, ctx):
    """dequantize_log_op.cc: int8 codes index a 128-entry log-scale dict;
    negative codes mirror to -dict[code-128]."""
    x = ins["X"][0].astype(jnp.int32)
    d = ins["Dict"][0]
    neg = x < 0
    idx = jnp.where(neg, x + 128, x)
    val = d[jnp.clip(idx, 0, d.shape[0] - 1)]
    return {"Out": [jnp.where(neg, -val, val)]}


@register_op("quantize", differentiable=False)
def _quantize(ins, attrs, ctx):
    x = ins["Input"][0]
    scale = attrs.get("Scale", 1.0)
    shift = attrs.get("Shift", 0.0)
    return {"Output": [jnp.round(x * scale + shift).astype(jnp.int8)]}


@register_op("dequantize", differentiable=False)
def _dequantize(ins, attrs, ctx):
    x = ins["Input"][0]
    scale = attrs.get("Scale", 1.0)
    shift = attrs.get("Shift", 0.0)
    return {"Output": [(x.astype(jnp.float32) - shift) / scale]}


@register_op("requantize", differentiable=False)
def _requantize(ins, attrs, ctx):
    x = ins["Input"][0]
    si, so = attrs.get("Scale_in", 1.0), attrs.get("Scale_out", 1.0)
    return {"Output": [jnp.round(x.astype(jnp.float32) * so / si)
                       .astype(x.dtype)]}


@register_op("moving_average_abs_max_scale",
             nondiff_outputs=("OutScale", "OutAccum", "OutState"),
             custom_grad=_st_grad())
def _moving_average_abs_max_scale(ins, attrs, ctx):
    """Scale observer only (used by QAT output quantization)."""
    x = ins["X"][0]
    rate = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    in_accum = ins["InAccum"][0].reshape(()) if ins.get("InAccum") else cur
    in_state = (ins["InState"][0].reshape(())
                if ins.get("InState") else jnp.ones(()))
    state = rate * in_state + 1.0
    accum = rate * in_accum + cur
    return {"Out": [x], "OutScale": [(accum / state).reshape(1)],
            "OutAccum": [accum.reshape(1)], "OutState": [state.reshape(1)]}
