"""paddle.reader namespace (reference python/paddle/reader/)."""
from . import decorator
from .decorator import (cache, map_readers, buffered, compose, chain,
                        shuffle, ComposeNotAligned, firstn, xmap_readers,
                        multiprocess_reader)

__all__ = list(decorator.__all__)
