"""paddle.reader.decorator analog (reference python/paddle/reader/
decorator.py): composable reader transforms for the 1.x reader pipeline."""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "ComposeNotAligned", "firstn", "xmap_readers",
           "multiprocess_reader"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    all_data = []

    def creator():
        if not all_data:
            all_data.extend(reader())
        return iter(all_data)
    return creator


def map_readers(func, *readers):
    def creator():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)
    return creator


def shuffle(reader, buf_size):
    def creator():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return creator


def chain(*readers):
    def creator():
        return itertools.chain(*[r() for r in readers])
    return creator


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def creator():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
    return creator


class _EndSignal:
    """Terminator sentinel carrying a worker exception if one occurred
    (reference XmapEndSignal error flag): consumers re-raise instead of
    deadlocking on a dead producer."""

    def __init__(self, exc=None):
        self.exc = exc


def buffered(reader, size):
    def creator():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
                q.put(_EndSignal())
            except BaseException as e:   # noqa: BLE001 — forwarded
                q.put(_EndSignal(e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if isinstance(e, _EndSignal):
                if e.exc is not None:
                    raise e.exc
                break
            yield e
    return creator


def firstn(reader, n):
    def creator():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item
    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Threaded map over a reader (the reference uses threads too)."""
    def creator():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        end = object()

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as e:   # noqa: BLE001 — forwarded
                for _ in range(process_num):
                    in_q.put(_EndSignal(e))

        def work():
            while True:
                item = in_q.get()
                if item is end or isinstance(item, _EndSignal):
                    out_q.put(item if isinstance(item, _EndSignal)
                              else end)
                    return
                i, d = item
                try:
                    out_q.put((i, mapper(d)))
                except BaseException as e:   # noqa: BLE001 — forwarded
                    out_q.put(_EndSignal(e))
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            import heapq
            heap, want = [], 0
            while finished < process_num:
                item = out_q.get()
                if isinstance(item, _EndSignal):
                    raise item.exc
                if item is end:
                    finished += 1
                    continue
                heapq.heappush(heap, item)
                while heap and heap[0][0] == want:
                    yield heapq.heappop(heap)[1]
                    want += 1
            while heap:
                yield heapq.heappop(heap)[1]
        else:
            while finished < process_num:
                item = out_q.get()
                if isinstance(item, _EndSignal):
                    raise item.exc
                if item is end:
                    finished += 1
                    continue
                yield item[1]
    return creator


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run several readers in worker PROCESSES feeding one queue
    (reference decorator.py multiprocess_reader)."""
    import multiprocessing as mp

    def creator():
        ctx = mp.get_context("fork")
        q = ctx.Queue(queue_size)

        def work(r):
            try:
                for d in r():
                    q.put(d)
                q.put(None)
            except BaseException as e:   # noqa: BLE001 — forwarded as a
                q.put(("__reader_error__", repr(e)))   # picklable marker
                q.put(None)

        procs = [ctx.Process(target=work, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            d = q.get()
            if d is None:
                finished += 1
                continue
            if isinstance(d, tuple) and len(d) == 2 and \
                    d[0] == "__reader_error__":
                raise RuntimeError(f"multiprocess reader failed: {d[1]}")
            yield d
        for p in procs:
            p.join(timeout=5)
    return creator
