"""paddle.metric 2.0 (reference python/paddle/metric/)."""
from ..fluid.metrics import Accuracy, Auc, CompositeMetric
from ..fluid.metrics import MetricBase as Metric
from ..fluid.layers.metric_op import accuracy, auc
