"""paddle.metric 2.0 (reference python/paddle/metric/)."""
from .metrics import Metric, Accuracy, Precision, Recall, Auc
from ..fluid.metrics import CompositeMetric
from ..fluid.layers.metric_op import accuracy, auc
