"""paddle.metric 2.0 metric classes.

Reference: python/paddle/metric/metrics.py — `Metric` ABC with
compute/update/accumulate/reset/name, plus Accuracy, Precision, Recall, Auc.
These run host-side over fetched numpy arrays (the reference computes them in
ops or numpy; on TPU the eval loop fetches and accumulates on host, keeping
the device program free of scalar bookkeeping).
"""
from __future__ import annotations

import numpy as np


class Metric:
    """Base class (reference metrics.py `class Metric(metaclass=ABCMeta)`)."""

    def __init__(self, name=None):
        self._name = name or self.__class__.__name__.lower()

    def reset(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional device-side pre-step; default passthrough."""
        return args

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name


class Accuracy(Metric):
    """Top-k accuracy (metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = tuple(topk) if isinstance(topk, (tuple, list)) else (topk,)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(-1)
        kmax = max(self.topk)
        top = np.argsort(-pred, axis=-1)[..., :kmax]
        correct = (top == label[:, None])
        return correct

    def update(self, correct):
        correct = np.asarray(correct)
        res = []
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(axis=-1).astype(np.float64)
            self.total[i] += c.sum()
            self.count[i] += c.size
            res.append(c.mean() if c.size else 0.0)
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        acc = np.where(self.count > 0, self.total / np.maximum(self.count, 1),
                       0.0)
        return float(acc[0]) if len(self.topk) == 1 else [float(a)
                                                          for a in acc]


class Precision(Metric):
    """Binary precision (metrics.py Precision): tp / (tp + fp)."""

    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = np.asarray(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(Metric):
    """Binary recall (metrics.py Recall): tp / (tp + fn)."""

    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = np.asarray(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(Metric):
    """Histogram-bucketed ROC AUC (metrics.py Auc / the auc_op algorithm)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx, labels == 1)
        np.add.at(self._stat_neg, idx, labels == 0)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoidal area walking thresholds high->low, anchored at (0,0)
        # so the first (highest-threshold) bucket's area is counted —
        # all-one-bucket degenerate input then yields 0.5, not 0.0
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = np.concatenate([[0.0], pos / tot_pos])
        fpr = np.concatenate([[0.0], neg / tot_neg])
        return float(np.trapezoid(tpr, fpr))
