"""fluid.dygraph namespace — re-exports the dygraph subsystem
(reference python/paddle/fluid/dygraph/__init__.py)."""
from ...dygraph import *  # noqa: F401,F403
from ...dygraph import (guard, to_variable, no_grad, Layer, Sequential,
                        LayerList, ParameterList, Linear, FC, Conv2D, Pool2D,
                        BatchNorm, Embedding, LayerNorm, Dropout, GRUUnit,
                        PRelu, Conv2DTranspose, Conv3D, Conv3DTranspose,
                        InstanceNorm, GroupNorm, SpectralNorm,
                        BilinearTensorProduct, SequenceConv, RowConv, NCE,
                        TreeConv, Flatten, DataParallel, ParallelEnv,
                        prepare_context, save_dygraph, load_dygraph,
                        TracedLayer, declarative, enable_dygraph,
                        disable_dygraph)
from ...dygraph import nn  # noqa: F401
from . import io  # noqa: E402,F401
from ...dygraph import jit  # noqa: E402,F401
from ...dygraph import dygraph_to_static  # noqa: E402,F401
from ...dygraph import learning_rate_scheduler  # noqa: E402,F401
from ...dygraph.jit import (dygraph_to_static_func,  # noqa: E402,F401
                            set_code_level, set_verbosity,
                            not_to_static)
from . import profiler  # noqa: E402,F401
