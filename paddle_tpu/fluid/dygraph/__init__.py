"""fluid.dygraph namespace — re-exports the dygraph subsystem
(reference python/paddle/fluid/dygraph/__init__.py)."""
from ...dygraph import *  # noqa: F401,F403
from ...dygraph import (guard, to_variable, no_grad, Layer, Sequential,
                        LayerList, ParameterList, Linear, FC, Conv2D, Pool2D,
                        BatchNorm, Embedding, LayerNorm, Dropout, GRUUnit,
                        PRelu, Conv2DTranspose, Conv3D, Conv3DTranspose,
                        InstanceNorm, GroupNorm, SpectralNorm,
                        BilinearTensorProduct, SequenceConv, RowConv, NCE,
                        TreeConv, Flatten, DataParallel, ParallelEnv,
                        prepare_context, save_dygraph, load_dygraph,
                        TracedLayer, declarative, enable_dygraph,
                        disable_dygraph)
from ...dygraph import nn  # noqa: F401
