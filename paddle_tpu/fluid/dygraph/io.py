"""fluid.dygraph.io namespace (reference dygraph/io.py): the loaded
inference-artifact layer."""
from ...jit import TranslatedLayer

__all__ = ["TranslatedLayer"]
