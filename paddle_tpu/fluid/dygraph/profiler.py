"""fluid.dygraph.profiler namespace (reference dygraph/profiler.py)."""
from ..profiler import start_gperf_profiler, stop_gperf_profiler  # noqa: F401

__all__ = ["start_gperf_profiler", "stop_gperf_profiler"]
