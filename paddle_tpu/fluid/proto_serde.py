"""Program <-> ProgramDesc protobuf + reference tensor binary serde.

This is the model-format interop layer (VERDICT r4 missing #1/#2):

* `program_to_proto_bytes` / `program_from_proto_bytes` — the repo IR
  (fluid/framework.py Program/Block/Operator/Variable) to/from the
  ProgramDesc wire format specified in proto/framework.proto, including
  the OpVersionMap handled by fluid/op_version_registry.py.  A `__model__`
  file saved by the reference (python/paddle/fluid/io.py:1198) parses into
  a runnable Program; a Program saved here parses with the reference's
  protobuf.
* `serialize_lod_tensor` / `deserialize_lod_tensor` — the reference's
  binary tensor stream (paddle/fluid/framework/lod_tensor.cc:243
  SerializeToStream + tensor_util.cc:666 TensorToStream): uint32 version,
  LoD level table, TensorDesc proto, raw data.  This is the format of the
  reference's per-variable param files and save_combine output, so
  reference-trained weights load directly.

Attr typing on save follows the value (bool -> BOOLEAN before int -> INT/
LONG by range, float -> FLOAT, str -> STRING, lists likewise); block-ref
attrs (the repo's control-flow ops carry sub-block indices in
_SUB_BLOCK_ATTRS) are written as BLOCK so the reference reader sees real
block references.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .framework import (Block, Operator, Parameter, Program, Variable,
                        _PROTO_DTYPE, _SUB_BLOCK_ATTRS)
from . import op_version_registry as opver
from .proto import framework_pb2 as fp

__all__ = ["program_to_proto_bytes", "program_from_proto_bytes",
           "program_to_proto", "program_from_proto",
           "serialize_lod_tensor", "deserialize_lod_tensor",
           "save_combined_params", "load_combined_params",
           "strip_feed_fetch_ops"]

_DTYPE_TO_PROTO = {name: code for code, name in _PROTO_DTYPE.items()}

# attr names whose int value is a block index; written with AttrType.BLOCK
_BLOCK_ATTRS = _SUB_BLOCK_ATTRS

_INT32_MIN, _INT32_MAX = -(2 ** 31), 2 ** 31 - 1


def _to_plain(v):
    """numpy scalars/arrays and tuples -> plain python values."""
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, tuple):
        return list(v)
    return v


_JSON_ATTR_TAG = "__pdtpu_json__:"


def _set_attr(pb_attr, name: str, value, op_type: str) -> bool:
    """Fill one OpDesc.Attr; returns False when the value has no proto
    representation (caller decides whether that is fatal)."""
    value = _to_plain(value)
    pb_attr.name = name
    if name in _BLOCK_ATTRS and isinstance(value, int):
        pb_attr.type = fp.BLOCK
        pb_attr.block_idx = int(value)
    elif isinstance(value, bool):
        pb_attr.type = fp.BOOLEAN
        pb_attr.b = value
    elif isinstance(value, int):
        if _INT32_MIN <= value <= _INT32_MAX:
            pb_attr.type = fp.INT
            pb_attr.i = value
        else:
            pb_attr.type = fp.LONG
            pb_attr.l = value
    elif isinstance(value, float):
        pb_attr.type = fp.FLOAT
        pb_attr.f = value
    elif isinstance(value, str):
        pb_attr.type = fp.STRING
        pb_attr.s = value
    elif isinstance(value, dict):
        # dict attrs (the AMP plane's __amp_cast__ slot->dtypes map) ride
        # as tagged-JSON STRINGs: the reference reader sees an opaque
        # string attr it ignores; our reader round-trips the dict
        import json
        try:
            pb_attr.type = fp.STRING
            pb_attr.s = _JSON_ATTR_TAG + json.dumps(value, sort_keys=True)
        except (TypeError, ValueError):
            return False
    elif isinstance(value, list):
        items = [_to_plain(x) for x in value]
        if not items:
            # empty lists carry no element type; INTS is the dominant
            # empty-list attr in practice (axes/shape/offsets)
            pb_attr.type = fp.INTS
        elif all(isinstance(x, bool) for x in items):
            pb_attr.type = fp.BOOLEANS
            pb_attr.bools.extend(items)
        elif all(isinstance(x, int) for x in items):
            if all(_INT32_MIN <= x <= _INT32_MAX for x in items):
                pb_attr.type = fp.INTS
                pb_attr.ints.extend(items)
            else:
                pb_attr.type = fp.LONGS
                pb_attr.longs.extend(items)
        elif all(isinstance(x, (int, float)) for x in items):
            pb_attr.type = fp.FLOATS
            pb_attr.floats.extend(float(x) for x in items)
        elif all(isinstance(x, str) for x in items):
            pb_attr.type = fp.STRINGS
            pb_attr.strings.extend(items)
        else:
            return False
    else:
        return False
    return True


def _get_attr(pb_attr):
    t = pb_attr.type
    if t == fp.INT:
        return pb_attr.i
    if t == fp.FLOAT:
        return pb_attr.f
    if t == fp.STRING:
        if pb_attr.s.startswith(_JSON_ATTR_TAG):
            import json
            return json.loads(pb_attr.s[len(_JSON_ATTR_TAG):])
        return pb_attr.s
    if t == fp.INTS:
        return list(pb_attr.ints)
    if t == fp.FLOATS:
        return list(pb_attr.floats)
    if t == fp.STRINGS:
        return list(pb_attr.strings)
    if t == fp.BOOLEAN:
        return pb_attr.b
    if t == fp.BOOLEANS:
        return list(pb_attr.bools)
    if t == fp.BLOCK:
        return pb_attr.block_idx
    if t == fp.LONG:
        return pb_attr.l
    if t == fp.BLOCKS:
        return list(pb_attr.blocks_idx)
    if t == fp.LONGS:
        return list(pb_attr.longs)
    raise ValueError(f"unknown attr type {t}")


def _var_to_proto(v: Variable, pb_var) -> None:
    pb_var.name = v.name
    # FEED_MINIBATCH / FETCH_LIST holder vars (reference io.py:1151,1179)
    kind = getattr(v, "proto_var_type", None)
    if kind == "feed":
        pb_var.type.type = fp.VarType.FEED_MINIBATCH
        pb_var.persistable = True
        return
    if kind == "fetch":
        pb_var.type.type = fp.VarType.FETCH_LIST
        pb_var.persistable = True
        return
    pb_var.type.type = fp.VarType.LOD_TENSOR
    td = pb_var.type.lod_tensor.tensor
    td.data_type = _DTYPE_TO_PROTO.get(v.dtype or "float32",
                                       fp.VarType.FP32)
    if v.shape is not None:
        td.dims.extend(int(d) for d in v.shape)
    if v.persistable:
        pb_var.persistable = True
    if getattr(v, "is_data", False):
        pb_var.need_check_feed = True


def program_to_proto(program: Program) -> "fp.ProgramDesc":
    pb = fp.ProgramDesc()
    op_types = []
    for block in program.blocks:
        pb_block = pb.blocks.add()
        pb_block.idx = block.idx
        pb_block.parent_idx = block.parent_idx
        for v in block.vars.values():
            _var_to_proto(v, pb_block.vars.add())
        for op in block.ops:
            pb_op = pb_block.ops.add()
            pb_op.type = op.type
            op_types.append(op.type)
            for slot, names in op.inputs.items():
                pv = pb_op.inputs.add()
                pv.parameter = slot
                pv.arguments.extend(names)
            for slot, names in op.outputs.items():
                pv = pb_op.outputs.add()
                pv.parameter = slot
                pv.arguments.extend(names)
            for aname in sorted(op.attrs):
                aval = op.attrs[aname]
                if aval is None:
                    continue
                pb_attr = pb_op.attrs.add()
                if not _set_attr(pb_attr, aname, aval, op.type):
                    raise ValueError(
                        f"op '{op.type}' attr '{aname}' "
                        f"({type(aval).__name__}) has no ProgramDesc "
                        f"representation — not serializable")
    for op_type, version in sorted(opver.snapshot(op_types).items()):
        pair = pb.op_version_map.pair.add()
        pair.op_name = op_type
        pair.op_version.version = version
    return pb


def program_to_proto_bytes(program: Program) -> bytes:
    return program_to_proto(program).SerializeToString()


def program_from_proto(pb: "fp.ProgramDesc") -> Program:
    prog = Program()
    saved_vers = {pair.op_name: pair.op_version.version
                  for pair in pb.op_version_map.pair}
    # allocate blocks first so parent links and block-attrs resolve;
    # place by idx — the repeated field may arrive in any order
    n_blocks = max((b.idx for b in pb.blocks), default=0) + 1
    prog.blocks.extend(None for _ in range(n_blocks - 1))
    for pb_block in pb.blocks:
        if pb_block.idx == 0:
            prog.blocks[0].parent_idx = pb_block.parent_idx
        else:
            prog.blocks[pb_block.idx] = Block(prog, pb_block.idx,
                                              pb_block.parent_idx)
    missing = [i for i, b in enumerate(prog.blocks) if b is None]
    if missing:
        raise ValueError(f"ProgramDesc has gaps in block indices: {missing}")
    # fill vars/ops in INDEX order: parent-block vars must exist before a
    # child block's ops resolve names, or a shadow var appears in the
    # child (wire order is arbitrary for repeated fields)
    for pb_block in sorted(pb.blocks, key=lambda b: b.idx):
        block = prog.blocks[pb_block.idx]
        for pb_var in pb_block.vars:
            _var_from_proto(pb_var, block)
        for pb_op in pb_block.ops:
            attrs = {}
            for pb_attr in pb_op.attrs:
                attrs[pb_attr.name] = _get_attr(pb_attr)
            opver.check_and_convert(pb_op.type, attrs,
                                    saved_vers.get(pb_op.type, 0))
            op = Operator(
                block, pb_op.type,
                {v.parameter: list(v.arguments) for v in pb_op.inputs},
                {v.parameter: list(v.arguments) for v in pb_op.outputs},
                attrs)
            block.ops.append(op)
            for names in op.outputs.values():
                for n in names:
                    if block._find_var_recursive(n) is None:
                        block.create_var(name=n)
                    block._find_var_recursive(n).op = op
    prog._bump_version()
    return prog


def _var_from_proto(pb_var, block: Block) -> None:
    t = pb_var.type.type
    if t == fp.VarType.FEED_MINIBATCH:
        v = block.create_var(name=pb_var.name, dtype=None)
        v.proto_var_type = "feed"
        v.persistable = True
        return
    if t == fp.VarType.FETCH_LIST:
        v = block.create_var(name=pb_var.name, dtype=None)
        v.proto_var_type = "fetch"
        v.persistable = True
        return
    td = None
    if t == fp.VarType.LOD_TENSOR and pb_var.type.HasField("lod_tensor"):
        td = pb_var.type.lod_tensor.tensor
    elif t == fp.VarType.SELECTED_ROWS \
            and pb_var.type.HasField("selected_rows"):
        td = pb_var.type.selected_rows
    elif t == fp.VarType.LOD_TENSOR_ARRAY \
            and pb_var.type.HasField("tensor_array"):
        td = pb_var.type.tensor_array.tensor
    shape = list(td.dims) if td is not None and len(td.dims) else None
    dtype = _PROTO_DTYPE.get(td.data_type, "float32") if td is not None \
        else None
    if pb_var.persistable and shape is not None \
            and t == fp.VarType.LOD_TENSOR:
        v = Parameter(block, pb_var.name, shape, dtype=dtype)
        block.vars[pb_var.name] = v
    else:
        v = block.create_var(name=pb_var.name, shape=shape, dtype=dtype,
                             persistable=pb_var.persistable,
                             is_data=pb_var.need_check_feed)


def program_from_proto_bytes(data: bytes) -> Program:
    pb = fp.ProgramDesc()
    pb.ParseFromString(data)
    return program_from_proto(pb)


def strip_feed_fetch_ops(program: Program
                         ) -> Tuple[List[str], List[str]]:
    """Remove reference-style feed/fetch ops from block 0 (the loader's
    PrepareProgram step, reference analysis_predictor.cc:199) and return
    (feed_names, fetch_names) ordered by their `col` attr."""
    block = program.global_block()
    feeds: List[Tuple[int, str]] = []
    fetches: List[Tuple[int, str]] = []
    kept = []
    for op in block.ops:
        if op.type == "feed":
            feeds.append((op.attrs.get("col", len(feeds)),
                          op.outputs["Out"][0]))
        elif op.type == "fetch":
            fetches.append((op.attrs.get("col", len(fetches)),
                            op.inputs["X"][0]))
        else:
            kept.append(op)
    if len(kept) != len(block.ops):
        block.ops[:] = kept
        program._bump_version()
    return ([n for _, n in sorted(feeds)], [n for _, n in sorted(fetches)])


# ---------------------------------------------------------------------------
# reference binary tensor streams (lod_tensor.cc:243 / tensor_util.cc:666)
# ---------------------------------------------------------------------------

def serialize_lod_tensor(arr: np.ndarray, lod=()) -> bytes:
    """One LoDTensor stream: uint32 version(0) | uint64 n_lod_levels
    {uint64 level_bytes, size_t[] level} | uint32 tensor version(0) |
    int32 desc_len, TensorDesc proto | raw data (C order)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.name not in _DTYPE_TO_PROTO:
        raise ValueError(f"dtype {arr.dtype} not in the VarType contract")
    out = [struct.pack("<I", 0), struct.pack("<Q", len(lod))]
    for level in lod:
        level = np.ascontiguousarray(level, dtype=np.uint64)
        out.append(struct.pack("<Q", level.nbytes))
        out.append(level.tobytes())
    desc = fp.VarType.TensorDesc()
    desc.data_type = _DTYPE_TO_PROTO[arr.dtype.name]
    desc.dims.extend(arr.shape)
    desc_bytes = desc.SerializeToString()
    out.append(struct.pack("<I", 0))                 # tensor version
    out.append(struct.pack("<i", len(desc_bytes)))
    out.append(desc_bytes)
    out.append(arr.tobytes())
    return b"".join(out)


_PROTO_TO_NP = {
    fp.VarType.BOOL: np.bool_, fp.VarType.INT16: np.int16,
    fp.VarType.INT32: np.int32, fp.VarType.INT64: np.int64,
    fp.VarType.FP16: np.float16, fp.VarType.FP32: np.float32,
    fp.VarType.FP64: np.float64, fp.VarType.UINT8: np.uint8,
    fp.VarType.INT8: np.int8,
}


def deserialize_lod_tensor(buf: bytes, offset: int = 0
                           ) -> Tuple[np.ndarray, list, int]:
    """Parse one LoDTensor stream at `offset`; returns (array, lod,
    next_offset) so combined files (save_combine) parse by iteration."""
    (version,) = struct.unpack_from("<I", buf, offset)
    offset += 4
    if version != 0:
        raise ValueError(f"unsupported LoDTensor stream version {version}")
    (n_levels,) = struct.unpack_from("<Q", buf, offset)
    offset += 8
    lod = []
    for _ in range(n_levels):
        (nbytes,) = struct.unpack_from("<Q", buf, offset)
        offset += 8
        level = np.frombuffer(buf, np.uint64, nbytes // 8, offset)
        lod.append(level.tolist())
        offset += nbytes
    (tversion,) = struct.unpack_from("<I", buf, offset)
    offset += 4
    if tversion != 0:
        raise ValueError(f"unsupported Tensor stream version {tversion}")
    (desc_len,) = struct.unpack_from("<i", buf, offset)
    offset += 4
    desc = fp.VarType.TensorDesc()
    desc.ParseFromString(bytes(buf[offset:offset + desc_len]))
    offset += desc_len
    if desc.data_type == fp.VarType.BF16:
        import ml_dtypes
        np_dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        np_dtype = np.dtype(_PROTO_TO_NP[desc.data_type])
    count = int(np.prod(desc.dims)) if len(desc.dims) else 1
    arr = np.frombuffer(buf, np_dtype, count, offset).reshape(
        tuple(desc.dims))
    offset += count * np_dtype.itemsize
    return arr.copy(), lod, offset


def save_combined_params(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """save_combine format: LoDTensor streams concatenated in sorted-name
    order (reference io.py save_vars sorts the combined var list)."""
    with open(path, "wb") as f:
        for name in sorted(arrays):
            f.write(serialize_lod_tensor(np.asarray(arrays[name])))


def load_combined_params(path: str, names: List[str]
                         ) -> Dict[str, np.ndarray]:
    with open(path, "rb") as f:
        buf = f.read()
    out, offset = {}, 0
    for name in sorted(names):
        arr, _lod, offset = deserialize_lod_tensor(buf, offset)
        out[name] = arr
    if offset != len(buf):
        raise ValueError(
            f"combined params file has {len(buf) - offset} trailing bytes "
            f"after reading {len(names)} tensors — name list mismatch")
    return out
