"""Program/Block/Operator/Variable IR — the fluid graph model, TPU-native.

Reference: paddle/fluid/framework/framework.proto:42-205 (ProgramDesc =
BlockDesc[] of VarDesc[] + OpDesc[]) and python/paddle/fluid/framework.py
(Program:3921, Block:2436, Operator:1839, Variable:928).  Semantics kept:
two-program idiom (startup/main), nested blocks for control flow, named
variadic input/output slots, persistable vars, stop_gradient.  Execution
differs: a Block is not interpreted op-by-op; executor.py lowers it to one
jaxpr and XLA-compiles it (the "kernel" is a lowering rule, not CUDA).
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

_dtype_aliases = {
    "float32": "float32", "fp32": "float32", np.float32: "float32",
    "float64": "float64", "fp64": "float64", np.float64: "float64",
    "float16": "float16", "fp16": "float16", np.float16: "float16",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "int64": "int64", np.int64: "int64",
    "int32": "int32", np.int32: "int32",
    "int16": "int16", "int8": "int8", "uint8": "uint8",
    "bool": "bool", bool: "bool",
}


# framework.proto VarType.Type enum values (framework.proto:104) — cast-op
# attrs and saved OpDescs carry these ints, not strings
_PROTO_DTYPE = {0: "bool", 1: "int16", 2: "int32", 3: "int64",
                4: "float16", 5: "float32", 6: "float64", 20: "uint8",
                21: "int8", 22: "bfloat16"}


def convert_dtype(dtype) -> str:
    """Normalise a user dtype spec to a canonical string name."""
    if isinstance(dtype, (int, np.integer)) \
            and not isinstance(dtype, bool) and int(dtype) in _PROTO_DTYPE:
        # numpy ints must hit this branch too: np.int64(5) would otherwise
        # fall through to np.dtype() and silently resolve as 'int64'
        return _PROTO_DTYPE[int(dtype)]
    if isinstance(dtype, str) and dtype in _dtype_aliases:
        return _dtype_aliases[dtype]
    if dtype in _dtype_aliases:
        return _dtype_aliases[dtype]
    try:
        return np.dtype(dtype).name
    except TypeError:
        # jax dtypes like jnp.bfloat16
        name = getattr(dtype, "name", None) or str(dtype)
        if name in _dtype_aliases:
            return _dtype_aliases[name]
        raise ValueError(f"unsupported dtype: {dtype!r}")


_64_TO_32 = {"int64": "int32", "uint64": "uint32", "float64": "float32"}


def device_dtype(dtype) -> str:
    """Canonical dtype name as it will exist ON DEVICE: 64-bit names map
    to their 32-bit counterparts when jax x64 mode is off (an explicit
    choice — requesting the 64-bit dtype would produce the same array
    plus a truncation warning per call).  Op lowerings use this for any
    dtype request that came from program attrs."""
    import jax
    name = convert_dtype(dtype)
    if not jax.config.jax_enable_x64:
        return _64_TO_32.get(name, name)
    return name


_name_counters: Dict[str, itertools.count] = {}


def unique_name(prefix: str = "tmp") -> str:
    """fluid.unique_name analog (python/paddle/fluid/unique_name.py)."""
    c = _name_counters.setdefault(prefix, itertools.count())
    return f"{prefix}_{next(c)}"


def reset_unique_name():
    _name_counters.clear()


class Variable:
    """A named tensor in a Block (VarDesc analog, framework.proto:104-167).

    Shape/dtype here are *advisory* IR metadata — the compiled function gets
    real shapes from the fed arrays; -1 marks a dynamic (batch) dim exactly as
    in fluid.  No LoD: ragged sequences are represented as padded tensors plus
    explicit length/segment-id tensors (SURVEY §5 long-context note).
    """

    def __init__(self, block: "Block", name: str, shape=None, dtype="float32",
                 persistable: bool = False, stop_gradient: bool = False,
                 is_data: bool = False, trainable: bool = True):
        self.block = block
        self.name = name
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype) if dtype is not None else None
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.trainable = trainable
        self.op: Optional[Operator] = None   # defining op (set by append_op)

    # --- operator sugar: building graph like fluid Variables do -------------
    def _binary(self, op_type, other, reverse=False):
        from ..fluid import layers
        other = layers.tensor._to_variable(self.block, other, self.dtype)
        x, y = (other, self) if reverse else (self, other)
        return layers.elementwise_op(op_type, x, y)

    def __add__(self, o): return self._binary("elementwise_add", o)
    def __radd__(self, o): return self._binary("elementwise_add", o, True)
    def __sub__(self, o): return self._binary("elementwise_sub", o)
    def __rsub__(self, o): return self._binary("elementwise_sub", o, True)
    def __mul__(self, o): return self._binary("elementwise_mul", o)
    def __rmul__(self, o): return self._binary("elementwise_mul", o, True)
    def __truediv__(self, o): return self._binary("elementwise_div", o)
    def __rtruediv__(self, o): return self._binary("elementwise_div", o, True)
    def __pow__(self, o): return self._binary("elementwise_pow", o)
    def __rpow__(self, o): return self._binary("elementwise_pow", o, True)
    def __floordiv__(self, o): return self._binary("elementwise_floordiv", o)
    def __rfloordiv__(self, o):
        return self._binary("elementwise_floordiv", o, True)
    def __mod__(self, o): return self._binary("elementwise_mod", o)
    def __rmod__(self, o): return self._binary("elementwise_mod", o, True)
    def __neg__(self):
        from ..fluid import layers
        return layers.scale(self, scale=-1.0)
    def __matmul__(self, o):
        from ..fluid import layers
        return layers.matmul(self, o)

    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")


class Parameter(Variable):
    """Persistable trainable variable (fluid framework.py Parameter)."""

    def __init__(self, block, name, shape, dtype="float32", trainable=True,
                 regularizer=None, need_clip=True, **kw):
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=not trainable,
                         trainable=trainable)
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_distributed = False
        # optional sharding annotation: PartitionSpec-like tuple over mesh axes
        self.sharding: Optional[tuple] = None


class Operator:
    """OpDesc analog: type + named input/output var-name lists + attrs."""

    def __init__(self, block: "Block", type: str,
                 inputs: Dict[str, List[str]], outputs: Dict[str, List[str]],
                 attrs: Optional[Dict[str, Any]] = None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in inputs.items()}
        self.outputs = {k: list(v) for k, v in outputs.items()}
        self.attrs = dict(attrs or {})

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for v in self.inputs.values() for n in v]

    @property
    def output_arg_names(self):
        return [n for v in self.outputs.values() for n in v]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def set_attr(self, name: str, val) -> None:
        """Mutate an attr on an op already in the graph, bumping the
        program's mutation version: an in-place rewrite keeps the op count
        AND ``_version`` unchanged, so a bare ``op.attrs[k] = v`` would let
        the executor's ``_fingerprint`` cache serve a stale digest (a
        cached executable compiled for the OLD attr value)."""
        self.attrs[name] = val
        self.block.program._bump_version()

    # reference OpDesc spelling (framework.py Operator._update_desc_attr)
    _update_desc_attr = set_attr

    def __repr__(self):
        return f"Op({self.type}: {self.inputs} -> {self.outputs})"


class Block:
    """BlockDesc analog: ordered ops + named vars, with parent scoping for
    control-flow sub-blocks (framework.proto BlockDesc.parent_idx)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"variable '{name}' not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        return None

    def create_var(self, name=None, shape=None, dtype="float32",
                   persistable=False, stop_gradient=False, is_data=False,
                   **kw) -> Variable:
        name = name or unique_name()
        v = Variable(self, name, shape=shape, dtype=dtype,
                     persistable=persistable, stop_gradient=stop_gradient,
                     is_data=is_data)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype="float32", trainable=True,
                         **kw) -> Parameter:
        p = Parameter(self, name, shape, dtype=dtype, trainable=trainable, **kw)
        # parameters live in block 0 (fluid global block convention)
        self.program.global_block().vars[name] = p
        return p

    def append_op(self, type: str, inputs: Dict[str, Any] = None,
                  outputs: Dict[str, Any] = None,
                  attrs: Dict[str, Any] = None) -> Operator:
        def norm(d):
            out = {}
            for k, v in (d or {}).items():
                if v is None:
                    continue
                if isinstance(v, (Variable, str)):
                    v = [v]
                out[k] = [x.name if isinstance(x, Variable) else x for x in v]
            return out
        op = Operator(self, type, norm(inputs), norm(outputs), attrs)
        if _current_device is not None and "op_device" not in op.attrs:
            # device_guard annotation — consumed by the pipeline splitter
            # (reference: operator.cc:1180 per-op `op_device` for pipeline)
            op.attrs["op_device"] = _current_device
        self.ops.append(op)
        self.program._bump_version()
        for names in op.outputs.values():
            for n in names:
                if self._find_var_recursive(n) is None:
                    self.create_var(name=n)
                var = self._find_var_recursive(n)
                var.op = op
        _infer_op_shapes(self, op)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = self.append_op(type, inputs, outputs, attrs)
        self.ops.insert(0, self.ops.pop())
        return op

    def _remove_op(self, index: int, end: Optional[int] = None):
        """Remove ``ops[index:end]`` (reference Block._remove_op), bumping
        the program mutation version.  Passes that pop-and-reinsert ops
        keep the op count stable, so without the bump the executor's
        ``_fingerprint`` count-based safety net cannot see the change."""
        del self.ops[index:(index + 1) if end is None else end]
        self.program._bump_version()

    def _insert_op(self, index: int, type: str, inputs=None, outputs=None,
                   attrs=None) -> Operator:
        """Build an op (var creation + shape inference, exactly like
        append_op) and place it at ``index`` (reference Block._insert_op).
        The bump rides on append_op."""
        op = self.append_op(type, inputs, outputs, attrs)
        self.ops.insert(index, self.ops.pop())
        return op

    def _insert_op_obj(self, index: int, op: Operator) -> Operator:
        """Insert an already-constructed Operator at ``index`` — the
        pattern-rewriter path, where ops are assembled detached and spliced
        in.  A bare ``ops.insert`` would keep ``_version`` stale exactly
        like the documented ``_remove_op`` hazard."""
        self.ops.insert(index, op)
        for names in op.outputs.values():
            for n in names:
                if self._find_var_recursive(n) is None:
                    self.create_var(name=n)
        self.program._bump_version()
        return op

    def _remove_var(self, name: str) -> bool:
        """Drop a var from this block (reference Block._remove_var),
        bumping the version: serialized descs and pass-managed rewrites
        key off it."""
        existed = self.vars.pop(name, None) is not None
        if existed:
            self.program._bump_version()
        return existed

    def _rename_var(self, old: str, new: str) -> Optional[Variable]:
        """Rename a var and every reference to it (reference
        Block._rename_var): op input/output lists in ALL blocks (sub-block
        ops capture outer vars by name), and the name-carrying control-flow
        attrs (`true_outs`, read by the conditional_block pass-through
        path).  Bumps the version: these name lists feed the executor
        fingerprint."""
        v = self.vars.pop(old, None)
        if v is not None:
            v.name = new
            self.vars[new] = v
        for b in self.program.blocks:
            for op in b.ops:
                for d in (op.inputs, op.outputs):
                    for slot, names in d.items():
                        d[slot] = [new if n == old else n for n in names]
                for k, val in op.attrs.items():
                    if k in ("true_outs", "false_outs") and isinstance(
                            val, (list, tuple)):
                        op.attrs[k] = type(val)(
                            new if n == old else n for n in val)
        self.program._bump_version()
        return v

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.program.global_block().vars.values()
                if isinstance(v, Parameter)]


_DEFAULT_DTYPE = "float32"

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")


def set_default_dtype(d) -> None:
    """paddle.set_default_dtype analog (reference
    python/paddle/framework/framework.py:20): the dtype layers use for
    parameters created without an explicit dtype."""
    global _DEFAULT_DTYPE
    try:
        name = convert_dtype(d)
    except (TypeError, ValueError):
        name = str(d)
    if name not in _FLOAT_DTYPES:
        raise TypeError(
            f"set_default_dtype only supports {_FLOAT_DTYPES}, got {name!r}")
    _DEFAULT_DTYPE = name


def get_default_dtype() -> str:
    return _DEFAULT_DTYPE


class Program:
    """ProgramDesc analog.  fluid's two-program idiom is kept: layer calls
    append compute ops to the *main* program and parameter-initialisation ops
    to the *startup* program (python/paddle/fluid/framework.py Program)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed: Optional[int] = None
        self._op_seed_counter = 0
        # annotations consumed by the executor / meta-optimizers
        self._amp_enabled = False
        self._amp_dtype = "bfloat16"
        self._hints: Dict[str, Any] = {}
        # executor fingerprint cache: bumped on every op mutation so the
        # per-step SHA-1 recompute is amortised away (executor._fingerprint)
        self._version = 0
        self._fp_cache = None

    def _bump_version(self):
        self._version += 1
        self._fp_cache = None

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def next_op_seed(self) -> int:
        base = self.random_seed if self.random_seed is not None else 0
        self._op_seed_counter += 1
        return base * 1_000_003 + self._op_seed_counter

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self):
        return self.global_block().all_parameters()

    @property
    def desc(self):
        """ProgramDesc protobuf snapshot (reference Program.desc is a live
        C++ wrapper; here the proto is regenerated from the IR on access
        — `program.desc.SerializeToString()` is the `__model__` bytes)."""
        from . import proto_serde
        return proto_serde.program_to_proto(self)

    def to_string(self, throw_on_error=True, with_details=False):
        """Debug string (reference framework.py:4655 Program.to_string):
        the protobuf text format of the ProgramDesc.  With
        throw_on_error=False a serialization failure becomes part of the
        debug output instead of raising (the reference contract)."""
        from google.protobuf import text_format
        try:
            return text_format.MessageToString(self.desc)
        except ValueError:
            if throw_on_error:
                raise
            return f"<Program: not fully serializable " \
                   f"({len(self.blocks)} blocks)>"

    def __str__(self):
        return self.to_string(True, False)

    @staticmethod
    def parse_from_string(binary_str: bytes) -> "Program":
        """Deserialize a Program from ProgramDesc protobuf bytes
        (reference framework.py:4657; parameters come back as plain
        persistable vars — values live in the scope, not the IR)."""
        from . import proto_serde
        return proto_serde.program_from_proto_bytes(binary_str)

    def clone(self, for_test: bool = False) -> "Program":
        """Structural clone; with for_test=True marks inference mode (dropout
        and batch_norm switch to eval behaviour via ctx.is_test), strips the
        backward/optimizer tail, and dead-code-eliminates by reachability —
        ops feeding only the removed tail (lr counters, grad-clip scratch)
        go too (framework/prune.cc semantics, not just the op-role filter)."""
        import copy
        p = copy.deepcopy(self)
        if for_test:
            p._hints["is_test"] = True
            p._hints.pop("recompute_checkpoints", None)
            p._hints.pop("pipeline_microbatches", None)
            # pass 1: strip the backward/optimizer tail from EVERY block
            # first, so the parent-block reachability scan below never sees
            # captures of sub-block grad ops that are about to be deleted
            for b in p.blocks:
                b.ops = [op for op in b.ops
                         if op.attr("op_role", 0) == 0 and
                         not op.type.endswith("_grad") and
                         op.type not in _OPTIMIZER_OP_TYPES]
            # pass 2: leaf-output seed; no state-write keep: eval must not
            # run lr counters or other train-state updates
            for b in p.blocks:
                b.ops = prune_ops(b, b.ops, targets=None,
                                  keep_state_writes=False)
        p._bump_version()
        return p

    def _prune(self, targets) -> "Program":
        """Program pruned to ops that `targets` (vars or names) depend on
        (reference Program._prune -> framework/prune.cc)."""
        import copy
        names = [t.name if isinstance(t, Variable) else str(t)
                 for t in (targets if isinstance(targets, (list, tuple))
                           else [targets])]
        p = copy.deepcopy(self)
        b = p.global_block()
        b.ops = prune_ops(b, b.ops, targets=names, keep_state_writes=False)
        p._bump_version()
        return p

    def __repr__(self):
        n_ops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={n_ops})"


_BATCH_PLACEHOLDER = 1031   # prime stand-in for -1 dims during eval_shape


def _infer_op_shapes(block: "Block", op: "Operator"):
    """Advisory shape/dtype inference: run the op's own lowering rule under
    jax.eval_shape (abstract — no compute).  This replaces the reference's
    676 per-op C++ InferShape functions (operator.cc:1095) with one
    mechanism; ops that need concrete values simply leave shapes unset."""
    from ..ops.registry import has_op, get_op, LoweringContext
    if not has_op(op.type) or op.type in ("generic_grad", "while",
                                          "conditional_block"):
        return
    import jax
    import jax.numpy as jnp
    opdef = get_op(op.type)
    ins = {}
    had_batch = False
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.shape is None or v.dtype is None:
                return
            shape = tuple(_BATCH_PLACEHOLDER if d == -1 else d
                          for d in v.shape)
            had_batch = had_batch or (-1 in v.shape)
            try:
                dt = jnp.dtype(v.dtype)
            except TypeError:
                return
            vals.append(jax.ShapeDtypeStruct(shape, dt))
        ins[slot] = vals
    ctx = LoweringContext()
    try:
        outs = jax.eval_shape(lambda i: opdef.fn(i, op.attrs, ctx), ins)
    except Exception:
        return
    for slot, names in op.outputs.items():
        for name, o in zip(names, outs.get(slot, []) or []):
            var = block._find_var_recursive(name)
            if var is None or o is None:
                continue
            if var.shape is None:
                var.shape = tuple(
                    -1 if (had_batch and d == _BATCH_PLACEHOLDER) else d
                    for d in o.shape)
            if var.dtype is None or var.dtype == "float32":
                var.dtype = str(jnp.dtype(o.dtype))


_OPTIMIZER_OP_TYPES = frozenset({
    "sgd", "momentum", "adam", "adamw", "adagrad", "rmsprop", "lamb",
    "lars_momentum", "ftrl", "dpsgd", "dgc_momentum",
    # bucketed kernel-tier updates (fluid/passes/kernel_tier.py)
    "fused_adam", "fused_lamb", "fused_momentum",
})

# ops kept during pruning regardless of reachability: cross-device and
# control-flow effects the dataflow scan can't see (select_input/output are
# pure dataflow with declared slots — plain reachability covers them)
_SIDE_EFFECT_OP_TYPES = frozenset({
    "send_v2", "partial_send", "barrier", "c_sync_calc_stream",
    "c_sync_comm_stream", "while", "conditional_block", "py_func", "print",
})

_SUB_BLOCK_ATTRS = ("sub_block", "cond_block", "true_block", "false_block")


def _op_reads(block, op, _seen=None):
    """All vars an op may read, INCLUDING captures of its control-flow
    sub-blocks (cond/while bodies read outer vars that are not declared
    as op inputs)."""
    reads = list(op.input_arg_names)
    if (op.type == "conditional_block"
            and op.attrs.get("false_block", -1) < 0):
        # pass-through false path READS the outputs' prior values
        reads += list(op.attrs.get("true_outs", ()))
    _seen = _seen if _seen is not None else set()
    prog = block.program
    for attr in _SUB_BLOCK_ATTRS:
        idx = op.attrs.get(attr)
        if isinstance(idx, int) and 0 <= idx < len(prog.blocks) \
                and idx not in _seen:
            _seen.add(idx)
            sub = prog.blocks[idx]
            written = set()
            for sop in sub.ops:
                reads += [n for n in _op_reads(sub, sop, _seen)
                          if n not in written]
                written.update(sop.output_arg_names)
    return reads


def prune_ops(block, ops, targets=None, keep_state_writes=True,
              extra_state=(), feeds=()):
    """Backward-reachability prune (framework/prune.cc analog).

    Keeps an op iff it (a) produces a var in the needed set, seeded from
    `targets` (None = every NON-persistable leaf output — predictions,
    losses, metrics; persistable leaves are training state whose updates
    are exactly what a for_test clone must drop), (b) writes a persistable
    or `extra_state` var while `keep_state_writes` (optimizer / BN-stats
    updates must survive a fetch-only prune), or (c) has side effects the
    dataflow can't see.  Kept ops contribute their reads — including
    control-flow sub-block captures — to the needed set, one reverse pass.

    `feeds` names vars the caller materialises directly: an op whose
    needed outputs are ALL fed is dropped and its inputs are not
    traversed — feeding an intermediate var runs the program FROM that
    var, exactly the reference's prune-with-input semantics
    (framework/prune.cc feed targets; executor.py feed of any var)."""
    def persistable(n):
        # resolve through parent blocks: sub-block ops write global-block
        # counters (GradientMerge-style state updated inside while bodies)
        v = block._find_var_recursive(n)
        return v is not None and v.persistable

    extra = set(extra_state)
    fed = set(feeds)
    if targets is None:
        consumed = {n for op in ops for n in _op_reads(block, op)}
        needed = {n for op in ops for n in op.output_arg_names
                  if n not in consumed and not persistable(n)}
    else:
        needed = set(targets)
    kept = []
    for op in reversed(ops):
        outs = op.output_arg_names
        state_write = keep_state_writes and any(
            persistable(n) or n in extra for n in outs)
        needed_outs = [n for n in outs if n in needed]
        if (fed and needed_outs and not state_write
                and op.type not in _SIDE_EFFECT_OP_TYPES
                and all(n in fed for n in needed_outs)
                # in-place op on the fed var (reads the same name it
                # writes): the op transforms the fed value — keep it
                and not (set(needed_outs) & set(_op_reads(block, op)))):
            continue          # feed satisfies everything this op is for
        keep = (op.type in _SIDE_EFFECT_OP_TYPES or needed_outs
                or state_write)
        if keep:
            kept.append(op)
            needed.update(_op_reads(block, op))
    kept.reverse()
    return kept

# ---------------------------------------------------------------------------
# device_guard: pipeline stage placement (fluid.device_guard analog —
# python/paddle/fluid/framework.py device_guard; ops appended inside the
# guard carry an `op_device` attr, consumed by PipelineOptimizer's splitter)
# ---------------------------------------------------------------------------
_current_device = None


class device_guard:
    """`with fluid.device_guard("tpu:1"):` — annotate appended ops with a
    pipeline stage device."""

    def __init__(self, device=None):
        self.device = device
        self._prev = None

    def __enter__(self):
        global _current_device
        self._prev = _current_device
        _current_device = self.device
        return self

    def __exit__(self, *a):
        global _current_device
        _current_device = self._prev
        return False

# ---------------------------------------------------------------------------
# default program machinery (program_guard etc.)
# ---------------------------------------------------------------------------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    global _main_program, _startup_program
    prev_main, prev_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_main, prev_startup


_dygraph_tracer_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _set_dygraph_tracer(tracer):
    global _dygraph_tracer_
    _dygraph_tracer_ = tracer


def _dygraph_tracer():
    return _dygraph_tracer_


def cuda_places(device_ids=None):
    """Accelerator places (framework.py cuda_places): TPU chips here."""
    from .core import TPUPlace
    import jax
    if device_ids is None:
        try:
            device_ids = range(len(jax.devices()))
        except RuntimeError:
            device_ids = [0]
    return [TPUPlace(int(i)) for i in device_ids]


def cpu_places(device_count=None, count=None):
    """count= kept as the historical keyword of this build's first
    signature; device_count= matches the reference."""
    from .core import CPUPlace
    import os
    n = device_count or count or int(os.environ.get("CPU_NUM", "1"))
    return [CPUPlace() for _ in range(n)]


def cuda_pinned_places(device_count=None):
    from .core import TPUPinnedPlace
    n = device_count or 1
    return [TPUPinnedPlace() for _ in range(n)]


def require_version(min_version, max_version=None):
    """framework.py require_version analog over the build's version."""
    from .. import __version__

    def parse(v):
        return [int(x) for x in str(v).split(".")[:3] if x.isdigit()]
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")


def load_op_library(path):
    from .core import load_op_library as _l
    return _l(path)
