"""Parameter initializers — ops appended to the startup program.

Reference: python/paddle/fluid/initializer.py — each Initializer appends a
fill/random op for the parameter into the startup Program (the two-program
idiom, SURVEY §2.8).  Identical design here; the random ops draw from the
functional PRNG (ops/random_ops.py).
"""
from __future__ import annotations

import math

import numpy as np

from .framework import default_startup_program


class Initializer:
    def __call__(self, param, block=None):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        block = block or default_startup_program().global_block()
        block.append_op("fill_constant", outputs={"Out": [param.name]},
                        attrs={"shape": list(param.shape),
                               "dtype": param.dtype, "value": self.value})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, param, block=None):
        block = block or default_startup_program().global_block()
        block.append_op(
            "uniform_random", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "min": self.low, "max": self.high,
                   "op_seed": self.seed or block.program.next_op_seed()})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, param, block=None):
        block = block or default_startup_program().global_block()
        block.append_op(
            "gaussian_random", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "mean": self.loc, "std": self.scale,
                   "op_seed": self.seed or block.program.next_op_seed()})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, param, block=None):
        block = block or default_startup_program().global_block()
        block.append_op(
            "truncated_gaussian_random", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "mean": self.loc, "std": self.scale,
                   "op_seed": self.seed or block.program.next_op_seed()})


def _fans(shape):
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) >= 3:
        rf = int(np.prod(shape[2:]))
        return shape[1] * rf, shape[0] * rf
    return shape[0], shape[0]


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, param, block=None):
        fi, fo = _fans(param.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(param, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(param, block)


class MSRAInitializer(Initializer):
    """Kaiming/He init (initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0,
                 negative_slope=0.0, nonlinearity="relu"):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, param, block=None):
        fi, _ = _fans(param.shape)
        fi = self.fan_in or fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(param, block)
        else:
            NormalInitializer(0.0, math.sqrt(2.0 / fi), self.seed)(param, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose."""

    def __call__(self, param, block=None):
        block = block or default_startup_program().global_block()
        shape = param.shape
        f = math.ceil(shape[3] / 2)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype="float32")
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            w[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        block.append_op("assign_value", outputs={"Out": [param.name]},
                        attrs={"shape": list(shape), "dtype": param.dtype,
                               "fp32_values": w.flatten().tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, param, block=None):
        block = block or default_startup_program().global_block()
        block.append_op(
            "assign_value", outputs={"Out": [param.name]},
            attrs={"shape": list(self.value.shape), "dtype": param.dtype,
                   "fp32_values": self.value.astype("float64").flatten().tolist()})


# fluid public aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _to_initializer(x, default=None):
    if x is None:
        return default or XavierInitializer()
    if isinstance(x, Initializer):
        return x
    if isinstance(x, (int, float)):
        return ConstantInitializer(float(x))
    raise TypeError(f"cannot convert {x!r} to an Initializer")


_global_weight_initializer = None
_global_bias_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """reference initializer.py set_global_initializer: the default
    initializer for parameters created WITHOUT an explicit one (per-param
    attr.initializer still wins).  Pass None to reset."""
    global _global_weight_initializer, _global_bias_initializer
    _global_weight_initializer = weight_init
    _global_bias_initializer = bias_init


def _global_initializer(is_bias):
    return _global_bias_initializer if is_bias \
        else _global_weight_initializer
