"""fluid.unique_name module analog (reference unique_name.py):
generate/switch/guard over the same counter the framework's internal
unique_name() function uses."""
from __future__ import annotations

import contextlib

from . import framework as _fw

__all__ = ["generate", "switch", "guard"]


def generate(key):
    return _fw.unique_name(key)


def switch(new_generator=None):
    old = dict(_fw._name_counters)
    _fw._name_counters.clear()
    if new_generator:
        _fw._name_counters.update(new_generator)
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        _fw._name_counters.clear()
        _fw._name_counters.update(old)
