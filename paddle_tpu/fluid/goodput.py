"""Goodput accounting: step-time attribution over the observability plane.

Reference: Google's ML-goodput accounting (see also the reference stack's
profiler summaries, platform/profiler.cc) answers the question the raw
trace cannot: *what fraction of wall-clock was productive training?*  A
trainer that spends half its life compiling, waiting on the input
pipeline, or replaying restarts looks healthy on a steps/sec counter —
the badput only shows up when every wall-clock second is charged to
exactly one bucket.

This module classifies a run's wall-clock into eight exhaustive,
mutually-exclusive buckets by consuming the spans the earlier PRs already
emit (``executor::compile``, ``executor::step``, ``executor::host_wait``,
``loader::wait``, ``checkpoint::save``/``::submit``/``::restore``,
``elastic::drain``, ``ps::pull_wait``):

=================  =========================================================
bucket             meaning
=================  =========================================================
device_compute     the device is doing training work: ``executor::step``
                   dispatch plus host time *blocked on device results*
                   (``executor::host_wait`` — backpressure means the device
                   is the bottleneck, which is the productive state)
host_input_wait    host blocked waiting for the input pipeline
                   (``loader::wait`` — the Prefetcher consumer side)
compile            trace + XLA compile (``executor::compile``, IR-pass
                   spans)
checkpoint_stall   step-window time lost to checkpointing: synchronous
                   ``checkpoint::save`` spans and the async submit slice
                   (``checkpoint::submit``); async writes on the
                   ``ckpt-writer`` thread overlap compute and are NOT
                   counted
preemption_drain   closing the in-flight window on preemption
                   (``elastic::drain``)
restart_init       process start -> first instrumented activity, plus
                   ``checkpoint::restore``
ps_pull_wait       step blocked on sharded parameter-server pulls
                   (``ps::pull_wait`` — what the PS prefetcher failed to
                   hide)
idle               everything else (host-side gaps the plane cannot name)
=================  =========================================================

Attribution is an interval sweep: overlapping spans never double-count —
each elementary segment goes to the single highest-priority bucket
covering it (drain > checkpoint stall > restart > compile > input wait >
device compute), so the buckets sum to wall-clock *exactly*.

Two entry points:

* :func:`attribute_events` — pure function over a Chrome-trace event
  list (exported timelines, synthetic tests, tools/timeline.py's goodput
  track).  This module imports nothing outside the stdlib at top level,
  so converters can load it by file path like tools/ loads trace.py.
* :func:`snapshot` / :func:`update_gauges` — live attribution over the
  in-process trace buffer; ``update_gauges`` refreshes the rolling
  ``goodput.ratio`` gauge (window = ``FLAGS_goodput_window_s``, 0 = the
  whole run) plus per-bucket ``goodput.<bucket>_seconds`` gauges.  The
  metrics HTTP endpoint and the JSONL snapshot writer call this on every
  scrape/tick.
* :func:`from_metrics` — a coarse estimate from histogram totals for
  runs with tracing OFF (bench children): the named badput buckets are
  measured, the remainder is credited to ``device_compute`` (idle cannot
  be split out without spans) — an upper bound, labeled
  ``source="metrics"``.

Gating: attribution needs the event stream, so exact goodput costs only
what tracing already costs; with tracing off nothing here runs on the hot
path (the acceptance contract: single-boolean-off).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

try:                                    # stdlib-pure when loaded by file
    from . import trace as _trace       # path (tools/timeline.py)
except ImportError:                     # pragma: no cover - standalone load
    _trace = None

__all__ = [
    "BUCKETS", "PRODUCTIVE_BUCKET", "classify_event", "attribute_events",
    "snapshot", "update_gauges", "publish_gauges", "from_metrics",
]

#: every wall-clock second lands in exactly one of these
BUCKETS = ("device_compute", "host_input_wait", "compile",
           "checkpoint_stall", "preemption_drain", "restart_init",
           "ps_pull_wait", "idle")

PRODUCTIVE_BUCKET = "device_compute"

# sweep priority (index 0 strongest): when spans overlap — elastic::drain
# CONTAINS the host_wait spans of the window it closes, a sync
# checkpoint::save inside drain_and_save, the first executor::step
# overlaps its own executor::compile — the strongest bucket owns the
# overlap and nothing double-counts.  ps_pull_wait sits between the input
# wait and device compute: a PS pull stalled inside a loader wait is the
# loader's problem, but a pull stalling the step body is its own bucket.
_PRIORITY = ("preemption_drain", "checkpoint_stall", "restart_init",
             "compile", "host_input_wait", "ps_pull_wait",
             "device_compute")
_PRIO_INDEX = {b: i for i, b in enumerate(_PRIORITY)}


def classify_event(ev: Dict[str, Any]) -> Optional[str]:
    """Bucket for one Chrome-trace event, or None when it carries no
    goodput signal (per-op trace-time spans, comm annotations, bench
    wrappers...)."""
    if ev.get("ph") != "X":
        return None
    name = ev.get("name", "")
    cat = ev.get("cat", "")
    if name == "executor::compile" or cat == "pass":
        return "compile"
    if name in ("executor::step", "executor::host_wait"):
        return "device_compute"
    if name == "loader::wait":
        return "host_input_wait"
    if name == "ps::pull_wait":
        # sharded-PS pull latency the prefetcher failed to hide
        return "ps_pull_wait"
    if name == "checkpoint::submit":
        return "checkpoint_stall"
    if name == "checkpoint::save":
        # async saves ride the ckpt-writer thread and OVERLAP compute —
        # only a synchronous save stalls the step window.  A missing
        # arg (traces exported before the flag existed) defaults to
        # ASYNC: async_save is the default mode, so biasing old traces
        # toward no-stall beats inventing phantom checkpoint stalls.
        if (ev.get("args") or {}).get("sync", False):
            return "checkpoint_stall"
        return None
    if name == "checkpoint::restore":
        return "restart_init"
    if name == "elastic::drain":
        return "preemption_drain"
    return None


def _intervals_of(events: Sequence[Dict[str, Any]]):
    """(classified intervals, min event ts, max span end) of an event
    list."""
    intervals: List[Tuple[float, float, int]] = []
    ev_lo = ev_hi = None
    for ev in events:
        if ev.get("ph") != "X":
            continue
        try:
            s = float(ev.get("ts", 0.0))
            e = s + float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        ev_lo = s if ev_lo is None else min(ev_lo, s)
        ev_hi = e if ev_hi is None else max(ev_hi, e)
        bucket = classify_event(ev)
        if bucket is not None and e > s:
            intervals.append((s, e, _PRIO_INDEX[bucket]))
    return intervals, ev_lo, ev_hi


def attribute_events(events: Sequence[Dict[str, Any]],
                     t0_us: Optional[float] = None,
                     t1_us: Optional[float] = None,
                     include_segments: bool = False) -> Dict[str, Any]:
    """Exhaustive, exclusive wall-clock attribution over ``events``.

    The window defaults to [min ts, max span end] of the event list;
    live callers pass ``t0_us=0`` (trace epoch = process start) and
    ``t1_us=now`` so init time and trailing idle are charged too.
    Uncovered time before the FIRST classified span in the list is
    charged to restart_init (the list is taken to start at the run's
    start; for a mid-run window use :func:`snapshot`, which knows the
    run's true first activity).  Returns ``{"wall_seconds", "buckets":
    {bucket: seconds}, "ratio", "classified_spans", "source"}``; with
    ``include_segments`` also a ``segments`` list of ``(start_us,
    end_us, bucket)`` (adjacent same-bucket segments merged) for
    timeline rendering.  The buckets always sum to ``wall_seconds``
    exactly (the 5%% acceptance bound in ci_smoke is slack for float
    accumulation only).
    """
    intervals, ev_lo, ev_hi = _intervals_of(events)
    return _attribute(intervals, ev_lo, ev_hi, t0_us, t1_us,
                      include_segments)


def _attribute(intervals, ev_lo, ev_hi,
               t0_us: Optional[float] = None,
               t1_us: Optional[float] = None,
               include_segments: bool = False,
               run_first_work_us: Optional[float] = None) -> Dict[str, Any]:
    """The sweep proper.  ``run_first_work_us`` — the run's earliest
    classified activity, independent of the window — bounds the
    restart_init rule: uncovered time is "restart" only while the run
    had not yet done ANY instrumented work, so a rolling window that
    starts mid-run never invents phantom restart seconds."""
    t0 = float(t0_us) if t0_us is not None else (ev_lo or 0.0)
    t1 = float(t1_us) if t1_us is not None else (ev_hi or t0)
    t1 = max(t0, t1)
    wall_us = t1 - t0

    buckets = {b: 0.0 for b in BUCKETS}
    segments: List[List[Any]] = []

    def _charge(s: float, e: float, bucket: str):
        if e <= s:
            return
        buckets[bucket] += e - s
        if include_segments:
            if segments and segments[-1][2] == bucket \
                    and segments[-1][1] == s:
                segments[-1][1] = e
            else:
                segments.append([s, e, bucket])

    # clip to the window, drop empties
    clipped = []
    for s, e, p in intervals:
        s, e = max(s, t0), min(e, t1)
        if e > s:
            clipped.append((s, e, p))

    first_work = min((s for s, _, _ in clipped), default=None)
    if run_first_work_us is not None:
        # the run's true first activity wins over the window-local one:
        # when it lies before t0 the sweep below (cur >= t0 > first)
        # charges nothing to restart_init — a rolling window that starts
        # mid-run never invents phantom restart seconds
        first_work = run_first_work_us

    # boundary sweep with per-priority active counts: each elementary
    # segment goes to the strongest covering bucket; uncovered segments
    # are restart_init before the first instrumented activity, idle after
    points: List[Tuple[float, int, int]] = []
    for s, e, p in clipped:
        points.append((s, 0, p))        # opens sort before closes at a tie
        points.append((e, 1, p))
    points.sort(key=lambda x: (x[0], x[1]))
    active = [0] * len(_PRIORITY)
    cur = t0
    for t, kind, p in points:
        if t > cur:
            owner = next((i for i, n in enumerate(active) if n > 0), None)
            if owner is not None:
                _charge(cur, t, _PRIORITY[owner])
            elif first_work is not None and cur < first_work:
                _charge(cur, min(t, first_work), "restart_init")
                if t > first_work:      # straddles the first span start
                    _charge(first_work, t, "idle")
            else:
                _charge(cur, t, "idle")
            cur = t
        active[p] += 1 if kind == 0 else -1
    if cur < t1:
        if first_work is None:
            _charge(cur, t1, "idle")
        elif cur < first_work:
            _charge(cur, min(t1, first_work), "restart_init")
            _charge(max(cur, first_work), t1, "idle")
        else:
            _charge(cur, t1, "idle")

    wall_s = wall_us / 1e6
    out = {
        "wall_seconds": wall_s,
        "buckets": {b: v / 1e6 for b, v in buckets.items()},
        "ratio": (buckets[PRODUCTIVE_BUCKET] / wall_us) if wall_us else 0.0,
        "classified_spans": len(clipped),
        "source": "spans",
    }
    if include_segments:
        out["segments"] = [(s, e, b) for s, e, b in segments]
    return out


# ---------------------------------------------------------------------------
# live surface (needs the in-process trace plane)
# ---------------------------------------------------------------------------

def _require_trace():
    if _trace is None:              # pragma: no cover - standalone load
        raise RuntimeError(
            "goodput live attribution needs the in-process trace plane; "
            "this module was loaded standalone — use attribute_events() "
            "on an exported event list instead")
    return _trace


# incremental accumulator for the live surface: a scrape must not copy
# the whole (up to 1M-event) trace buffer under the tracer's lock on
# every tick — only the tail since the last cursor is fetched, and only
# the goodput-classified intervals are retained.  Reset()s of the trace
# buffer are detected by the cursor running past the buffer length.
_acc_lock = threading.Lock()
_acc = {"cursor": 0, "generation": 0, "intervals": [], "first_work": None}


def _live_intervals(tr):
    """(classified intervals so far, the run's first classified
    activity) — consuming only the NEW tail of the trace buffer."""
    with _acc_lock:
        gen = tr.buffer_generation()
        if gen != _acc["generation"]:               # buffer was reset
            _acc["cursor"] = 0
            _acc["generation"] = gen
            _acc["intervals"] = []
            _acc["first_work"] = None
        new = tr.get_events(_acc["cursor"])
        _acc["cursor"] += len(new)
        if new:
            intervals, ev_lo, _ = _intervals_of(new)
            _acc["intervals"].extend(intervals)
            fresh_first = min((s for s, _, _ in intervals), default=None)
            if fresh_first is not None \
                    and (_acc["first_work"] is None
                         or fresh_first < _acc["first_work"]):
                _acc["first_work"] = fresh_first
            # bound retention when a rolling window is configured: only
            # intervals that can still enter a future window are kept
            w = _flag_window_s()
            if w:
                cut = tr.elapsed_us() - w * 1e6
                _acc["intervals"] = [iv for iv in _acc["intervals"]
                                     if iv[1] >= cut]
        return list(_acc["intervals"]), _acc["first_work"]


def _flag_window_s() -> float:
    try:
        from . import core
        return float(core.get_flag("goodput_window_s", 0.0) or 0.0)
    except Exception:               # noqa: BLE001 — flags are advisory
        return 0.0


def snapshot(window_s: Optional[float] = None,
             t0_us: Optional[float] = None,
             include_segments: bool = False) -> Dict[str, Any]:
    """Attribution over the live trace buffer, up to *now*.

    ``window_s`` restricts to the trailing window (rolling goodput;
    default = ``FLAGS_goodput_window_s``, a bounded 600s so scrapes
    stay O(window) on long runs; pass 0 for the whole run back to the
    trace epoch, where init time shows up as restart_init).  ``t0_us``
    pins an explicit start (e.g. "since this gate began").  A window
    that starts after the run's first instrumented activity charges its
    uncovered head to idle, never to restart_init.

    Note: the live accumulator prunes intervals that can no longer
    enter the FLAG-configured window, so on a run older than
    ``FLAGS_goodput_window_s`` a wider explicit query here is
    approximate — for exact whole-run attribution export the timeline
    and use :func:`attribute_events` (or set the flag to 0 up front).
    """
    tr = _require_trace()
    t1 = tr.elapsed_us()
    if t0_us is None:
        if window_s is None:
            window_s = _flag_window_s()
        t0_us = max(0.0, t1 - window_s * 1e6) if window_s else 0.0
    intervals, first_work = _live_intervals(tr)
    rep = _attribute(intervals, None, None, t0_us=t0_us, t1_us=t1,
                     include_segments=include_segments,
                     run_first_work_us=first_work)
    dropped = tr.dropped_count()
    if dropped:
        # the trace buffer hit FLAGS_trace_max_events and is dropping
        # new spans: attribution is blind to recent activity (new time
        # decays toward "idle").  Never let that masquerade as a real
        # goodput collapse — flag it, and let publish_gauges surface
        # goodput.degraded for alerting.
        rep["degraded"] = True
        rep["dropped_events"] = dropped
    return rep


def publish_gauges(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Publish one attribution report to the ``goodput.*`` gauges (the
    single place the gauge set is defined — the traced and
    metrics-fallback paths must publish identically)."""
    tr = _require_trace()
    m = tr.metrics()
    m.gauge("goodput.ratio").set(rep["ratio"])
    m.gauge("goodput.wall_seconds").set(rep["wall_seconds"])
    m.gauge("goodput.degraded").set(1.0 if rep.get("degraded") else 0.0)
    for b, v in rep["buckets"].items():
        m.gauge(f"goodput.{b}_seconds").set(v)
    return rep


def update_gauges(window_s: Optional[float] = None) -> Dict[str, Any]:
    """Refresh the ``goodput.*`` gauges from a fresh :func:`snapshot` and
    return the report.  Called by the metrics HTTP handler on every
    scrape and by the JSONL snapshot writer each tick — the gauges are a
    *view* of the event stream, never a second source of truth."""
    return publish_gauges(snapshot(window_s=window_s))


def from_metrics(wall_s: float) -> Dict[str, Any]:
    """Coarse attribution from histogram totals, for runs with tracing
    OFF (bench children report goodput without paying for the event
    stream).  The named badput buckets are measured; the remainder is
    credited to device_compute (idle is indistinguishable without
    spans), so the ratio is an upper bound — labeled
    ``source="metrics"``."""
    tr = _require_trace()
    m = tr.metrics()

    def _total(name):
        # read-only: a scrape must not register empty histograms as a
        # side effect (dead summary families in every later export)
        inst = m.get(name)
        return float(inst.stats()["total"]) \
            if isinstance(inst, tr.Histogram) else 0.0

    wall_s = max(0.0, float(wall_s))
    buckets = {b: 0.0 for b in BUCKETS}
    buckets["compile"] = _total("executor.compile_seconds")
    buckets["host_input_wait"] = _total("loader.consume_wait_seconds")
    buckets["checkpoint_stall"] = _total("ckpt.stall_seconds")
    buckets["preemption_drain"] = _total("elastic.drain_seconds")
    buckets["restart_init"] = _total("ckpt.restore_seconds")
    buckets["ps_pull_wait"] = _total("ps.pull_wait_seconds")
    badput = sum(buckets.values())
    if badput > wall_s > 0.0:           # totals can exceed a sub-run wall
        scale = wall_s / badput
        buckets = {b: v * scale for b, v in buckets.items()}
        badput = wall_s
    buckets["device_compute"] = max(0.0, wall_s - badput)
    return {
        "wall_seconds": wall_s,
        "buckets": buckets,
        "ratio": (buckets["device_compute"] / wall_s) if wall_s else 0.0,
        "classified_spans": 0,
        "source": "metrics",
    }
