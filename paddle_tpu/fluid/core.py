"""Platform layer: Place/device identity + global flags.

Reference: paddle/fluid/platform/place.h:26-62 (CPUPlace/CUDAPlace variants),
device_context.h:60-568 (per-device handle bundles), flags.cc (runtime gflags).
TPU-native: a Place names a JAX device; there is no per-place stream/handle
bundle because XLA/PJRT owns streams and HBM — the DeviceContext analog is
just the resolved `jax.Device` plus the process-wide compilation cache that
executor.py maintains.
"""
from __future__ import annotations

import os as _os
from typing import Dict, Optional

# stdlib-only module; single source of truth for trace env parsing and the
# default timeline path (import order with this package is cycle-safe:
# trace only touches core lazily, inside functions)
from . import trace as _trace


class Place:
    device_kind = "cpu"
    device_id = 0

    def jax_device(self):
        import jax
        devs = [d for d in jax.devices() if self._match(d)]
        if not devs:
            # fall back to whatever the default backend offers (e.g. running
            # TPU-targeted code on the CPU backend in tests)
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def _match(self, d) -> bool:
        return True

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == getattr(other, "device_id", 0))

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    device_kind = "cpu"

    def _match(self, d):
        return d.platform == "cpu"


class TPUPlace(Place):
    """The CUDAPlace analog (place.h:62): names one accelerator chip."""
    device_kind = "tpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def _match(self, d):
        return d.platform != "cpu"


# fluid alias: code written against the reference uses CUDAPlace; on this
# framework it resolves to the accelerator (TPU) as well.
CUDAPlace = TPUPlace


class TPUPinnedPlace(CPUPlace):
    """Host staging buffers; XLA handles pinning internally."""


def is_compiled_with_tpu() -> bool:
    import jax
    return any(d.platform != "cpu" for d in jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def get_device_count() -> int:
    import jax
    return jax.device_count()


# ---------------------------------------------------------------------------
# global flags (platform/flags.cc analog; settable from Python like
# global_value_getter_setter.cc). Only flags meaningful on TPU are kept.
# ---------------------------------------------------------------------------
_FLAGS: Dict[str, object] = {
    "check_nan_inf": False,          # per-fetch NaN scan (operator.cc:1149 analog)
    "benchmark": False,
    "paddle_num_threads": 1,
    "use_donated_buffers": True,     # buffer donation == inplace/GC knobs
    "jit_cache_size": 128,
    "deterministic": False,
    # TPU hardware RNG (XLA RngBitGenerator) instead of threefry for dropout
    # and *_random ops.  The reference uses curand Philox per device
    # (platform/ *generator*); counter-based threefry on TPU costs ~3x a BERT
    # forward in dropout masks alone, so hardware RNG is the default.  Set
    # FLAGS_deterministic_rng=True for threefry (bit-reproducible across
    # backends, like cudnn_deterministic in platform/flags.cc:98).
    "deterministic_rng": False,
    # 64-bit integer feeds on device.  Off by default (jax x64 mode also
    # promotes float64, hurting TPU perf); the framework's CTR paths keep
    # full-width uint64 feasigns HOST-side (PS/Box tiers translate ids to
    # indices in numpy), so device programs rarely need 64-bit ints.  The
    # executor raises on silently-truncating feeds instead of corrupting.
    "enable_x64": False,
    # observability plane (fluid/trace.py): host-side structured tracing.
    # Env defaults let `FLAGS_enable_trace=1 python train.py` produce a
    # chrome://tracing timeline at FLAGS_trace_path with no code changes;
    # trace.enable()/disable()/set_path() keep these mirror values in sync.
    "enable_trace": _trace.enabled(),
    "trace_path": _trace.get_path(),
    # recompile hygiene (fluid/compile_cache.py).  shape_bucketing pads
    # ragged leading batch dims up to a bucket edge so a tail batch reuses
    # a cached executable; bucket_edges=None means powers of two.  The
    # persistent cache dir survives process restarts (jax compilation
    # cache + program-level index).  Env defaults let
    # `FLAGS_shape_bucketing=1 python train.py` opt in with no code change.
    "shape_bucketing": _os.environ.get(
        "FLAGS_shape_bucketing", "").strip().lower() in _trace._TRUE_STRINGS,
    "shape_bucket_edges": _os.environ.get("FLAGS_shape_bucket_edges") or None,
    "persistent_cache_dir": _os.environ.get(
        "FLAGS_persistent_cache_dir") or None,
    # in-memory executable cache bound (executor LRU; 0 disables eviction)
    "executor_cache_capacity": int(_os.environ.get(
        "FLAGS_executor_cache_capacity", "128")),
    # recompile-storm warning: N compile misses within the window (seconds)
    # emit a trace event with shape/bucket attribution; 0 disables
    "recompile_warn_threshold": int(_os.environ.get(
        "FLAGS_recompile_warn_threshold", "8")),
    "recompile_warn_window": float(_os.environ.get(
        "FLAGS_recompile_warn_window", "60")),
    # async step pipeline (fluid/async_pipeline.py, docs/performance.md).
    # max_inflight_steps bounds how many dispatched steps may be
    # outstanding before the runner blocks on the oldest one's fetches
    # (also caps the Prefetcher's device-staged queue at inflight+1);
    # steps_per_dispatch=K compiles a lax.scan over K stacked microbatches
    # so one Python dispatch drives K device steps.
    "max_inflight_steps": int(_os.environ.get(
        "FLAGS_max_inflight_steps", "2")),
    "steps_per_dispatch": int(_os.environ.get(
        "FLAGS_steps_per_dispatch", "1")),
    # elastic checkpoint plane (fluid/checkpoint.py, docs/checkpointing.md).
    # keep_last bounds retention (newest K checkpoints); keep_every
    # additionally pins every Nth step (0 = off); async routes snapshot
    # writes to a background thread so the step window never blocks;
    # shard_bytes caps per-shard file size.
    "checkpoint_keep_last": int(_os.environ.get(
        "FLAGS_checkpoint_keep_last", "3")),
    "checkpoint_keep_every": int(_os.environ.get(
        "FLAGS_checkpoint_keep_every", "0")),
    "checkpoint_async": _os.environ.get(
        "FLAGS_checkpoint_async", "1").strip().lower()
        in _trace._TRUE_STRINGS,
    "checkpoint_shard_bytes": int(_os.environ.get(
        "FLAGS_checkpoint_shard_bytes", str(64 << 20))),
    # live metrics export plane (fluid/metrics_export.py,
    # docs/observability.md "Goodput & device memory").  metrics_port
    # serves /metrics (Prometheus text) + /goodput (JSON) on a daemon
    # thread (0 = off); the snapshot path/interval append periodic JSONL
    # metrics rows for headless runs.  Both are exact no-ops when unset.
    "metrics_port": int(_os.environ.get("FLAGS_metrics_port", "0") or 0),
    # bind address for the export server.  Localhost by default: the
    # registry names executables/checkpoints — serving beyond the host
    # is an explicit opt-in (FLAGS_metrics_host=0.0.0.0 for fleet
    # scrapers).
    "metrics_host": _os.environ.get("FLAGS_metrics_host", "127.0.0.1"),
    "metrics_snapshot_path": _os.environ.get(
        "FLAGS_metrics_snapshot_path") or None,
    "metrics_snapshot_interval_s": float(_os.environ.get(
        "FLAGS_metrics_snapshot_interval_s", "60") or 60),
    # device truth (fluid/device_stats.py): AOT cost/memory analysis of
    # every freshly compiled executable.  "auto" = follows tracing;
    # True/False force it.  The capture pays a second (only partially
    # cached) XLA compile per compile MISS and nothing per step — which
    # is why serving /metrics alone does NOT opt a run in.
    "device_cost_analysis": _os.environ.get(
        "FLAGS_device_cost_analysis", "auto"),
    # serving plane (paddle_tpu/serving/, docs/serving.md).  max_batch
    # caps the rows per coalesced device batch; max_wait_us is the
    # batch-formation deadline (dispatch a partial batch rather than
    # hold a request longer); queue_depth bounds the admission queue
    # (a full queue REJECTS at submit — backpressure, not OOM);
    # default_deadline_ms rejects requests that queue longer than their
    # deadline (0 = no deadline unless the request carries one).
    "serving_max_batch": int(_os.environ.get(
        "FLAGS_serving_max_batch", "32")),
    "serving_max_wait_us": int(_os.environ.get(
        "FLAGS_serving_max_wait_us", "2000")),
    "serving_queue_depth": int(_os.environ.get(
        "FLAGS_serving_queue_depth", "256")),
    "serving_default_deadline_ms": float(_os.environ.get(
        "FLAGS_serving_default_deadline_ms", "0") or 0),
    # serving fleet (paddle_tpu/serving/fleet.py, docs/serving.md
    # "Serving fleet"): the router polls each replica's compact /stats
    # every scrape_interval_s; missed_scrapes consecutive failed polls
    # eject an unreachable replica (a stalled/breached /healthz verdict
    # ejects on the FIRST scrape that carries it)
    "fleet_scrape_interval_s": float(_os.environ.get(
        "FLAGS_fleet_scrape_interval_s", "1.0") or 1.0),
    "fleet_missed_scrapes": int(_os.environ.get(
        "FLAGS_fleet_missed_scrapes", "3") or 3),
    # rolling window for the goodput.ratio gauge and /goodput (seconds;
    # 0 = whole run).  A bounded default keeps scrape cost O(window) on
    # long traced runs: the live accumulator prunes intervals that can
    # no longer enter a window, so attribution never re-sweeps hours of
    # history per scrape.  Whole-run attribution stays available
    # explicitly (goodput.snapshot(window_s=0) / attribute_events on an
    # exported timeline).
    "goodput_window_s": float(_os.environ.get(
        "FLAGS_goodput_window_s", "600") or 600),
    # forensic plane (fluid/flight_recorder.py + fluid/watchdog.py,
    # docs/observability.md "Flight recorder & post-mortems").  The
    # flight recorder is a bounded ring of wide events (one per step /
    # served request) that runs even with tracing OFF; the watchdog is
    # a daemon that detects stalled progress / sustained p99 breach /
    # crash+OOM and dumps one atomic diagnostic bundle per incident
    # into diagnostic_dir (tools/diagnose.py renders them).
    "flight_recorder": _os.environ.get(
        "FLAGS_flight_recorder", "1").strip().lower()
        in _trace._TRUE_STRINGS,
    "flight_recorder_events": int(_os.environ.get(
        "FLAGS_flight_recorder_events", "4096") or 4096),
    "watchdog": _os.environ.get(
        "FLAGS_watchdog", "").strip().lower() in _trace._TRUE_STRINGS,
    "watchdog_interval_s": float(_os.environ.get(
        "FLAGS_watchdog_interval_s", "1.0") or 1.0),
    # stalled = work outstanding (inflight / step-in-progress / serving
    # queue) with zero completions for this long; live compiles and
    # elastic drains count as liveness so a long legit XLA compile
    # never false-positives
    "watchdog_stall_s": float(_os.environ.get(
        "FLAGS_watchdog_stall_s", "30") or 30),
    # sustained-p99 breach: threshold in ms (0 = off) held for N
    # consecutive watchdog windows
    "watchdog_p99_ms": float(_os.environ.get(
        "FLAGS_watchdog_p99_ms", "0") or 0),
    "watchdog_breach_windows": int(_os.environ.get(
        "FLAGS_watchdog_breach_windows", "3") or 3),
    "diagnostic_dir": _os.environ.get("FLAGS_diagnostic_dir") or None,
    # how many trailing trace events a bundle embeds
    "diagnostic_trace_tail": int(_os.environ.get(
        "FLAGS_diagnostic_trace_tail", "5000") or 5000),
    # chaos/robustness plane (distributed/faultline.py + ps/rpc.py +
    # serving/fleet.py, docs/robustness.md).  faultline installs a
    # seeded socket-level fault-injection schedule (JSON spec or @path;
    # replica subprocesses inherit it via the env var).  The rpc_* knobs
    # bound the hardened framing: max_frame_bytes rejects garbage/
    # hostile length prefixes before allocation, retries/backoff_ms
    # shape the client retry policy (exponential + jitter), and
    # dedup_window sizes the server's req_id window that makes retried
    # non-idempotent pushes exactly-once.  fleet_breaker_* shape the
    # per-replica circuit breaker (consecutive transport failures to
    # open; cooldown before the half-open probe; 0 failures disables).
    "faultline": _os.environ.get("FLAGS_faultline") or None,
    "rpc_max_frame_bytes": int(_os.environ.get(
        "FLAGS_rpc_max_frame_bytes", str(1 << 30))),
    "rpc_retries": int(_os.environ.get("FLAGS_rpc_retries", "3")),
    "rpc_backoff_ms": float(_os.environ.get(
        "FLAGS_rpc_backoff_ms", "25")),
    "rpc_dedup_window": int(_os.environ.get(
        "FLAGS_rpc_dedup_window", "1024")),
    "fleet_breaker_failures": int(_os.environ.get(
        "FLAGS_fleet_breaker_failures", "5") or 5),
    "fleet_breaker_cooldown_s": float(_os.environ.get(
        "FLAGS_fleet_breaker_cooldown_s", "3.0") or 3.0),
    # sharded parameter server (distributed/ps/sharded.py,
    # docs/parameter_server.md).  ps_staleness bounds how many async
    # pushes may be outstanding before a pull fences (0 = fully
    # synchronous = bit-parity with the single-table baseline);
    # ps_hot_rows caps each shard's hot RAM tier (0 = untired);
    # ps_snapshot_every takes an incremental snapshot after every N
    # logged mutations (0 = manual snapshots only); ps_wal_fsync forces
    # fsync per WAL record (off: flush to the OS, which survives process
    # SIGKILL — the restart drill — but not machine loss);
    # ps_shard_vnodes sets virtual nodes per shard on the hash ring.
    "ps_staleness": int(_os.environ.get("FLAGS_ps_staleness", "0") or 0),
    "ps_hot_rows": int(_os.environ.get("FLAGS_ps_hot_rows", "0") or 0),
    "ps_snapshot_every": int(_os.environ.get(
        "FLAGS_ps_snapshot_every", "0") or 0),
    "ps_wal_fsync": _os.environ.get(
        "FLAGS_ps_wal_fsync", "0") not in ("0", "", "false", "False"),
    "ps_shard_vnodes": int(_os.environ.get(
        "FLAGS_ps_shard_vnodes", "64") or 64),
    # kernel tier (fluid/passes/kernel_tier.py, ops/attention.py): minimum
    # sequence length before attention dispatches to the Pallas flash
    # kernel.  Default 1024 — measured on the round-3 BERT sweep: at seq
    # 512 the flash kernel loses end-to-end (23.4% vs 34.8% MFU) because
    # XLA's softmax(QK^T)V fusion is still near-roofline there; the knob
    # lets bench.py/tpu_watch sweep the real crossover per chip and the
    # future auto-tuner (ROADMAP item 5) own the value.
    "pallas_min_seq": int(_os.environ.get(
        "FLAGS_pallas_min_seq", "1024") or 1024),
    # profile-guided self-tuning runtime (fluid/autotune.py,
    # docs/performance.md "Auto-tuning"): auto_tune arms BOTH surfaces
    # (executor programs tune once per fingerprint on first run; serving
    # engines get a flag-started online tuner, reconciled by
    # autotune.apply_flags on mid-run flips); auto_tune_probe_steps is
    # the probe-window length in real steps; auto_tune_dir re-roots the
    # persisted-config store away from FLAGS_persistent_cache_dir;
    # auto_tune_hbm_budget_mb pins the OOM-rejection budget (0 = ask the
    # backend for bytes_limit); auto_tune_max_candidates bounds the
    # proposal stream per search.
    "auto_tune": _os.environ.get(
        "FLAGS_auto_tune", "0") not in ("0", "", "false", "False"),
    "auto_tune_probe_steps": int(_os.environ.get(
        "FLAGS_auto_tune_probe_steps", "8") or 8),
    "auto_tune_dir": _os.environ.get("FLAGS_auto_tune_dir") or None,
    "auto_tune_hbm_budget_mb": float(_os.environ.get(
        "FLAGS_auto_tune_hbm_budget_mb", "0") or 0),
    "auto_tune_max_candidates": int(_os.environ.get(
        "FLAGS_auto_tune_max_candidates", "16") or 16),
}


def _apply_prng_impl(deterministic):
    """Apply the PRNG choice.  `deterministic=None` (import-time default)
    defers to a JAX_DEFAULT_PRNG_IMPL env override; an explicit set_flags
    call always wins."""
    import os
    if deterministic is None and os.environ.get("JAX_DEFAULT_PRNG_IMPL"):
        return
    impl = "threefry2x32" if deterministic else "rbg"
    try:
        import jax
        jax.config.update("jax_default_prng_impl", impl)
    except Exception as e:                   # noqa: BLE001 — never block import,
        # but NEVER silently: a swallowed error here once left dropout on
        # threefry and cost ~25% MFU for a full round (see STATUS.md)
        import sys
        print(f"paddle_tpu: WARNING: could not set PRNG impl {impl!r}: "
              f"{type(e).__name__}: {e} — dropout/random ops will use the "
              f"jax default (threefry), which is ~3x slower on TPU",
              file=sys.stderr)


_apply_prng_impl(None)


def set_flags(flags: Dict[str, object]):
    for k, v in flags.items():
        k = k.removeprefix("FLAGS_")
        _FLAGS[k] = v
        if k == "deterministic_rng":
            _apply_prng_impl(bool(v))
        elif k == "enable_x64":
            import jax
            jax.config.update("jax_enable_x64", bool(v))
        elif k == "enable_trace":
            from . import trace
            (trace.enable if v else trace.disable)()
        elif k == "trace_path":
            from . import trace
            trace.set_path(str(v))
        elif k == "shape_bucket_edges":
            from . import compile_cache
            _FLAGS[k] = compile_cache.normalize_edges(v)
        elif k == "persistent_cache_dir" and v:
            # eagerly wire jax's compilation cache so compiles between this
            # call and the first executor run also persist
            from . import compile_cache
            compile_cache.persistent_cache()
        elif k in ("metrics_port", "metrics_host", "metrics_snapshot_path",
                   "metrics_snapshot_interval_s"):
            # reconcile the export surfaces with the new flag values
            # (start, restart on a changed port/path, or stop on unset)
            from . import metrics_export
            metrics_export.apply_flags()
        elif k in ("flight_recorder", "flight_recorder_events"):
            from . import flight_recorder
            flight_recorder.configure(
                capacity=int(_FLAGS.get("flight_recorder_events", 4096)
                             or 4096),
                enabled=bool(_FLAGS.get("flight_recorder", True)))
        elif k == "watchdog":
            from . import watchdog
            watchdog.apply_flags()
        elif k == "faultline":
            # install/replace/uninstall the fault-injection schedule
            from ..distributed import faultline
            faultline.apply_flags()
        elif k in ("auto_tune", "auto_tune_probe_steps", "auto_tune_dir"):
            # reconcile the self-tuning runtime with the new flag values
            # (start flag-started serving tuners / stop ONLY flag-started
            # ones — the metrics-export reconciliation contract)
            from . import autotune
            autotune.apply_flags()


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: _FLAGS.get(n.removeprefix("FLAGS_")) for n in names}


def get_flag(name: str, default=None):
    return _FLAGS.get(name, default)


# ---------------------------------------------------------------------------
# crash/stuck diagnostics (platform/init.cc:257 InitGLOG signal-handler
# analog).  The reference installs glog's FailureSignalHandler to dump C++
# stacks on SIGSEGV/SIGABRT; here faulthandler dumps every thread's Python
# stack on fatal signals, and SIGUSR1 gives a live dump for hung runs
# (stuck collective, wedged TPU tunnel) without killing the process.
# ---------------------------------------------------------------------------
_signal_handlers_installed = False


def init_signal_handlers():
    global _signal_handlers_installed
    if _signal_handlers_installed:
        return
    import faulthandler
    import signal
    import sys
    try:
        faulthandler.enable(file=sys.stderr, all_threads=True)
        if hasattr(signal, "SIGUSR1") and hasattr(faulthandler, "register"):
            faulthandler.register(signal.SIGUSR1, file=sys.stderr,
                                  all_threads=True, chain=True)
        _signal_handlers_installed = True
    except (ValueError, OSError, RuntimeError):
        pass        # non-main thread or exotic embedding: run without dumps


class Scope:
    """name -> device array map (framework/scope.h analog, flat)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self.parent = parent

    def var(self, name):
        return self._vars.get(name)

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def set_var(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = prev


# ---------------------------------------------------------------------------
# custom-op loading (framework.py:5517 load_op_library + op_function_generator
# analog).  TPU-native: a custom op is a lowering-rule plugin —
#   * .py module: calls ops.registry.register_op directly (the first-class
#     path; pallas kernels plug in here too)
#   * .so library: C ABI kernels exposed through jax.pure_callback (host
#     execution — arbitrary native code cannot run ON the TPU; the
#     reference's custom CUDA kernels map to host callbacks or pallas)
# ---------------------------------------------------------------------------

def load_op_library(path: str):
    """Load a custom-op plugin; returns the list of newly registered ops."""
    import importlib.util
    import os as _os
    from ..ops import registry as _registry

    before = set(_registry.all_ops())
    if str(path).endswith(".py"):
        name = f"paddle_tpu_custom_{_os.path.basename(path)[:-3]}"
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    elif str(path).endswith(".so"):
        _load_native_op_library(path)
    else:
        raise ValueError(f"op library must be .py or .so, got {path}")
    new = sorted(set(_registry.all_ops()) - before)
    for t in new:                      # plugin ops sit outside the
        _registry.get_op(t).custom = True   # catalog/grad-audit contract
    return new


def _load_native_op_library(path: str):
    """C-ABI convention: the .so exports `pt_op_names()` returning a
    comma-separated op list, and per op `void <name>_run(const float* in,
    float* out, int64_t n)` — an elementwise f32 kernel wrapped into a
    lowering via jax.pure_callback."""
    import ctypes
    import jax
    import numpy as _np
    from ..ops.registry import register_op, has_op

    lib = ctypes.CDLL(path)
    lib.pt_op_names.restype = ctypes.c_char_p
    names = lib.pt_op_names().decode().split(",")
    for name in [n for n in names if n]:
        fn = getattr(lib, f"{name}_run")
        fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                       ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

        def _host_kernel(x, _fn=fn):
            x = _np.ascontiguousarray(x, _np.float32)
            out = _np.empty_like(x)
            _fn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                x.size)
            return out

        if has_op(name):
            continue

        def _lowering(ins, attrs, ctx, _k=_host_kernel):
            import jax.numpy as jnp
            x = ins["X"][0]
            out = jax.pure_callback(
                _k, jax.ShapeDtypeStruct(x.shape, jnp.float32),
                x.astype(jnp.float32))
            return {"Out": [out]}

        # pure_callback has no JVP/transpose rule — never differentiate
        register_op(name, _lowering, differentiable=False)
