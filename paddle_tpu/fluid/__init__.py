"""paddle_tpu.fluid — the fluid-compatible front-end, TPU-native underneath.

Public surface per python/paddle/fluid/__init__.py (SURVEY A.6): Program /
Executor / layers / optimizer / backward / io / initializer / ParamAttr ...
"""
from .. import ops as _ops  # registers all lowering rules

from . import core
from .core import (CPUPlace, TPUPlace, CUDAPlace, TPUPinnedPlace, Scope,
                   global_scope, scope_guard, set_flags, get_flags,
                   is_compiled_with_cuda, is_compiled_with_tpu)
from .framework import (Program, Variable, Parameter, program_guard,
                        default_main_program, default_startup_program,
                        in_dygraph_mode, convert_dtype,
                        cpu_places, device_guard)
from .executor import Executor
from . import async_pipeline
from .async_pipeline import AsyncStepRunner, FetchHandle, StepFuture
from .backward import append_backward, gradients
from . import initializer
from .initializer import Constant, Uniform, Normal, Xavier, MSRA
from .param_attr import ParamAttr, WeightNormParamAttr
from . import layers
from . import optimizer
from . import regularizer
from . import clip
from .layers.tensor import data
from . import io
from .io import save_persistables, load_persistables, save_params, load_params
from . import checkpoint
from .checkpoint import CheckpointManager
from . import nets
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import passes
from . import autotune
from . import dygraph
from ..contrib import memory_usage_calc as _muc  # noqa: F401 (cycle guard)
from .. import contrib                            # fluid.contrib alias
from .. import incubate                           # fluid.incubate alias
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from .data_feeder import DataFeeder
from . import metrics
from . import dataset
from .dataset import DatasetFactory, InMemoryDataset, QueueDataset
from . import trace
from . import goodput
from . import flight_recorder
from . import profiler
from . import monitor
from .reader import DataLoader

core.init_signal_handlers()

# SLO watchdog (fluid/watchdog.py): env-gated like the export plane —
# `FLAGS_watchdog=1 python serve.py` arms stall/breach/crash/OOM
# detection with post-mortem diagnostic bundles, no code changes.
if core.get_flag("watchdog"):
    try:
        from . import watchdog as _watchdog
        _watchdog.apply_flags()
    except Exception as _e:             # noqa: BLE001 — forensics are
        import sys as _sys              # advisory, never block import
        print(f"paddle_tpu: WARNING: watchdog failed to start: "
              f"{type(_e).__name__}: {_e}", file=_sys.stderr)

# live metrics export (fluid/metrics_export.py): env-gated like the trace
# plane — `FLAGS_metrics_port=9090 python train.py` serves /metrics with
# no code changes, and a snapshot path starts the JSONL writer.  Lazy:
# the module is only imported when a flag asks for it.
if core.get_flag("metrics_port") or core.get_flag("metrics_snapshot_path"):
    try:
        from . import metrics_export as _metrics_export
        _metrics_export.apply_flags()
    except Exception as _e:             # noqa: BLE001 — export is advisory
        import sys as _sys
        print(f"paddle_tpu: WARNING: metrics export failed to start: "
              f"{type(_e).__name__}: {_e}", file=_sys.stderr)


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()


embedding = layers.embedding
one_hot = layers.one_hot

# --- reference fluid module surface (round-4 global __all__ closure) ---
from . import average                 # noqa: E402,F401
from . import communicator            # noqa: E402,F401
from . import data_feed_desc          # noqa: E402,F401
from .data_feed_desc import DataFeedDesc  # noqa: E402,F401
from . import dataloader              # noqa: E402,F401
from . import default_scope_funcs     # noqa: E402,F401
from . import device_worker           # noqa: E402,F401
from . import trainer_desc            # noqa: E402,F401
from . import trainer_factory         # noqa: E402,F401
from . import entry_attr              # noqa: E402,F401
from .entry_attr import ProbabilityEntry, CountFilterEntry  # noqa: E402,F401
from . import evaluator               # noqa: E402,F401
from . import generator               # noqa: E402,F401
from .generator import Generator      # noqa: E402,F401
from . import install_check           # noqa: E402,F401
from . import layer_helper_base       # noqa: E402,F401
from .layer_helper_base import LayerHelperBase  # noqa: E402,F401
from . import lod_tensor              # noqa: E402,F401
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor  # noqa: E402,F401
from . import log_helper              # noqa: E402,F401
from . import parallel_executor       # noqa: E402,F401
from .parallel_executor import ParallelExecutor  # noqa: E402,F401
from . import unique_name             # noqa: E402,F401
from . import wrapped_decorator       # noqa: E402,F401
from . import distributed             # noqa: E402,F401
from .average import WeightedAverage  # noqa: E402,F401
from .communicator import Communicator, LargeScaleKV  # noqa: E402,F401
from .framework import (cuda_places, cpu_places,  # noqa: E402,F401
                        cuda_pinned_places, require_version,
                        load_op_library)
from .initializer import set_global_initializer  # noqa: E402,F401
