"""paddle_tpu.fluid — the fluid-compatible front-end, TPU-native underneath.

Public surface per python/paddle/fluid/__init__.py (SURVEY A.6): Program /
Executor / layers / optimizer / backward / io / initializer / ParamAttr ...
"""
from .. import ops as _ops  # registers all lowering rules

from . import core
from .core import (CPUPlace, TPUPlace, CUDAPlace, TPUPinnedPlace, Scope,
                   global_scope, scope_guard, set_flags, get_flags,
                   is_compiled_with_cuda, is_compiled_with_tpu)
from .framework import (Program, Variable, Parameter, program_guard,
                        default_main_program, default_startup_program,
                        in_dygraph_mode, unique_name, convert_dtype,
                        cpu_places, device_guard)
from .executor import Executor
from .backward import append_backward, gradients
from . import initializer
from .initializer import Constant, Uniform, Normal, Xavier, MSRA
from .param_attr import ParamAttr, WeightNormParamAttr
from . import layers
from . import optimizer
from . import regularizer
from . import clip
from .layers.tensor import data
from . import io
from .io import save_persistables, load_persistables, save_params, load_params
from . import nets
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import dygraph
from ..contrib import memory_usage_calc as _muc  # noqa: F401 (cycle guard)
from .. import contrib                            # fluid.contrib alias
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from .data_feeder import DataFeeder
from . import metrics
from . import dataset
from .dataset import DatasetFactory, InMemoryDataset, QueueDataset
from . import profiler
from . import monitor
from .reader import DataLoader

core.init_signal_handlers()


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()


embedding = layers.embedding
one_hot = layers.one_hot
