"""Generated protobuf bindings for the ProgramDesc wire format.

`framework_pb2.py` is checked in (generated from `framework.proto`, see
that file for the interop contract); regenerate with:

    protoc --python_out=paddle_tpu/fluid/proto \
        -I paddle_tpu/fluid/proto paddle_tpu/fluid/proto/framework.proto
"""
from . import framework_pb2  # noqa: F401
