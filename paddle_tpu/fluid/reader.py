"""DataLoader: host input pipeline with background prefetch.

Reference: python/paddle/fluid/reader.py — DataLoader.from_generator:418
feeds a C++ BlockingQueue reader op; buffered_reader.cc double-buffers
batches onto the GPU with cuda events.  TPU-native: a background thread
pipeline that (a) runs the user generator, (b) converts to numpy, and
(c) jax.device_put's the NEXT batch while the current step runs — the
double-buffer prefetch analog (device transfer overlaps compute because XLA
dispatch is async).  `num_workers > 0` runs dataset/transform work in a
fork worker-process pool (dataloader_iter.py: per-worker index queues,
shared data queue, in-order reorder buffer); `use_multiprocess=True` on
the generator path moves the whole generator into a streamer process.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Callable, Iterable, List, Optional

import numpy as np


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        return GeneratorLoader(feed_list, capacity, use_double_buffer,
                               iterable, return_list, drop_last,
                               use_multiprocess=use_multiprocess)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        return _DatasetLoader(dataset, drop_last)

    def __init__(self, dataset=None, feed_list=None, places=None,
                 return_list=False, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, timeout=0,
                 worker_init_fn=None, prefetch_factor=2):
        # map-style dataset path (2.0 DataLoader)
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.return_list = return_list
        self.feed_list = feed_list
        self.batch_sampler = batch_sampler
        if batch_sampler is not None and (shuffle or drop_last
                                          or batch_size != 1):
            # reference DataLoader asserts the same: the sampler OWNS
            # batching — a silently ignored drop_last would hand a ragged
            # final batch to a fixed-shape jit step
            raise ValueError(
                "DataLoader: batch_sampler is mutually exclusive with "
                "batch_size/shuffle/drop_last — configure them on the "
                "sampler")
        self.num_workers = int(num_workers)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor

    def _index_batches(self):
        if self.batch_sampler is not None:
            # paddle.io sampler algebra decides the batches (incl.
            # DistributedBatchSampler rank sharding)
            return [np.asarray(b) for b in self.batch_sampler]
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.shuffle(idx)
        n = len(idx)
        bs = self.batch_size
        end = n - n % bs if self.drop_last else n
        return [idx[i:i + bs] for i in range(0, end, bs)]

    @property
    def bucket_edges(self):
        """The exact batch sizes this loader will emit — full batches of
        ``batch_size`` plus (with ``drop_last=False``) the one deterministic
        tail — advertised so the executor's shape-bucketing layer
        (FLAGS_shape_bucketing, program hint ``bucket_edges``) compiles one
        executable per size instead of discovering the tail the hard way.
        None when a batch_sampler owns batching (sizes unknown here)."""
        if self.batch_sampler is not None:
            return None
        sizes = {int(self.batch_size)}
        if not self.drop_last:
            tail = len(self.dataset) % self.batch_size
            if tail:
                sizes.add(int(tail))
        return tuple(sorted(sizes))

    def __iter__(self):
        batches = self._index_batches()
        if self.num_workers > 0:
            from .dataloader_iter import MultiprocessMapIter
            yield from MultiprocessMapIter(
                batches, self.dataset, self.collate_fn, self.num_workers,
                worker_init_fn=self.worker_init_fn, timeout=self.timeout,
                prefetch_factor=self.prefetch_factor)
            return
        for b in batches:
            yield self.collate_fn([self.dataset[int(j)] for j in b])

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size

    def stacked(self, k, mesh=None, capacity=2):
        """K-step staging hook for the async pipeline
        (`FLAGS_steps_per_dispatch`): yield lists of ``k`` consecutive
        batches, `jax.device_put` on the Prefetcher's producer thread —
        sharded along the data-parallel mesh axis when `mesh` is given —
        so H2D transfer of group t+1 overlaps the device steps of group
        t.  Feed the groups to `Executor.run_scan` or submit each member
        to an `AsyncStepRunner(steps_per_dispatch=k)`."""
        return _stacked_prefetcher(self, k, mesh, capacity)


def _stacked_prefetcher(loader, k, mesh, capacity):
    from ..utils.prefetch import Prefetcher
    from .async_pipeline import batch_stack, group_steps
    return Prefetcher(group_steps(iter(loader), k),
                      stage=batch_stack(k, mesh), capacity=capacity)


def _default_collate(batch):
    first = batch[0]
    if isinstance(first, (tuple, list)):
        return [np.stack([np.asarray(s[i]) for s in batch])
                for i in range(len(first))]
    return np.stack([np.asarray(s) for s in batch])


class GeneratorLoader:
    """Static-graph loader (reader.py GeneratorLoader:1064): iterate feed
    dicts with background prefetch."""

    _SENTINEL = object()

    def __init__(self, feed_list, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, drop_last=True,
                 use_multiprocess=False):
        self._feed_names = [v if isinstance(v, str) else v.name
                            for v in (feed_list or [])]
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._generator: Optional[Callable] = None
        self._places = None
        self._use_multiprocess = use_multiprocess
        # advertised to the executor's shape-bucketing layer; generator
        # length is unknown so the tail can be ANY size < batch_size —
        # set_sample_generator advertises power-of-two edges
        self.bucket_edges = None

    # -- wiring -------------------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batcher():
            it = iter(reader())
            while True:
                rows = list(itertools.islice(it, batch_size))
                if len(rows) < batch_size:
                    if rows and not drop_last:
                        yield rows
                    return
                yield rows
        self._generator = lambda: (_rows_to_feed(self._feed_names, rows)
                                   for rows in batcher())
        if drop_last:
            self.bucket_edges = (int(batch_size),)
        else:
            from . import compile_cache
            self.bucket_edges = compile_cache.pow2_edges(batch_size)
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        self._generator = lambda: (_rows_to_feed(self._feed_names, rows)
                                   for rows in reader())
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def gen():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {n: np.asarray(b)
                           for n, b in zip(self._feed_names, batch)}
        self._generator = gen
        self._places = places
        return self

    # -- iteration with background prefetch ---------------------------------
    def __iter__(self):
        if self._generator is None:
            raise RuntimeError("DataLoader: no generator set")
        if self._use_multiprocess:
            # whole generator runs in a streamer process (reader.py:789)
            from .dataloader_iter import MultiprocessGenIter
            source = MultiprocessGenIter(self._generator,
                                         capacity=self._capacity)
        else:
            source = self._generator()
        from ..utils.prefetch import Prefetcher
        # shared prefetcher: forwards producer exceptions instead of
        # silently truncating the epoch, and cleans up on consumer break
        for item in Prefetcher(source, capacity=self._capacity):
            if self._return_list:
                yield [item[n] for n in self._feed_names]
            else:
                yield item

    def stacked(self, k, mesh=None, capacity=2):
        """K-step staging hook (see DataLoader.stacked): groups of ``k``
        feed dicts device-staged on the producer thread for
        `steps_per_dispatch=k` scan dispatch."""
        return _stacked_prefetcher(self, k, mesh, capacity)

    # legacy non-iterable protocol
    def start(self):
        self._it = iter(self)

    def reset(self):
        self._it = None

    def next(self):
        return next(self._it)


def _rows_to_feed(names, rows):
    return {n: np.stack([np.asarray(r[i]) for r in rows])
            for i, n in enumerate(names)}


class _DatasetLoader:
    def __init__(self, dataset, drop_last=True):
        self.dataset = dataset
        self.drop_last = drop_last

    def __iter__(self):
        yield from self.dataset._iter_batches()


class PyReader(GeneratorLoader):
    """fluid.io.PyReader compat shim."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, use_double_buffer, iterable,
                         return_list)


def batch(reader, batch_size, drop_last=False):
    """paddle.batch composition helper."""
    def batched():
        it = iter(reader())
        while True:
            rows = list(itertools.islice(it, batch_size))
            if not rows or (len(rows) < batch_size and drop_last):
                return
            yield rows
    return batched


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                np.random.shuffle(buf)
                yield from buf
                buf = []
        np.random.shuffle(buf)
        yield from buf
    return shuffled


# reference reader.py exports the default batch-collation function
default_collate_fn = _default_collate
