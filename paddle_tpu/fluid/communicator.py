"""fluid.communicator analog (reference communicator.py over
operators/distributed/communicator.h): the async/geo gradient
communicator facade + the LargeScaleKV store handle."""
from __future__ import annotations

__all__ = ["Communicator", "LargeScaleKV"]


class Communicator:
    def __init__(self, program=None, mode=None, kwargs=None, envs=None):
        self._mode = mode
        self._running = False
        self._comm = None

    def _runtime(self):
        from ..distributed import fleet
        return fleet._fleet_singleton._runtime_handle

    def start(self):
        rt = self._runtime()
        self._comm = getattr(rt, "communicator", None) if rt else None
        if self._comm is not None and hasattr(self._comm, "start"):
            self._comm.start()
        self._running = True

    def stop(self):
        if self._comm is not None and hasattr(self._comm, "stop"):
            self._comm.stop()
        self._running = False

    def is_running(self):
        return self._running


class LargeScaleKV:
    """Host-RAM unbounded sparse KV (large_scale_kv.h analog): a thin
    handle over the PS sparse table tier."""

    def __init__(self, dim=1):
        from ..distributed.ps.table import CommonSparseTable
        self._table = CommonSparseTable(dim=dim)

    def save(self, name, dirname=None):
        import os
        path = name if dirname is None else os.path.join(dirname, name)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._table.save(path)

    def load(self, name, dirname=None):
        import os
        path = name if dirname is None else os.path.join(dirname, name)
        self._table.load(path)

    def size(self, name=None):
        return self._table.size()
