"""Global stat counters — the platform/monitor.h analog.

Reference: platform/monitor.h:31,43,129 — ``StatValue`` int counters in a
process-wide ``StatRegistry``, bumped via ``STAT_ADD``/``STAT_SUB`` macros
(BoxPS memory stats, dataset ingest counters).  TPU-native: the counters
live host-side and are BACKED by the unified observability plane's metrics
registry (fluid/trace.py) — ``stat_add("psgpu/mem", n)`` and
``trace.metrics().counter("psgpu/mem")`` are the same thread-safe cell, so
monitor stats ride into exported Chrome timelines for free.  StatRegistry
remains the reference-shaped facade (singleton + ``get``/``stats``) and
tracks which names were created through it, so ``print_stats`` shows only
monitor-plane counters, not every framework metric.
"""
from __future__ import annotations

import threading
from typing import List, Tuple

from . import trace


class StatValue:
    """Reference StatValue surface over a plane instrument (thread-safe).

    Binds to whatever already lives under ``name`` in the metrics
    registry — Counter, Gauge (``goodput.ratio``, ``xla.mem.*``), or
    Histogram.  Binding is lazy and READS never create: a
    ``stat_get("xla.mem.lru_total_peak_bytes")`` issued before the
    first compile returns 0 without registering a Counter under a name
    the executor will later need as a Gauge (that poisoning would make
    the plane's ``gauge()`` call raise TypeError mid-training).  Only a
    WRITE (``increase``/``decrease``) on a still-unknown name creates
    the legacy Counter.  ``get()`` on a gauge returns its float; on a
    histogram, its observation count."""

    __slots__ = ("name", "_m")

    def __init__(self, name: str):
        self.name = name
        self._m = trace.metrics().get(name)     # bind if present only

    def _bound(self, create: bool = False):
        if self._m is not None \
                and trace.metrics().get(self.name) is not self._m:
            # the registry retired this instrument (evicted-executable
            # gauge): drop the pinned binding instead of serving its
            # frozen value forever
            self._m = None
        if self._m is None:
            if create:
                # instrument(): bind-any-type-or-create under ONE lock
                # acquisition, so a gauge created concurrently between a
                # lookup and a counter() call can never raise
                self._m = trace.metrics().instrument(
                    self.name, default=trace.Counter)
            else:
                self._m = trace.metrics().get(self.name)
        return self._m

    def increase(self, n: int = 1):
        m = self._bound(create=True)
        if isinstance(m, trace.Histogram):
            raise TypeError(
                f"stat '{self.name}' is a histogram — read-only through "
                f"the monitor facade (observe via "
                f"trace.metrics().histogram)")
        return m.add(n)             # Counter.add / Gauge.add: both atomic

    def decrease(self, n: int = 1):
        return self.increase(-n)

    def reset(self) -> None:
        m = self._bound()
        if m is not None:
            m.reset()

    def get(self):
        m = self._bound()
        if m is None:
            return 0
        if isinstance(m, trace.Histogram):
            return m.stats()["count"]
        return m.value


class StatRegistry:
    """Process-wide registry; ``StatRegistry.instance()`` mirrors the
    reference singleton.  Map creation and lookups are lock-guarded so
    data-feed worker threads can create/bump stats concurrently."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get(self, name: str) -> StatValue:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = StatValue(name)
            return stat

    def stats(self, prefix: str = None) -> List[Tuple[str, int]]:
        """Registered stats as ``(name, value)``.  With ``prefix``, query
        the PLANE registry instead: every instrument whose name starts
        with it — e.g. ``stats(prefix="goodput.")`` or
        ``stats(prefix="xla.mem.")`` surfaces the new gauges through the
        legacy API.  Prefix queries read without registering StatValues,
        so instruments a later eviction removes (per-executable
        footprint gauges) don't linger here as stale copies."""
        if prefix is not None:
            out = []
            for n, inst in trace.metrics().items():   # one lock pass
                if not n.startswith(prefix):
                    continue
                v = inst.stats()["count"] \
                    if isinstance(inst, trace.Histogram) else inst.value
                out.append((n, v))
            return out                  # items() is already name-sorted
        with self._lock:
            items = list(self._stats.items())
        return sorted((n, s.get()) for n, s in items)

    def reset_all(self) -> None:
        """Zero every registered stat — test isolation (reference has no
        analog; the C++ registry lives for the process)."""
        with self._lock:
            items = list(self._stats.values())
        for s in items:
            s.reset()


def stat_add(name: str, n: int = 1) -> int:
    """STAT_ADD macro analog."""
    return StatRegistry.instance().get(name).increase(n)


def stat_sub(name: str, n: int = 1) -> int:
    """STAT_SUB macro analog."""
    return StatRegistry.instance().get(name).decrease(n)


def stat_get(name: str) -> int:
    return StatRegistry.instance().get(name).get()


def reset_all() -> None:
    StatRegistry.instance().reset_all()


def print_stats() -> str:
    """Render all counters, one per line (monitor dump format)."""
    return "\n".join(f"{n} = {v}"
                     for n, v in StatRegistry.instance().stats())
