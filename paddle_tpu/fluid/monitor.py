"""Global stat counters — the platform/monitor.h analog.

Reference: platform/monitor.h:31,43,129 — ``StatValue`` int counters in a
process-wide ``StatRegistry``, bumped via ``STAT_ADD``/``STAT_SUB`` macros
(BoxPS memory stats, dataset ingest counters).  TPU-native: the counters
live host-side (device-side counts belong in the profiler); thread-safe so
data-feed worker threads can bump them.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple


class StatValue:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def increase(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            return self._value

    def decrease(self, n: int = 1) -> int:
        return self.increase(-n)

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def get(self) -> int:
        with self._lock:
            return self._value


class StatRegistry:
    """Process-wide registry; ``StatRegistry.instance()`` mirrors the
    reference singleton."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, StatValue] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get(self, name: str) -> StatValue:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = StatValue(name)
            return stat

    def stats(self) -> List[Tuple[str, int]]:
        with self._lock:
            return sorted((n, s.get()) for n, s in self._stats.items())


def stat_add(name: str, n: int = 1) -> int:
    """STAT_ADD macro analog."""
    return StatRegistry.instance().get(name).increase(n)


def stat_sub(name: str, n: int = 1) -> int:
    """STAT_SUB macro analog."""
    return StatRegistry.instance().get(name).decrease(n)


def stat_get(name: str) -> int:
    return StatRegistry.instance().get(name).get()


def print_stats() -> str:
    """Render all counters, one per line (monitor dump format)."""
    return "\n".join(f"{n} = {v}"
                     for n, v in StatRegistry.instance().stats())
