"""Global stat counters — the platform/monitor.h analog.

Reference: platform/monitor.h:31,43,129 — ``StatValue`` int counters in a
process-wide ``StatRegistry``, bumped via ``STAT_ADD``/``STAT_SUB`` macros
(BoxPS memory stats, dataset ingest counters).  TPU-native: the counters
live host-side and are BACKED by the unified observability plane's metrics
registry (fluid/trace.py) — ``stat_add("psgpu/mem", n)`` and
``trace.metrics().counter("psgpu/mem")`` are the same thread-safe cell, so
monitor stats ride into exported Chrome timelines for free.  StatRegistry
remains the reference-shaped facade (singleton + ``get``/``stats``) and
tracks which names were created through it, so ``print_stats`` shows only
monitor-plane counters, not every framework metric.
"""
from __future__ import annotations

import threading
from typing import List, Tuple

from . import trace


class StatValue:
    """Reference StatValue surface over a plane Counter (thread-safe)."""

    __slots__ = ("name", "_counter")

    def __init__(self, name: str):
        self.name = name
        self._counter = trace.metrics().counter(name)

    def increase(self, n: int = 1) -> int:
        return self._counter.add(n)

    def decrease(self, n: int = 1) -> int:
        return self._counter.add(-n)

    def reset(self) -> None:
        self._counter.reset()

    def get(self) -> int:
        return self._counter.value


class StatRegistry:
    """Process-wide registry; ``StatRegistry.instance()`` mirrors the
    reference singleton.  Map creation and lookups are lock-guarded so
    data-feed worker threads can create/bump stats concurrently."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get(self, name: str) -> StatValue:
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = StatValue(name)
            return stat

    def stats(self) -> List[Tuple[str, int]]:
        with self._lock:
            items = list(self._stats.items())
        return sorted((n, s.get()) for n, s in items)

    def reset_all(self) -> None:
        """Zero every registered stat — test isolation (reference has no
        analog; the C++ registry lives for the process)."""
        with self._lock:
            items = list(self._stats.values())
        for s in items:
            s.reset()


def stat_add(name: str, n: int = 1) -> int:
    """STAT_ADD macro analog."""
    return StatRegistry.instance().get(name).increase(n)


def stat_sub(name: str, n: int = 1) -> int:
    """STAT_SUB macro analog."""
    return StatRegistry.instance().get(name).decrease(n)


def stat_get(name: str) -> int:
    return StatRegistry.instance().get(name).get()


def reset_all() -> None:
    StatRegistry.instance().reset_all()


def print_stats() -> str:
    """Render all counters, one per line (monitor dump format)."""
    return "\n".join(f"{n} = {v}"
                     for n, v in StatRegistry.instance().stats())
