"""fluid.average analog (reference python/paddle/fluid/average.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        value = np.asarray(value, dtype="float64")
        self.numerator += float(value.sum()) * float(weight)
        self.denominator += float(weight) * value.size

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError("WeightedAverage.eval before any add()")
        return self.numerator / self.denominator
