"""Device truth for compiled executables: measured FLOPs + HBM footprint.

Reference: the reference stack's per-kernel stats and
memory/allocation/ accounting give device-side answers the host plane
cannot (PAPER.md layers 1-2): how many FLOPs does this executable
*actually* issue, and how much device memory does it *actually* need?
TPU-native, the same truth comes from XLA itself — an AOT
``jitted.lower(...).compile()`` yields ``cost_analysis()`` (measured
FLOPs / bytes accessed, the denominator-free half of MFU) and
``memory_analysis()`` (argument / output / temp / generated-code bytes:
the executable's peak HBM footprint).

What lives here:

* :func:`capture` — lower + compile a jitted callable against example
  avals (``jax.ShapeDtypeStruct`` trees, so donated/deleted buffers are
  never touched) and normalise both analyses into one flat dict.  The
  AOT compile is a real SECOND compile of the program (the jit call's
  executable is not reused; only the persistent compilation cache or a
  repeated capture shortcut it), so its cost — observed in
  ``xla.analysis_seconds`` — is why capture is opt-in.
* :func:`capture_enabled` — the gate.  ``FLAGS_device_cost_analysis``:
  ``auto`` (default: follows tracing), or an explicit true/false —
  serving /metrics alone never opts a run into the extra compile.
  When off, the executor pays one flag read per compile MISS — nothing
  per step.
* :func:`publish` / :func:`unpublish` — per-executable
  ``xla.mem.exe.<label>.*`` / ``xla.cost.exe.<label>.*`` gauges, removed
  again when the executor's LRU evicts the executable.
* :func:`attach_oom_report` — on a RESOURCE_EXHAUSTED compile/run error
  the executor attaches the top footprints (structured, on
  ``exc.device_footprints``, plus a stderr table) so OOM forensics can
  name the biggest executables instead of guessing.
* :func:`sds_tree` — pytree -> ShapeDtypeStruct twin (shared with
  bench.py's ``mfu_measured`` capture of its raw jitted step fns).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import trace

__all__ = [
    "capture_enabled", "capture", "sds_tree", "publish", "unpublish",
    "peak_bytes_of", "flops_of", "is_oom", "attach_oom_report",
    "format_footprints", "live_footprints",
]

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def capture_enabled() -> bool:
    """FLAGS_device_cost_analysis gate: explicit bool wins; ``auto``
    follows TRACING only.  The capture pays a second (only partially
    cached) XLA compile per compile miss, so merely serving /metrics
    must not opt a production run into it — runs that want footprint
    gauges on the scrape without tracing set the flag to True
    explicitly."""
    from . import core
    v = core.get_flag("device_cost_analysis", "auto")
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in _TRUE:
        return True
    if s in _FALSE:
        return False
    return trace.enabled()


def sds_tree(tree):
    """ShapeDtypeStruct twin of a pytree of arrays — safe to lower
    against even when the originals were donated (shape/dtype survive
    deletion; buffer contents are never read)."""
    import jax

    def _sds(a):
        if isinstance(a, jax.ShapeDtypeStruct):
            return a
        dt = getattr(a, "dtype", None)
        if dt is None:
            a = np.asarray(a)
            dt = a.dtype
        return jax.ShapeDtypeStruct(tuple(np.shape(a)), dt)

    return jax.tree_util.tree_map(_sds, tree)


def _cost_dict(cost) -> Dict[str, Any]:
    """cost_analysis() returns a dict on new jax, a 1-list of dicts on
    older ones, or None on backends without the query."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    return dict(cost) if isinstance(cost, dict) else {}


def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            total += int(np.prod(np.shape(leaf)) or 1) \
                * np.dtype(getattr(leaf, "dtype", "f4")).itemsize
        except (TypeError, ValueError):
            pass
    return total


def capture(jitted, example_args: Sequence,
            label: Optional[str] = None,
            n_devices: int = 1) -> Optional[Dict[str, Any]]:
    """Lower + compile ``jitted`` at ``example_args`` (arrays or
    ShapeDtypeStruct trees) and return the merged device-truth record::

        {"flops", "bytes_accessed",
         "argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
         "generated_code_bytes", "peak_bytes", "per_device_peak_bytes",
         "mesh_devices", "analysis_seconds"}

    Under a sharded (SPMD) compile, XLA's analyses describe the
    PER-DEVICE program — pass ``n_devices`` (the plan's mesh size) so the
    record says both what one device holds (``per_device_peak_bytes``,
    the HBM-fit question) and how wide the executable runs
    (``mesh_devices``).

    Returns None when the callable has no ``lower`` (checkify wrappers,
    custom step builders) or the backend refuses the analysis — capture
    degrades, never raises into the training loop."""
    if not hasattr(jitted, "lower"):
        return None
    m = trace.metrics()
    t0 = time.perf_counter()
    try:
        examples = [sds_tree(a) for a in example_args]
        compiled = jitted.lower(*examples).compile()
    except Exception:                   # noqa: BLE001 — capture degrades
        m.counter("xla.analysis_errors").inc()
        return None
    cost = {}
    try:
        cost = _cost_dict(compiled.cost_analysis())
    except Exception:                   # noqa: BLE001
        m.counter("xla.analysis_errors").inc()
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception:                   # noqa: BLE001
        m.counter("xla.analysis_errors").inc()
    info: Dict[str, Any] = {
        "flops": float(cost.get("flops", 0.0) or 0.0),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
    }
    if mem is not None:
        for field, key in (("argument_size_in_bytes", "argument_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("temp_size_in_bytes", "temp_bytes"),
                           ("alias_size_in_bytes", "alias_bytes"),
                           ("generated_code_size_in_bytes",
                            "generated_code_bytes")):
            info[key] = int(getattr(mem, field, 0) or 0)
    else:
        # backend without CompiledMemoryStats: argument bytes from the
        # example avals is still real truth; temp/code are unknowable
        info["argument_bytes"] = sum(_tree_bytes(a) for a in example_args)
        info["output_bytes"] = 0
        info["temp_bytes"] = 0
        info["alias_bytes"] = 0
        info["generated_code_bytes"] = 0
    info["peak_bytes"] = max(
        0,
        info["argument_bytes"] + info["output_bytes"] + info["temp_bytes"]
        + info["generated_code_bytes"] - info["alias_bytes"])
    # per-shard HBM truth: the analysis above is already per-device (one
    # SPMD program per chip); record it under the explicit name the
    # sharding plane's consumers (bench --sharding, tpu_watch, OOM
    # forensics) read, beside the mesh width
    info["mesh_devices"] = max(1, int(n_devices or 1))
    info["per_device_peak_bytes"] = info["peak_bytes"]
    dt = time.perf_counter() - t0
    info["analysis_seconds"] = round(dt, 4)
    m.histogram("xla.analysis_seconds").observe(dt)
    if label:
        info["label"] = str(label)
    return info


def flops_of(jitted, example_args: Sequence) -> float:
    """Measured FLOPs of one executable (0.0 when unavailable) — what
    bench.py sums across its step's programs for ``mfu_measured``."""
    info = capture(jitted, example_args)
    return float(info["flops"]) if info else 0.0


def peak_bytes_of(info: Dict[str, Any]) -> int:
    return int(info.get("peak_bytes", 0) or 0)


# ---------------------------------------------------------------------------
# gauge surface
# ---------------------------------------------------------------------------

_MEM_FIELDS = ("peak_bytes", "argument_bytes", "output_bytes", "temp_bytes",
               "per_device_peak_bytes", "mesh_devices")
_COST_FIELDS = ("flops", "bytes_accessed")

# process-wide label -> peak bytes of every published executable.  The
# xla.mem.lru_* aggregate gauges derive from THIS map, not from any one
# Executor's private footprint dict — two executors (hapi's internal one
# plus a user's) would otherwise last-writer-win each other's totals,
# and closing a scratch executor would zero the aggregates while the
# main one still holds resident executables.
_agg_lock = threading.Lock()
_agg: Dict[str, float] = {}


def publish(label: str, info: Dict[str, Any]) -> None:
    """Per-executable gauges (``xla.mem.exe.<label>.<field>`` /
    ``xla.cost.exe.<label>.<field>``) + the process-wide aggregates."""
    m = trace.metrics()
    for f in _MEM_FIELDS:
        m.gauge(f"xla.mem.exe.{label}.{f}").set(float(info.get(f, 0) or 0))
    for f in _COST_FIELDS:
        m.gauge(f"xla.cost.exe.{label}.{f}").set(float(info.get(f, 0) or 0))
    with _agg_lock:
        _agg[label] = float(info.get("peak_bytes", 0) or 0)
    _refresh_aggregates()


def unpublish(label: str) -> None:
    m = trace.metrics()
    for f in _MEM_FIELDS:
        m.remove(f"xla.mem.exe.{label}.{f}")
    for f in _COST_FIELDS:
        m.remove(f"xla.cost.exe.{label}.{f}")
    with _agg_lock:
        _agg.pop(label, None)
    _refresh_aggregates()


def live_footprints() -> List[Dict[str, Any]]:
    """Every published (still-resident) executable as
    ``{"label", "peak_bytes"}`` rows, biggest first — what a diagnostic
    bundle embeds as the device-memory picture at incident time."""
    with _agg_lock:
        items = sorted(_agg.items(), key=lambda kv: kv[1], reverse=True)
    return [{"label": k, "peak_bytes": int(v)} for k, v in items]


def _refresh_aggregates() -> None:
    """Aggregate footprint across every live executable in the process:
    how much HBM the resident executables claim in total and at worst —
    the signal OOM forensics and eviction tuning read."""
    with _agg_lock:
        peaks = list(_agg.values())
    m = trace.metrics()
    m.gauge("xla.mem.lru_executables").set(len(peaks))
    m.gauge("xla.mem.lru_total_peak_bytes").set(float(sum(peaks)))
    m.gauge("xla.mem.largest_peak_bytes").set(float(max(peaks, default=0)))


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def is_oom(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "out of memory" in text.lower()
            or "hbm" in text.lower() and "exceed" in text.lower())


def format_footprints(footprints: Sequence[Dict[str, Any]],
                      top: int = 5) -> str:
    rows = sorted(footprints, key=peak_bytes_of, reverse=True)[:top]
    lines = [f"{'executable':<24s} {'peak':>10s} {'args':>10s} "
             f"{'temp':>10s} {'out':>10s}"]
    for r in rows:
        lines.append(
            f"{str(r.get('label', '?'))[:24]:<24s} "
            f"{_fmt_bytes(r.get('peak_bytes', 0)):>10s} "
            f"{_fmt_bytes(r.get('argument_bytes', 0)):>10s} "
            f"{_fmt_bytes(r.get('temp_bytes', 0)):>10s} "
            f"{_fmt_bytes(r.get('output_bytes', 0)):>10s}")
    return "\n".join(lines)


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"                # pragma: no cover - loop returns


def attach_oom_report(exc: BaseException,
                      footprints: Sequence[Dict[str, Any]],
                      top: int = 5) -> BaseException:
    """Attach OOM forensics to a RESOURCE_EXHAUSTED error: the
    structured top footprints land on ``exc.device_footprints`` (OOM
    handlers can act on them) and a rendered table goes to stderr (on
    py3.11+ it would ride ``add_note``; 3.10 gets the attribute + print).
    The exception object is returned, never replaced — the original
    traceback and type survive."""
    rows = sorted(footprints, key=peak_bytes_of, reverse=True)[:top]
    try:
        exc.device_footprints = rows
    except Exception:                   # noqa: BLE001 — slotted exc types
        pass
    report = ("paddle_tpu: device OOM — largest live executables by "
              "XLA-reported footprint:\n" + format_footprints(rows, top))
    note = getattr(exc, "add_note", None)
    if callable(note):                  # pragma: no cover - py3.11+
        try:
            note(report)
        except Exception:               # noqa: BLE001
            pass
    import sys
    print(report, file=sys.stderr)
    trace.metrics().counter("xla.oom_errors").inc()
    if trace.enabled():
        trace.instant("device_oom", cat="compile",
                      args={"top": [
                          {"label": r.get("label"),
                           "peak_bytes": r.get("peak_bytes")}
                          for r in rows]})
    try:
        # RESOURCE_EXHAUSTED hook for the SLO watchdog: a running
        # watchdog freezes the evidence (footprints now ride on exc)
        # into an `oom` diagnostic bundle — rate-limited there
        from . import watchdog
        watchdog.notify_oom(exc)
    except Exception:                   # noqa: BLE001 — forensics never
        pass                            # worsen the primary error
    return exc
