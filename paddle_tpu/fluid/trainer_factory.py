"""fluid.trainer_factory analog (reference trainer_factory.py):
TrainerDesc construction from an opt_info dict + fetch monitoring."""
from __future__ import annotations

import threading
import time

import numpy as np

from . import trainer_desc as _td
from . import device_worker as _dw

__all__ = ["TrainerFactory", "FetchHandler", "FetchHandlerMonitor"]


class TrainerFactory:
    def _create_trainer(self, opt_info=None):
        opt_info = opt_info or {}
        trainer_name = opt_info.get("trainer", "MultiTrainer")
        worker_name = opt_info.get("device_worker", "Hogwild")
        trainer = getattr(_td, trainer_name, _td.MultiTrainer)()
        worker = getattr(_dw, worker_name, _dw.Hogwild)()
        trainer.set_device_worker(worker)
        if "thread_num" in opt_info:
            trainer.set_thread(opt_info["thread_num"])
        if "fleet_desc" in opt_info:
            trainer.set_fleet_desc(opt_info["fleet_desc"])
        return trainer


class FetchHandler:
    def __init__(self, var_dict=None, period_secs=60):
        self.var_dict = var_dict or {}
        self.period_secs = period_secs

    def handler(self, res_dict):
        for k, v in res_dict.items():
            if v is not None:
                print(f"{k}: {np.asarray(v).ravel()[:8]}")

    @staticmethod
    def help():
        print("FetchHandler: subclass and override handler(res_dict); "
              "var_dict maps names to scope vars, polled every "
              "period_secs during train_from_dataset")


class FetchHandlerMonitor:
    """Polls scope vars on a timer thread while a dataset-trainer runs."""

    def __init__(self, scope, handler):
        self._scope = scope
        self._handler = handler
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        def loop():
            while not self._stop.wait(self._handler.period_secs):
                res = {}
                for name in self._handler.var_dict:
                    v = self._scope.find_var(name)
                    res[name] = None if v is None else np.asarray(v)
                self._handler.handler(res)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
