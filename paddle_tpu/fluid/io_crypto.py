"""Model encryption — analog of the reference's crypto tier
(paddle/fluid/framework/io/crypto/: cipher.h Cipher/CipherFactory,
aes_cipher.cc AES modes, cipher_utils.h GenKey/config loading).

Same surface, Python-native: a Cipher with encrypt/decrypt (+ file
variants), a factory keyed by cipher_name with `AES_CTR_NoPadding` as the
reference's default, and key/config utilities.  Backed by the
`cryptography` package's AES (CTR and GCM modes); artifact layout is
iv || ciphertext (CTR) or iv || ciphertext || tag (GCM), with sizes from
the config exactly like the reference's iv_size/tag_size knobs.

`encrypt_inference_model` / `decrypt_inference_model` apply it to the
`__model__` + params artifact produced by fluid.io.save_inference_model,
giving the at-rest protection the reference's inference deployment path
uses.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

__all__ = ["Cipher", "AESCipher", "CipherFactory", "CipherUtils",
           "encrypt_inference_model", "decrypt_inference_model"]

_AES_DEFAULT_IV_SIZE = 128          # bits, cipher_utils.h
_AES_DEFAULT_TAG_SIZE = 128


class Cipher:
    """cipher.h Cipher interface."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def encrypt_to_file(self, plaintext: bytes, key: bytes,
                        filename: str) -> None:
        with open(filename, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, filename: str) -> bytes:
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key)


class AESCipher(Cipher):
    """aes_cipher.h analog: AES in CTR (stream, no padding — the
    reference default) or GCM (authenticated) mode."""

    def __init__(self, cipher_name: str = "AES_CTR_NoPadding",
                 iv_size: int = _AES_DEFAULT_IV_SIZE,
                 tag_size: int = _AES_DEFAULT_TAG_SIZE):
        if "AES" not in cipher_name:
            raise ValueError(f"not an AES cipher: {cipher_name}")
        self.name = cipher_name
        # fail fast on sizes the backend cannot serve, naming the knob:
        # CTR needs a full 16-byte counter block; our iv||ct||tag layout
        # needs the full 16-byte GCM tag to split unambiguously
        if "GCM" in cipher_name:
            if iv_size % 8 or not 64 <= iv_size <= 128:
                raise ValueError(
                    f"iv_size {iv_size} unsupported for GCM (use 64-128 "
                    f"bits in byte multiples)")
            if tag_size != 128:
                raise ValueError(
                    f"tag_size {tag_size} unsupported: the artifact "
                    f"layout requires the full 128-bit GCM tag")
        elif iv_size != 128:
            raise ValueError(
                f"iv_size {iv_size} unsupported for CTR (the counter "
                f"block is 128 bits)")
        self.iv_bytes = iv_size // 8
        self.tag_bytes = tag_size // 8

    def _check_key(self, key: bytes) -> bytes:
        key = bytes(key)
        if len(key) not in (16, 24, 32):
            raise ValueError(
                f"AES key must be 16/24/32 bytes, got {len(key)}")
        return key

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import (Cipher as _C,
                                                            algorithms,
                                                            modes)
        key = self._check_key(key)
        iv = os.urandom(self.iv_bytes)
        if "GCM" in self.name:
            enc = _C(algorithms.AES(key), modes.GCM(iv)).encryptor()
            ct = enc.update(bytes(plaintext)) + enc.finalize()
            return iv + ct + enc.tag[:self.tag_bytes]
        enc = _C(algorithms.AES(key), modes.CTR(iv)).encryptor()
        return iv + enc.update(bytes(plaintext)) + enc.finalize()

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import (Cipher as _C,
                                                            algorithms,
                                                            modes)
        key = self._check_key(key)
        ciphertext = bytes(ciphertext)
        iv, rest = ciphertext[:self.iv_bytes], ciphertext[self.iv_bytes:]
        if "GCM" in self.name:
            ct, tag = rest[:-self.tag_bytes], rest[-self.tag_bytes:]
            dec = _C(algorithms.AES(key), modes.GCM(iv, tag)).decryptor()
            return dec.update(ct) + dec.finalize()
        dec = _C(algorithms.AES(key), modes.CTR(iv)).decryptor()
        return dec.update(rest) + dec.finalize()


class CipherFactory:
    """cipher.cc CipherFactory::CreateCipher: name + iv/tag sizes from a
    config file of `key: value` lines, AES_CTR_NoPadding when
    unconfigured."""

    @staticmethod
    def create_cipher(config_file: str = "") -> Cipher:
        name, iv, tag = "AES_CTR_NoPadding", None, None
        if config_file:
            cfg = CipherUtils.load_config(config_file)
            name = cfg.get("cipher_name", name)
            iv = int(cfg["iv_size"]) if "iv_size" in cfg else None
            tag = int(cfg["tag_size"]) if "tag_size" in cfg else None
        if "AES" not in name:
            raise ValueError(
                f"invalid cipher name {name!r}: only AES modes exist")
        return AESCipher(name, iv or _AES_DEFAULT_IV_SIZE,
                         tag or _AES_DEFAULT_TAG_SIZE)


class CipherUtils:
    """cipher_utils.h: key generation + config parsing."""

    @staticmethod
    def gen_key(length_bits: int) -> bytes:
        if length_bits % 8:
            raise ValueError("key length must be a multiple of 8 bits")
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, filename: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        # key material: owner-only regardless of umask; fchmod covers
        # rotation into a pre-existing (possibly wider-mode) file, where
        # the open() mode argument is ignored
        fd = os.open(filename, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                     0o600)
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(filename: str) -> bytes:
        with open(filename, "rb") as f:
            return f.read()

    @staticmethod
    def load_config(filename: str) -> Dict[str, str]:
        out = {}
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or ":" not in line:
                    continue
                k, v = line.split(":", 1)
                out[k.strip()] = v.strip()
        return out


_ENC_SUFFIX = ".encrypted"


def _looks_like_key_material(fn: str) -> bool:
    """Never self-encrypt key/config files living next to the model —
    encrypting the key with itself makes the artifact unrecoverable."""
    low = fn.lower()
    return (fn.startswith(".") or low == "key" or low.endswith(".key")
            or low.endswith(".pem") or low.endswith(".conf"))


def encrypt_inference_model(dirname: str, key: bytes,
                            cipher: Optional[Cipher] = None,
                            files=None) -> list:
    """Encrypt the artifact files in place (original removed, `.encrypted`
    written) — the deployment-side at-rest protection step.  By default
    EVERY regular file in the directory is encrypted (model, params in
    any format, manifest, per-var reference files) so no sibling
    plaintext survives; pass `files` to restrict."""
    cipher = cipher or CipherFactory.create_cipher()
    if files is None:
        files = [fn for fn in sorted(os.listdir(dirname))
                 if os.path.isfile(os.path.join(dirname, fn))
                 and not fn.endswith(_ENC_SUFFIX)
                 and not _looks_like_key_material(fn)]
    done = []
    for name in files:
        path = os.path.join(dirname, name)
        if not os.path.exists(path):
            continue
        with open(path, "rb") as f:
            cipher.encrypt_to_file(f.read(), key, path + _ENC_SUFFIX)
        os.remove(path)
        done.append(name)
    if not done:
        raise FileNotFoundError(f"no artifact files found in {dirname}")
    return done


def decrypt_inference_model(dirname: str, key: bytes,
                            cipher: Optional[Cipher] = None) -> list:
    """Restore the plaintext artifact files from their `.encrypted`
    siblings (loader-side)."""
    cipher = cipher or CipherFactory.create_cipher()
    done = []
    for fn in sorted(os.listdir(dirname)):
        if not fn.endswith(_ENC_SUFFIX):
            continue
        plain = cipher.decrypt_from_file(
            key, os.path.join(dirname, fn))
        out = os.path.join(dirname, fn[:-len(_ENC_SUFFIX)])
        with open(out, "wb") as f:
            f.write(plain)
        done.append(fn[:-len(_ENC_SUFFIX)])
    if not done:
        raise FileNotFoundError(f"no .encrypted files in {dirname}")
    return done
