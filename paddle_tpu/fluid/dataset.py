"""Dataset tier: DatasetFactory / InMemoryDataset / QueueDataset.

Reference: python/paddle/fluid/dataset.py (DatasetFactory:30,
InMemoryDataset:322 with load_into_memory/local_shuffle/global_shuffle,
QueueDataset:747 streaming) over the C++ MultiSlot feeds
(framework/data_feed.cc, data_set.cc).  TPU-native: both flavors sit on
the native C++ feed pipeline (native/src/data_feed.cc — channels +
multi-threaded parsing) with the PyDataFeed fallback, and `_iter_batches`
yields executor-ready feed dicts so `exe.train_from_dataset` overlaps host
parsing with device steps (see distributed/trainer.py).

Slot mapping: each `set_use_var` Variable becomes a slot — int64 vars are
sparse (ids come back CSR and are densified per batch), float vars are
dense with dim = prod(var.shape[1:]).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class DatasetFactory:
    """dataset.py:30 — create_dataset("InMemoryDataset"|"QueueDataset")."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")


class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_vars = []
        self.pipe_command = "cat"      # accepted for parity; parsing is the
        self._feed = None              # native MultiSlot schema
        self._pad_value = 0

    # -- reference config surface -------------------------------------------
    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        self.pipe_command = pipe_command

    def set_hdfs_config(self, fs_name, fs_ugi):
        pass

    # -- feed construction --------------------------------------------------
    def _slots(self):
        from ..native import SlotDesc
        slots = []
        for v in self.use_vars:
            dtype = str(getattr(v, "dtype", "float32") or "float32")
            if "int" in dtype:
                slots.append(SlotDesc(v.name, is_dense=False))
            else:
                shape = [d for d in (v.shape or [1])[1:]] or [1]
                dim = int(np.prod([abs(d) for d in shape]))
                slots.append(SlotDesc(v.name, is_dense=True, dim=dim))
        return slots

    def _make_feed(self):
        from ..native import NativeDataFeed, PyDataFeed, native_available
        cls = NativeDataFeed if native_available() else PyDataFeed
        feed = cls(self._slots(), self.batch_size,
                   num_threads=self.thread_num)
        feed.set_filelist(self.filelist)
        return feed

    def _densify(self, batch):
        """CSR sparse slots -> [B, L] padded id matrices (uniform-length
        slots — the CTR norm — reshape without padding)."""
        out = {}
        for name, val in batch.items():
            if isinstance(val, tuple):
                ids, lod = val
                b = len(lod) - 1
                lens = np.diff(lod)
                width = int(lens.max()) if len(lens) else 1
                if b == 0:
                    out[name] = np.empty((0, width), np.int64)
                elif len(lens) and (lens == lens[0]).all():
                    out[name] = ids.reshape(b, int(lens[0]))
                else:
                    # one native memcpy pass (16x the vectorized-numpy
                    # scatter on ragged CTR batches); numpy fallback inside
                    from ..native import pack_padded_csr
                    out[name], _ = pack_padded_csr(
                        np.asarray(ids, np.int64),
                        np.asarray(lod, np.int64),
                        pad_value=self._pad_value, max_len=width)
            else:
                out[name] = val
        return out

    def _iter_batches(self):
        raise NotImplementedError


class QueueDataset(DatasetBase):
    """Streaming pass over the filelist (dataset.py:747)."""

    def _iter_batches(self):
        feed = self._make_feed()
        feed.start()
        for batch in feed:
            yield self._densify(batch)


class InMemoryDataset(DatasetBase):
    """load_into_memory + shuffles, then repeatable passes
    (dataset.py:322)."""

    def __init__(self):
        super().__init__()
        self._loaded = False

    def load_into_memory(self):
        self._feed = self._make_feed()
        self._feed.load_into_memory()
        self._loaded = True

    def local_shuffle(self, seed: int = 0):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        self._feed.local_shuffle(seed)

    def global_shuffle(self, fleet=None, thread_num=0, seed: int = 0):
        """Cross-node shuffle: with a fleet handle + PS client, records
        re-route across trainers via the RPC plane; single-process falls
        back to a local shuffle (data_set.h:118)."""
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        client = None
        if fleet is not None:
            handle = getattr(fleet, "_runtime_handle", None) or getattr(
                getattr(fleet, "_fleet_singleton", None), "_runtime_handle",
                None)
            client = getattr(handle, "client", None)
        if client is None:
            self._feed.local_shuffle(seed)
            return
        self._global_shuffle_rpc(client, seed)

    def _global_shuffle_rpc(self, client, seed, n_trainers=None,
                            trainer_id=None):
        """Cross-node record-level shuffle (data_set.h:118): every record is
        content-hash-routed to trainer hash(record) % n; records bound for
        remote ranks are extracted from the local pool and exchanged through
        the PS RPC blob mailbox, then each trainer ingests its share and
        shuffles locally.  Falls back to file-granularity resharding for
        feeds without extract/ingest."""
        import os as _os
        n = (max(1, int(_os.environ.get("PADDLE_TRAINERS_NUM", "1")))
             if n_trainers is None else n_trainers)
        tid = (int(_os.environ.get("PADDLE_TRAINER_ID", "0"))
               if trainer_id is None else trainer_id)
        if n <= 1:
            self._feed.local_shuffle(seed)
            return
        # contract (matches the documented file-granularity behavior):
        # EVERY trainer holds the GLOBAL filelist.  Step 1 reshards it
        # disjointly (same seeded permutation on all trainers, strided
        # shard per tid) and reloads, so no record exists on two trainers.
        self._reshard_files_and_reload(seed, n, tid)
        if hasattr(self._feed, "extract_shards"):
            tag = f"gshuffle:{seed}"
            # step 2: content-hash record exchange — one pool pass buckets
            # all destinations (O(pool), not O(n*pool)), deposits fan out
            # in parallel over the mailbox servers
            shards = self._feed.extract_shards(n, tid)
            client.put_blobs({d: shards[d] for d in range(n) if d != tid},
                             tag)
            client.barrier()                 # all deposits visible
            for blob in client.take_blobs(tid, tag):
                self._feed.ingest(blob)
        self._feed.local_shuffle(seed + tid)
        try:
            client.barrier()                 # nobody proceeds mid-exchange
        except Exception:                    # noqa: BLE001 — shuffle is done;
            pass                             # barrier is best-effort sync

    def _reshard_files_and_reload(self, seed, n, tid):
        """All trainers compute the same seeded permutation of the GLOBAL
        filelist and take their strided shard, then reload memory from it —
        records move between nodes at file resolution and, crucially, the
        resulting pools are DISJOINT (a global list loaded on every trainer
        would otherwise duplicate each record n times post-exchange)."""
        rng = np.random.RandomState(seed)
        # shard from the preserved GLOBAL list every time — resharding the
        # previous shard would drop data on the second shuffle of a run
        if not hasattr(self, "_global_filelist"):
            self._global_filelist = list(self.filelist)
        files = list(self._global_filelist)
        rng.shuffle(files)
        self.filelist = files[tid::n] if n > 1 else files
        self._feed = self._make_feed()
        self._feed.load_into_memory()

    def release_memory(self):
        self._feed = None
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return self._feed.memory_size if self._feed is not None else 0

    get_shuffle_data_size = get_memory_data_size

    def _iter_batches(self):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        self._feed.start_from_memory()
        for batch in self._feed:
            yield self._densify(batch)
