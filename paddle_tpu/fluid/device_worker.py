"""fluid.device_worker analog (reference device_worker.py): per-thread
worker configs paired with TrainerDesc."""
from __future__ import annotations

__all__ = ["DeviceWorker", "Hogwild", "DownpourSGD", "DownpourSGDOPT",
           "Section", "BoxPSWorker"]


class DeviceWorker:
    def __init__(self):
        self._infer = False
        self._fleet_desc = None
        self._program = None

    def _set_infer(self, infer=False):
        self._infer = bool(infer)

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program


class Hogwild(DeviceWorker):
    """Lock-free per-thread SGD loop (hogwild_worker.cc:194)."""


class DownpourSGD(DeviceWorker):
    """PS pull->compute->push worker (downpour_worker.cc:739)."""


class DownpourSGDOPT(DownpourSGD):
    pass


class Section(DeviceWorker):
    """Pipeline stage worker (section_worker.cc:44)."""


class BoxPSWorker(DeviceWorker):
    """BoxPS pass-based worker (device_worker.h:619)."""
