"""fluid.install_check analog (reference install_check.py run_check):
a tiny end-to-end train step proving the install works."""
from __future__ import annotations

__all__ = ["run_check"]


def run_check():
    from ..utils import run_check as _rc
    _rc()
    print("Your Paddle Fluid is installed successfully!")
