"""fluid.transpiler namespace (reference python/paddle/fluid/transpiler/)."""
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)
from .geo_sgd_transpiler import GeoSgdTranspiler
from .ps_dispatcher import PSDispatcher, HashName, RoundRobin
from .memory_optimization_transpiler import memory_optimize, release_memory
from . import collective

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "GeoSgdTranspiler", "PSDispatcher", "HashName", "RoundRobin",
           "memory_optimize", "release_memory", "collective"]
