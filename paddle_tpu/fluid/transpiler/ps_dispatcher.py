"""fluid.transpiler.ps_dispatcher analog (reference transpiler/
ps_dispatcher.py): assign parameter blocks to parameter-server
endpoints."""
from __future__ import annotations

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eplist = list(pserver_endpoints)
        self._step = 0

    @property
    def eplist(self):
        return self._eplist

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    """Endpoint by hash of the var name — crc32, so the assignment is
    stable across PROCESSES (python's builtin hash is salted per run and
    would route the same param to different servers on each trainer)."""

    def dispatch(self, varlist):
        import zlib
        out = []
        for var in varlist:
            name = getattr(var, "name", var)
            idx = zlib.crc32(name.encode("utf-8")) % len(self._eplist)
            out.append(self._eplist[idx])
        return out


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        out = []
        for _var in varlist:
            out.append(self._eplist[self._step % len(self._eplist)])
            self._step += 1
        return out
