"""fluid.transpiler.collective analog (reference transpiler/collective.py):
program rewriters that make a single-device program data-parallel by
inserting collective ops — the GradAllReduce / LocalSGD tier under the
1.x collective fleet (incubate/fleet/collective uses these).

TPU design: c_allreduce_sum ops lower to lax.psum over the mesh axis
registered for their ring_id (ops/collective_ops.py), so "insert
c_allreduce on every grad" is the whole transform — bucketing/fusion and
stream ordering are XLA's job."""
from __future__ import annotations

from ..framework import _OPTIMIZER_OP_TYPES

__all__ = ["GradAllReduce", "LocalSGD"]


class Collective:
    def __init__(self, nrings=1):
        self.nrings = nrings

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        self.startup_program = startup_program
        self.main_program = main_program
        self.rank = rank
        self.nranks = len(endpoints.split(",")
                          if isinstance(endpoints, str) else endpoints)
        self._transpile_main_program()
        return main_program

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Insert scale(1/nranks) + c_allreduce_sum on every gradient consumed
    by an optimizer op (multi_devices_graph_pass AllReduce mode analog)."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        grads = []
        for op in block.ops:
            if op.type in _OPTIMIZER_OP_TYPES:
                g = op.input("Grad")
                if g:
                    grads.append(g[0])
        if not grads:
            raise ValueError("GradAllReduce: no optimizer ops found — "
                             "transpile after optimizer.minimize")
        first_opt = next(i for i, op in enumerate(block.ops)
                         if op.type in _OPTIMIZER_OP_TYPES)
        n_before = len(block.ops)
        for name in grads:
            block.append_op("scale", {"X": [name]}, {"Out": [name]},
                            {"scale": 1.0 / max(self.nranks, 1),
                             "op_role": 1})
            block.append_op("c_allreduce_sum", {"X": [name]},
                            {"Out": [name]},
                            {"ring_id": 0, "use_calc_stream": True,
                             "op_role": 1})
        # the new ops must run after backward but BEFORE the updates
        new_ops = block.ops[n_before:]
        del block.ops[n_before:]
        block.ops[first_opt:first_opt] = new_ops
        self.main_program._bump_version()


class LocalSGD(Collective):
    """Every k steps, average the PARAMETERS across ranks instead of the
    per-step gradients (localsgd_optimizer.py concept).  The rewrite
    appends scale + c_allreduce_sum on each param after its optimizer op;
    step-gating lives in the LocalSGD meta-optimizer tier."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        params = []
        for op in block.ops:
            if op.type in _OPTIMIZER_OP_TYPES:
                p = op.input("Param")
                if p:
                    params.append(p[0])
        if not params:
            raise ValueError("LocalSGD: no optimizer ops found")
        for name in params:
            block.append_op("scale", {"X": [name]}, {"Out": [name]},
                            {"scale": 1.0 / max(self.nranks, 1),
                             "op_role": 1})
            block.append_op("c_allreduce_sum", {"X": [name]},
                            {"Out": [name]},
                            {"ring_id": 0, "use_calc_stream": True,
                             "op_role": 1})
        self.main_program._bump_version()


class MultiThread(GradAllReduce):
    """Reference collective.py MultiThread: multi-ring/multi-thread
    allreduce.  Ring scheduling is XLA's job on TPU; the rewrite is the
    same GradAllReduce insertion."""

    def __init__(self, nrings=1, trans_mode="all_reduce"):
        super().__init__(nrings)
        self.mode = trans_mode
