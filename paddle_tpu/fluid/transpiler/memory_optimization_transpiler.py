"""fluid.transpiler.memory_optimization_transpiler analog.

The reference's var-reuse rewriting (memory_optimization_transpiler.py)
was already deprecated in 1.8 in favor of build-strategy passes; on this
stack XLA owns buffer liveness and reuse outright (SURVEY §2.2 TPU note),
so both entry points are contract-keeping no-ops that warn once."""
from __future__ import annotations

import warnings

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    warnings.warn(
        "memory_optimize is a no-op on the TPU build: XLA performs buffer "
        "sharing/reuse during compilation (the reference deprecated this "
        "pass in 1.8 as well)", DeprecationWarning, stacklevel=2)
    return None


def release_memory(input_program, skip_opt_set=None):
    warnings.warn(
        "release_memory is a no-op on the TPU build: XLA owns HBM "
        "lifetime", DeprecationWarning, stacklevel=2)
    return None
