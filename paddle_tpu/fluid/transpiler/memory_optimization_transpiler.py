"""fluid.transpiler.memory_optimization_transpiler analog.

The reference's var-reuse rewriting (memory_optimization_transpiler.py)
was already deprecated in 1.8 in favor of build-strategy passes; on this
stack XLA owns buffer liveness and reuse outright (SURVEY §2.2 TPU note).
Both entry points are deprecation shims that route through the IR pass
manager (fluid/passes/): they apply the registered
``memory_optimize_legacy`` no-op pass, so a legacy caller sees a
``pass::memory_optimize_legacy`` span and counter in the observability
plane instead of silently doing nothing.  Callers who want the op-stream
actually shrunk should set ``BuildStrategy.memory_optimize = True`` on a
CompiledProgram — that wires the real constant_fold / prune_identity /
dce passes (docs/passes.md).
"""
from __future__ import annotations

import warnings

__all__ = ["memory_optimize", "release_memory"]


def _apply_legacy_noop(input_program):
    from ..passes import PassPipeline, create_pass
    if input_program is None or not hasattr(input_program, "blocks"):
        return None
    return PassPipeline([create_pass("memory_optimize_legacy")]).apply(
        input_program)


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    warnings.warn(
        "memory_optimize is deprecated on the TPU build: XLA performs "
        "buffer sharing/reuse during compilation (the reference "
        "deprecated this pass in 1.8 as well).  The call now routes "
        "through the IR pass manager as the no-op "
        "'memory_optimize_legacy' pass; for real op-stream shrinking use "
        "CompiledProgram with BuildStrategy.memory_optimize=True "
        "(docs/passes.md)", DeprecationWarning, stacklevel=2)
    _apply_legacy_noop(input_program)
    return None


def release_memory(input_program, skip_opt_set=None):
    warnings.warn(
        "release_memory is deprecated on the TPU build: XLA owns HBM "
        "lifetime; the call routes through the IR pass manager as a "
        "traced no-op", DeprecationWarning, stacklevel=2)
    _apply_legacy_noop(input_program)
    return None
