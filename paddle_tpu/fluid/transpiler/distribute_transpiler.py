"""fluid.transpiler.distribute_transpiler analog (reference transpiler/
distribute_transpiler.py DistributeTranspiler:256).

The reference rewrites the program into send/recv ops against
listen_and_serv pserver programs.  The TPU build's PS runtime
(distributed/ps/) replaces that op plumbing with a pull -> device-step ->
push loop driven by a PsPlan carried in program._hints, served by the TCP
RPC table tier.  This shim keeps the 1.x user flow:

    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id, pservers=..., trainers=N, sync_mode=...)
    # pserver process:
    ps_prog = t.get_pserver_program(ep)       # blocks inside exe.run
    exe.run(t.get_startup_program(ep, ps_prog))
    exe.run(ps_prog)
    # trainer process:
    exe.run(startup); exe.run(t.get_trainer_program(), feed=..., ...)

by translating transpile() arguments into the same PsPlan the fleet 2.0
pass produces (optimizer ops stripped from the trainer, sparse lookups
swapped to ps_lookup_rows, accessor kind + lr lifted from the optimizer
ops), and into the env contract the PS runtime reads."""
from __future__ import annotations

import os
from typing import Optional

from ..framework import (default_main_program, default_startup_program,
                         Parameter, _OPTIMIZER_OP_TYPES)
from .ps_dispatcher import RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """Reference DistributeTranspilerConfig (transpiler knobs).  Block
    slicing (slice_var_up/min_block_size) has no analog: the TPU-side
    tables shard by feasign hash, not by param block."""
    slice_var_up = True
    split_method = RoundRobin
    min_block_size = 8192
    enable_dc_asgd = False
    sync_mode = None
    runtime_split_send_recv = False
    half_async = False
    completely_not_async = False
    # GEO knobs (geo_sgd_transpiler reads them)
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100


_ACCESSOR_OF_OP = {"sgd": "sgd", "momentum": "sgd", "adagrad": "adagrad",
                   "adam": "adam", "adamw": "adam", "lamb": "adam",
                   "rmsprop": "adagrad", "ftrl": "sgd", "dpsgd": "sgd",
                   "lars_momentum": "sgd", "dgc_momentum": "sgd"}


def _lr_value_of(program, startup, lr_name, default=0.01):
    """The lr var is seeded by a fill op in one of the two programs (the
    create_global_var pattern); read its value."""
    for prog in (startup, program):
        if prog is None:
            continue
        for b in prog.blocks:
            for op in b.ops:
                if op.type == "fill_constant" and \
                        lr_name in op.output_arg_names:
                    return float(op.attr("value", default))
    return default


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._plan = None
        self._program = None
        self._startup = None
        self._eps = []
        self._trainers = 1
        self._trainer_id = 0

    # -- the rewrite ---------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        from ...distributed.ps.program_pass import (PsPlan,
                                                    _startup_init_kind,
                                                    ROWS_SUFFIX, GRAD_SUFFIX,
                                                    _SPARSE_LOOKUP_TYPES)

        program = program or default_main_program()
        startup = startup_program or default_startup_program()
        self._program, self._startup = program, startup
        self._eps = [e.strip() for e in pservers.split(",") if e.strip()]
        self._trainers, self._trainer_id = int(trainers), int(trainer_id)

        mode = "sync" if sync_mode else "async"
        if getattr(self.config, "geo_sgd_mode", False):
            mode = "geo"
        block = program.global_block()

        # 0. the 1.x contract is minimize-then-transpile, but the swap
        #    below changes what the backward must differentiate (W-grad
        #    becomes pulled-rows-grad) — so lift the optimizer facts, find
        #    the loss from its grad seed, strip backward+optimizer ops,
        #    and re-derive backward AFTER the swap
        accessor, lr, loss_name = None, None, None
        for b in program.blocks:
            for op in b.ops:
                if (loss_name is None and op.type == "fill_constant"
                        and op.attr("op_role", 0) == 1):
                    out = op.output_arg_names[0]
                    if out.endswith("@GRAD"):
                        loss_name = out[:-len("@GRAD")]
                if accessor is None and op.type in _OPTIMIZER_OP_TYPES:
                    accessor = _ACCESSOR_OF_OP.get(op.type, "sgd")
                    lr_in = op.input("LearningRate")
                    lr = _lr_value_of(program, startup,
                                      lr_in[0] if lr_in else "")
        if accessor is None:
            raise ValueError(
                "transpile() found no optimizer ops — call "
                "optimizer.minimize(loss) before transpiling (the 1.x flow)")
        for b in program.blocks:
            b.ops = [op for op in b.ops
                     if op.attr("op_role", 0) == 0
                     and op.type != "generic_grad"
                     and not op.type.endswith("_grad")
                     and op.type not in _OPTIMIZER_OP_TYPES]
            b.program._bump_version()

        # 1. sparse lookups -> ps_lookup_rows (same in-place swap as
        #    apply_ps_pass)
        plan_sparse = []
        sparse_params = set()
        for op in block.ops:
            if op.type not in _SPARSE_LOOKUP_TYPES:
                continue
            w_name = op.input("W")[0]
            w = block._find_var_recursive(w_name)
            if not isinstance(w, Parameter):
                continue
            if not (op.attr("is_sparse") or op.attr("is_distributed")
                    or getattr(w, "is_distributed", False)):
                continue
            ids_name = op.input("Ids")[0]
            dim = int(w.shape[-1])
            k = len(plan_sparse)
            rows_name = f"{w_name}{ROWS_SUFFIX}{k}"
            rows = block.create_var(name=rows_name, shape=(-1, dim),
                                    dtype=w.dtype, is_data=True)
            rows.stop_gradient = False
            is_v1 = op.type == "lookup_table"
            pad = op.attr("padding_idx", -1)
            op.type = "ps_lookup_rows"
            op.inputs = {"Rows": [rows_name], "Ids": [ids_name]}
            op.attrs = {"padding_idx": pad, "v1": is_v1, "op_role": 0}
            init_kind, init_scale = _startup_init_kind(startup, w_name)
            plan_sparse.append({
                "table": w_name, "dim": dim, "ids": ids_name,
                "rows": rows_name, "grad": rows_name + GRAD_SUFFIX,
                "init_kind": init_kind, "init_scale": init_scale})
            sparse_params.add(w_name)

        # 2. re-derive backward on the swapped program: dense params get
        #    their grads back, the pulled rows get rows@GRAD (the tensors
        #    the push phase ships to the tables); NO optimizer ops — the
        #    server table IS the optimizer
        from ..backward import append_backward
        if loss_name is None:
            raise ValueError("transpile(): could not locate the loss "
                             "gradient seed in the minimized program")
        loss_var = block._find_var_recursive(loss_name)
        params_grads = append_backward(loss_var)
        plan_dense = []
        for p, g in params_grads:
            if p.name in sparse_params or g is None:
                continue
            plan_dense.append({"param": p.name, "grad": g.name,
                               "shape": list(p.shape)})

        plan = PsPlan(mode, accessor, lr)
        plan.sparse = plan_sparse
        plan.dense = plan_dense
        self._plan = plan
        program._hints["ps_plan"] = plan

        # 3. env contract the PS runtime reads (rpc endpoints + role)
        os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(self._eps)
        os.environ["PADDLE_TRAINERS_NUM"] = str(self._trainers)
        return program

    # -- role programs -------------------------------------------------------
    def _init_fleet(self, role, current_endpoint=None):
        from ...distributed import fleet
        from ...distributed.fleet.base.role_maker import (UserDefinedRoleMaker,
                                                          Role)
        from ...distributed.fleet import DistributedStrategy
        rm = UserDefinedRoleMaker(
            current_id=(self._eps.index(current_endpoint)
                        if role == Role.SERVER and current_endpoint in
                        self._eps else self._trainer_id),
            role=role, worker_num=self._trainers,
            server_endpoints=self._eps)
        strat = DistributedStrategy()
        strat.a_sync = self._plan.mode != "sync"
        if self._plan.mode == "geo":
            strat.a_sync_configs = {"k_steps": getattr(
                self.config, "geo_sgd_need_push_nums", 100)}
        fleet.init(rm, strategy=strat)
        fleet._fleet_singleton._user_defined_strategy = strat
        return fleet

    def get_trainer_program(self, wait_port=True):
        """The rewritten main program; also brings up the worker runtime so
        a bare `exe.run(program)` drives the pull/step/push loop."""
        from ...distributed.fleet.base.role_maker import Role
        fleet = self._init_fleet(Role.WORKER)
        fleet.init_worker()
        return self._program

    def get_pserver_program(self, endpoint):
        """A server program: running it in an Executor starts the table
        server for `endpoint` and blocks until trainers send stop (the
        listen_and_serv_op role, executor-hooked via the ps_server hint)."""
        from ..framework import Program
        prog = Program()
        prog._hints["ps_server"] = {
            "endpoint": endpoint,
            "eps": list(self._eps),
            "trainers": self._trainers,
            "mode": self._plan.mode if self._plan else "sync",
            "geo_k": getattr(self.config, "geo_sgd_need_push_nums", 100),
        }
        return prog

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), \
            self.get_startup_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Server-side startup: tables initialise lazily on first pull, so
        this is an empty program kept for flow parity."""
        from ..framework import Program
        return Program()


def serve_ps_program(hints):
    """Executor entry for a get_pserver_program() Program: bring up the
    table server for this endpoint and block until trainers send stop."""
    from ...distributed import fleet
    from ...distributed.fleet.base.role_maker import (UserDefinedRoleMaker,
                                                      Role)
    from ...distributed.fleet import DistributedStrategy
    ep = hints["endpoint"]
    eps = hints["eps"]
    host, port = ep.rsplit(":", 1)
    os.environ["POD_IP"] = host
    os.environ["PADDLE_PORT"] = port
    os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(eps)
    os.environ["PADDLE_TRAINERS_NUM"] = str(hints.get("trainers", 1))
    rm = UserDefinedRoleMaker(
        current_id=eps.index(ep) if ep in eps else 0, role=Role.SERVER,
        worker_num=int(hints.get("trainers", 1)), server_endpoints=eps)
    strat = DistributedStrategy()
    strat.a_sync = hints.get("mode", "sync") != "sync"
    if hints.get("mode") == "geo":
        strat.a_sync_configs = {"k_steps": hints.get("geo_k", 100)}
    fleet.init(rm, strategy=strat)
    fleet._fleet_singleton._user_defined_strategy = strat
    fleet.init_server()
    fleet.run_server()
    return []
