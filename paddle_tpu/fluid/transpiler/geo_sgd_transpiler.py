"""fluid.transpiler.geo_sgd_transpiler analog (reference transpiler/
geo_sgd_transpiler.py): GEO-SGD — trainers step locally, push deltas
every k steps; here the plan mode is "geo" and the GeoCommunicator
(distributed/ps/communicator.py) batches the delta pushes."""
from __future__ import annotations

from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)

__all__ = ["GeoSgdTranspiler"]


class GeoSgdTranspiler(DistributeTranspiler):
    def __init__(self, config=None):
        config = config or DistributeTranspilerConfig()
        config.geo_sgd_mode = True
        super().__init__(config)
