"""Async step pipeline: lazy fetches + bounded in-flight dispatch window.

Reference: operators/reader/buffered_reader.cc keeps a double-buffer thread
between the host feed path and the device, and ParallelExecutor's async
SSA-graph executors (fast_threaded_ssa_graph_executor.cc) keep the host out
of the device's critical path.  TPU-native: XLA dispatch is ALREADY async —
``jax.jit``'d calls return device arrays immediately — so the framework's
job is to stop forcing synchronisation.  Three pieces live here:

* :class:`FetchHandle` — the lazy fetch wrapper ``Executor.run`` returns
  under ``return_numpy=False``: a live device array that materialises on
  ``.numpy()`` / ``np.asarray`` / ``float()``; NaN scans and deferred
  checkify errors surface at materialisation, not at dispatch.
* :class:`AsyncStepRunner` — ``submit(feed)`` dispatches steps while
  keeping at most ``FLAGS_max_inflight_steps`` dispatches outstanding;
  backpressure blocks on the OLDEST step's handles (the framework.channel.h
  bounded-queue analog).  With ``steps_per_dispatch=K`` it groups K feeds
  and drives them through one ``lax.scan``-compiled executable
  (``Executor.run_scan``) — one Python dispatch, K device steps.
* :func:`batch_stack` / :func:`group_steps` — the loader-side staging
  hooks: group K feeds and ``jax.device_put`` them on the Prefetcher's
  producer thread (sharded along the data-parallel axis when a mesh is
  active) so H2D transfer overlaps device compute.

Observability (docs/observability.md): ``executor.inflight_steps`` /
``executor.inflight_peak`` gauges, ``executor.dispatch_seconds`` vs
``executor.host_wait_seconds`` histograms — the overlap is visible, not
inferred.

Donation safety: with ``donate_buffers`` active the NEXT dispatch donates
the scope's state arrays to XLA.  A still-live older fetch that aliases
that state (``FetchHandle.aliases_state``) would then read a deleted
buffer — the Executor registers every aliasing lazy fetch
(``Executor._alias_live``) and persists (host-copies) them before any
donating dispatch, across runners, programs, and sync runs.  The runner's
``donate_guard=True`` replicates that guard locally for duck-typed /
fake executors (tests simulating donation on CPU).

Single-threaded contract: one runner is driven from one thread (the train
loop); the device-side overlap comes from XLA's async dispatch, not from
host threads.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from . import core
from . import trace


class ScanUnsupportedError(ValueError):
    """Raised by Executor.run_scan when the program cannot be scan-fused
    (mesh / pipeline / recompute / PS hints, checkify debug mode,
    non-uniform feed shapes).  AsyncStepRunner catches it and degrades to
    sequential dispatches — permanently for structural causes
    (``permanent=True``, the default), per-group for transient ones like
    a ragged tail batch or a debug flag that may be turned off."""

    def __init__(self, msg, permanent=True):
        super().__init__(msg)
        self.permanent = permanent


def _once(fn: Callable[[], None]) -> Callable[[], None]:
    """Idempotent wrapper: shared across one step's handles so a deferred
    checkify throw fires exactly once no matter which handle materialises
    first."""
    done = [False]

    def call():
        if not done[0]:
            done[0] = True
            fn()
    return call


class FetchHandle:
    """A lazy fetch: wraps the live device array of one fetched var.

    Materialisation (``numpy()`` / ``__array__`` / ``float()``) is the
    ONLY point that forces a D2H transfer; until then the array stays
    device-resident and the host keeps dispatching.  Deferred per-op
    checkify errors (``pre_check``) and the ``FLAGS_check_nan_inf`` fetch
    scan run at materialisation — an error raised at dispatch N surfaces
    when handle N is read, never earlier and never lost by the runner
    (``AsyncStepRunner.drain`` re-raises unconsumed dispatch errors).
    """

    __slots__ = ("name", "aliases_state", "_raw", "_np", "_pre_check",
                 "_check_nan", "_waiter", "__weakref__")

    def __init__(self, value, name: Optional[str] = None,
                 aliases_state: bool = False, check_nan: bool = False,
                 pre_check: Optional[Callable[[], None]] = None,
                 waiter: Optional[Callable[[], None]] = None):
        self.name = name
        self.aliases_state = bool(aliases_state)
        self._raw = value
        self._np: Optional[np.ndarray] = None
        self._pre_check = pre_check
        self._check_nan = bool(check_nan)
        self._waiter = waiter          # test seam: fake-device completion

    # -- introspection (no sync) -------------------------------------------
    @property
    def raw(self):
        """The underlying device array (no host copy, no sync)."""
        return self._raw if self._np is None else self._np

    @property
    def shape(self):
        return tuple(np.shape(self.raw))

    @property
    def dtype(self):
        return np.dtype(getattr(self.raw, "dtype", type(self.raw)))

    @property
    def ndim(self):
        return len(self.shape)

    def is_materialized(self) -> bool:
        return self._np is not None

    # -- synchronisation ----------------------------------------------------
    def block_until_ready(self) -> "FetchHandle":
        """Wait for the device value (no host copy).  Deferred dispatch
        checks fire here too — blocking on handle N surfaces step N's
        error."""
        self._run_pre_check()
        if self._waiter is not None:
            self._waiter()
        elif self._np is None:
            import jax
            jax.block_until_ready(self._raw)
        return self

    def persist(self) -> np.ndarray:
        """Materialise to host and cache — after this the handle survives
        donation of the underlying device buffer.  Safe under concurrent
        callers (the serving plane persists from its collector thread
        while the runner's backpressure path may persist the same
        handle): the loser of the race re-reads the winner's cached
        value instead of converting an already-dropped reference."""
        if self._np is None:
            self._run_pre_check()
            if self._waiter is not None:
                self._waiter()
            raw = self._raw            # local ref: survives a concurrent
            if raw is None:            # winner clearing the attribute
                return self._np
            v = np.asarray(raw)
            if self._check_nan and np.issubdtype(v.dtype, np.floating) \
                    and not np.all(np.isfinite(v)):
                raise FloatingPointError(
                    f"NaN/Inf in fetched var '{self.name}'")
            self._np = v               # publish BEFORE dropping the ref
            self._raw = None
        return self._np

    def _run_pre_check(self):
        if self._pre_check is not None:
            check, self._pre_check = self._pre_check, None
            check()

    # -- materialisation protocols -----------------------------------------
    def numpy(self) -> np.ndarray:
        return self.persist()

    def __array__(self, dtype=None, copy=None):
        v = self.persist()
        return v.astype(dtype) if dtype is not None else v

    def __float__(self):
        return float(np.ravel(self.persist())[0])

    def __int__(self):
        return int(np.ravel(self.persist())[0])

    def __repr__(self):
        state = "np" if self._np is not None else "device"
        return (f"FetchHandle({self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, {state})")


class _LazyFetch:
    """A fetch bound to a not-yet-dispatched step (scan grouping buffers
    feeds).  Reading it numerically flushes the runner's partial group and
    materialises — which is why per-step host-side logging caps the
    effective ``steps_per_dispatch`` (docs/performance.md)."""

    __slots__ = ("_future", "_index")

    def __init__(self, future: "StepFuture", index: int):
        self._future = future
        self._index = index

    def handle(self) -> FetchHandle:
        return self._future.handles()[self._index]

    def numpy(self) -> np.ndarray:
        return self.handle().persist()

    def __array__(self, dtype=None, copy=None):
        return self.handle().__array__(dtype)

    def __float__(self):
        return float(self.handle())

    def __int__(self):
        return int(self.handle())

    def __repr__(self):
        return f"_LazyFetch(step fetch #{self._index})"


class StepFuture:
    """One submitted step's result: resolves to a list of FetchHandles.

    A dispatch error is stored and raised when THIS step's handles are
    requested; ``AsyncStepRunner.drain`` raises any error nobody consumed.
    """

    __slots__ = ("_runner", "_handles", "_error", "_consumed")

    def __init__(self, runner: "AsyncStepRunner"):
        self._runner = runner
        self._handles: Optional[List[FetchHandle]] = None
        self._error: Optional[BaseException] = None
        self._consumed = False

    def _set_handles(self, handles: List[FetchHandle]):
        self._handles = list(handles)

    def _set_error(self, exc: BaseException):
        self._error = exc

    @property
    def dispatched(self) -> bool:
        return self._handles is not None or self._error is not None

    def handles(self) -> List[FetchHandle]:
        """The step's FetchHandles; forces dispatch of a buffered partial
        scan group, and raises the step's dispatch error if it had one."""
        if not self.dispatched:
            self._runner.flush()
        if self._error is not None:
            self._consumed = True
            raise self._error
        return self._handles

    def lazy(self, index: int = 0) -> _LazyFetch:
        """A deferred view of fetch ``index`` that does NOT force dispatch
        until read numerically — what hapi.Model.fit hands to callbacks."""
        return _LazyFetch(self, index)

    def result(self) -> List[np.ndarray]:
        """Materialise every fetch to numpy (the blocking read)."""
        return [h.persist() for h in self.handles()]

    def __len__(self):
        return len(self.handles())

    def __iter__(self):
        return iter(self.handles())

    def __getitem__(self, i):
        return self.handles()[i]


class AsyncStepRunner:
    """Bounded in-flight dispatch window over one (program, fetch set).

    ``submit(feed)`` returns a :class:`StepFuture` immediately; at most
    ``max_inflight`` dispatches stay outstanding — the window applies
    backpressure by blocking on the oldest dispatch's handles, and the
    blocked time lands in ``executor.host_wait_seconds`` (vs
    ``executor.dispatch_seconds`` for time spent dispatching), so the
    host/device overlap is measurable.  ``steps_per_dispatch=K`` buffers K
    feeds and drives them through ``Executor.run_scan`` (one lax.scan
    executable, K device steps per Python dispatch); programs the scan path
    cannot fuse (mesh/pipeline/recompute/PS) degrade to sequential
    dispatches transparently.
    """

    def __init__(self, executor, program, fetch_list: Sequence,
                 scope=None, max_inflight: Optional[int] = None,
                 steps_per_dispatch: Optional[int] = None,
                 donate_guard: Optional[bool] = None):
        self._exe = executor
        self._program = program
        self._fetch_list = list(fetch_list or [])
        self._scope = scope
        if max_inflight is None:
            max_inflight = core.get_flag("max_inflight_steps", 2)
        self.max_inflight = max(1, int(max_inflight or 1))
        prog = getattr(program, "_program", program)
        hints = getattr(prog, "_hints", {}) or {}
        if steps_per_dispatch is None:
            steps_per_dispatch = (hints.get("steps_per_dispatch")
                                  or core.get_flag("steps_per_dispatch", 1))
        self.steps_per_dispatch = max(1, int(steps_per_dispatch or 1))
        if (getattr(program, "_mesh", None) is not None
                or hints.get("pipeline_microbatches")
                or hints.get("recompute_checkpoints")
                or hints.get("ps_plan") or hints.get("ps_server")):
            # these step builders do their own batch surgery / host loops —
            # no scan fusion, plain async window only
            self.steps_per_dispatch = 1
        self._donate_guard = donate_guard
        self._pending: List[tuple] = []    # (feed, future, trace ctx)
        self._inflight: "deque[List[FetchHandle]]" = deque()
        # serialises the window's FRONT pops: _wait_oldest (batcher /
        # drain thread) vs reap() (serving collector) — never held
        # across a device wait
        self._pop_lock = threading.Lock()
        self._error_futures: List[StepFuture] = []
        # every not-yet-persisted state-aliasing handle issued while
        # donation is active — the guard persists THESE before a dispatch
        # donates, so handles the window already waited out (or that the
        # caller holds across drain()) are covered too, not just the ones
        # still sitting in _inflight
        self._alias_handles: List[FetchHandle] = []
        self._scan_ok = self.steps_per_dispatch > 1
        # elastic-runtime accounting (distributed/elastic.py): after a
        # drain() every submitted step has completed, so `submitted` IS
        # the exact resume cursor a preemption checkpoint records
        self.submitted = 0

    # -- public -------------------------------------------------------------
    def submit(self, feed: Dict[str, Any]) -> StepFuture:
        fut = StepFuture(self)
        self.submitted += 1
        # the submitter's ambient trace context (a serving batch id)
        # rides with the feed: a buffered scan group dispatches LATER,
        # possibly under a different request's context — the step must
        # still attribute to the one that submitted it
        self._pending.append((dict(feed or {}), fut,
                              trace.current_trace_id()))
        if len(self._pending) >= self.steps_per_dispatch:
            self._dispatch_group()
        return fut

    def flush(self):
        """Dispatch a buffered partial scan group now (epoch tails,
        eager metric reads)."""
        self._dispatch_group()

    def drain(self):
        """Dispatch everything, wait for every in-flight step, and raise
        the first dispatch error nobody consumed — an error at dispatch N
        is never lost, even if handle N was never read."""
        self.flush()
        while self._inflight:
            self._wait_oldest()
        for fut in self._error_futures:
            if not fut._consumed:
                fut._consumed = True
                raise fut._error
        self._error_futures = [f for f in self._error_futures
                               if not f._consumed]

    def abort(self):
        """Error-path cleanup: DROP buffered feeds (their futures resolve
        to an error, never dispatch stale batches later), wait out
        in-flight dispatches, and clear stored errors — without raising,
        so the primary exception in the driving loop stays primary."""
        aborted = RuntimeError(
            "AsyncStepRunner.abort(): step was buffered when the driving "
            "loop aborted — it was never dispatched")
        for _, fut, _ctx in self._pending:
            fut._set_error(aborted)
        self.submitted -= len(self._pending)    # never ran: not resumable
        self._pending = []
        while self._inflight:
            try:
                self._wait_oldest()
            except Exception:       # noqa: BLE001 — cleanup never raises
                pass
        self._error_futures = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.drain()
        return False

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    def reap(self) -> None:
        """Pop fully-materialised entries off the front of the window —
        for consumers (the serving collector) that wait results OUT of
        band instead of through drain()/backpressure.  Without this, the
        last dispatched batch sits in the window forever once traffic
        stops, and ``executor.inflight_steps`` reads >0 on an idle
        engine — which the SLO watchdog must interpret as outstanding
        work (a false ``stalled`` verdict that would get a healthy idle
        replica ejected from a fleet).  The front-pop is serialised with
        ``_wait_oldest`` through ``_pop_lock`` (check-then-pop on the
        bare deque would race the batcher's backpressure pop); the lock
        never spans a device wait, so contention is a few instructions."""
        with self._pop_lock:
            popped = False
            while self._inflight and all(h.is_materialized()
                                         for h in self._inflight[0]):
                self._inflight.popleft()
                popped = True
            if popped:
                # gauge set INSIDE the lock: outside it, a stale 0 from
                # this thread could overwrite the count of a batch the
                # batcher dispatched in between — and the watchdog would
                # miss that batch wedging
                trace.metrics().gauge("executor.inflight_steps").set(
                    len(self._inflight))

    @property
    def pending(self) -> int:
        """Buffered submits not yet dispatched (a partial scan group).
        Their updates are NOT in the scope yet, so a point-in-time
        checkpoint cursor is ``submitted - pending`` until a
        flush()/drain() empties the buffer."""
        return len(self._pending)

    # -- internals ----------------------------------------------------------
    def _dispatch_feeds(self, feeds: List[Dict[str, Any]]
                        ) -> List[List[FetchHandle]]:
        """One group -> per-step handle lists.  Overridable test seam."""
        if len(feeds) > 1 and self._scan_ok:
            try:
                return self._exe.run_scan(
                    self._program, feeds, self._fetch_list,
                    scope=self._scope, return_handles=True)
            except ScanUnsupportedError as e:
                if getattr(e, "permanent", True):
                    self._scan_ok = False   # structural: dispatch 1:1
                else:
                    # transient (ragged tail group, debug flag): THIS
                    # group runs sequentially, the next uniform group
                    # scans again — counted, never silent
                    trace.metrics().counter(
                        "executor.scan_fallback_groups").inc()
        return [self._exe.run(self._program, feed=f,
                              fetch_list=self._fetch_list,
                              scope=self._scope, return_numpy=False)
                for f in feeds]

    def _dispatch_group(self):
        group, self._pending = self._pending, []
        if not group:
            return
        # donation safety for REAL Executors lives executor-side
        # (Executor._alias_live: run() registers aliasing handles, every
        # donating dispatch persists them first).  The runner-local guard
        # below runs only on explicit donate_guard=True — duck-typed /
        # fake executors and tests that simulate donation on CPU.
        donate = self._donate_guard is True
        try:
            # backpressure BEFORE dispatching: never more than
            # max_inflight dispatches outstanding
            while len(self._inflight) >= self.max_inflight:
                self._wait_oldest()
            if donate:
                # the dispatch below would donate the scope's state
                # buffers — host-copy every still-live fetch that aliases
                # them first (in-flight or already waited out)
                for h in self._alias_handles:
                    h.persist()
                del self._alias_handles[:]
        except BaseException:
            # an OLDER step's deferred error (NaN scan, checkify) — the
            # new group was never dispatched: put it back so its futures
            # aren't stranded without handles or error, then surface
            self._pending = group + self._pending
            raise
        m = trace.metrics()
        t0 = time.perf_counter()
        # restore the SUBMITTER's trace context around the real dispatch:
        # a buffered group dispatches later (flush/next submit), possibly
        # under another request's ambient context — the executor::step
        # span and step wide event must attribute to the context that
        # submitted the group (its head; a scan group shares one span)
        token = trace.set_context(group[0][2])
        try:
            per_step = self._dispatch_feeds([f for f, _, _ in group])
        except BaseException as exc:    # noqa: BLE001 — stored, not lost
            for _, fut, _ctx in group:
                fut._set_error(exc)
                self._error_futures.append(fut)
            m.counter("executor.async_dispatch_errors").inc()
            return
        finally:
            trace.restore_context(token)
        m.histogram("executor.dispatch_seconds").observe(
            time.perf_counter() - t0)
        m.counter("executor.async_steps").inc(len(group))
        # PS-wrapped programs and duck-typed executors may hand back raw
        # arrays — normalise so futures always resolve to FetchHandles
        per_step = [[h if isinstance(h, FetchHandle) else FetchHandle(h)
                     for h in hs] for hs in per_step]
        flat: List[FetchHandle] = []
        for (_, fut, _ctx), handles in zip(group, per_step):
            fut._set_handles(handles)
            flat.extend(handles)
        if donate:
            self._alias_handles.extend(h for h in flat if h.aliases_state)
        self._inflight.append(flat)
        depth = len(self._inflight)
        m.gauge("executor.inflight_steps").set(depth)
        peak = m.gauge("executor.inflight_peak")
        if depth > peak.value:
            peak.set(depth)

    def _wait_oldest(self):
        with self._pop_lock:
            if not self._inflight:
                return
            handles = self._inflight.popleft()
        _sp = trace.now() if trace.enabled() else 0
        t0 = time.perf_counter()
        for h in handles:
            if h._check_nan:
                # FLAGS_check_nan_inf contract: the per-fetch scan must
                # fire even for fetches nobody reads — persist (host
                # copy) instead of just waiting, like the sync path did
                h.persist()
            else:
                h.block_until_ready()
        if _sp:
            # goodput plane: host blocked on device results = the device
            # was the bottleneck doing productive work — this span is
            # what charges backpressure to the device_compute bucket
            trace.complete("executor::host_wait", _sp, cat="step",
                           args={"n_handles": len(handles)})
        m = trace.metrics()
        m.histogram("executor.host_wait_seconds").observe(
            time.perf_counter() - t0)
        m.gauge("executor.inflight_steps").set(len(self._inflight))


# ---------------------------------------------------------------------------
# loader-side staging hooks
# ---------------------------------------------------------------------------

def group_steps(source: Iterable, k: int) -> Iterable[list]:
    """Group a feed stream into lists of up to ``k`` consecutive feeds —
    the unit `steps_per_dispatch=k` consumes.  The tail group may be
    short (scan == sequential numerics, so a short group is just less
    fusion, never different math)."""
    k = max(1, int(k))
    group: list = []
    for item in source:
        group.append(item)
        if len(group) >= k:
            yield group
            group = []
    if group:
        yield group


def _stage_one(feed, sharding):
    import jax
    if isinstance(feed, dict):
        return {k: jax.device_put(v, sharding) if sharding is not None
                else jax.device_put(v) for k, v in feed.items()}
    if isinstance(feed, (list, tuple)):
        return type(feed)(jax.device_put(v, sharding) if sharding is not None
                          else jax.device_put(v) for v in feed)
    return jax.device_put(feed, sharding) if sharding is not None \
        else jax.device_put(feed)


def batch_stack(k: int, mesh=None) -> Callable:
    """Prefetcher ``stage=`` hook for K-step groups: ``jax.device_put``
    every array of every feed in the group on the PRODUCER thread, so the
    H2D transfer of group t+1 overlaps the device steps of group t.  With
    a data-parallel mesh the batch axis is sharded across the mesh's first
    axis (the ``with_data_parallel`` layout)."""
    del k                               # the group is already formed
    sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        sharding = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))

    def stage(group):
        if isinstance(group, list):
            return [_stage_one(feed, sharding) for feed in group]
        return _stage_one(group, sharding)
    return stage
