"""fluid.log_helper analog."""
from __future__ import annotations

import logging

__all__ = ["get_logger"]


def get_logger(name, level, fmt=None):
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:
        h = logging.StreamHandler()
        if fmt:
            h.setFormatter(logging.Formatter(fmt))
        logger.addHandler(h)
    logger.propagate = False
    return logger
