"""Fault-tolerant training checkpoints: async snapshots, atomic commit,
deterministic resume.

Reference: python/paddle/fluid/io.py save_persistables/load_persistables is
the reference's checkpoint path — a blocking, whole-state save the PS/BoxPS
trainers call between passes.  On preemptible TPUs that contract is not
enough: the save must come OFF the step window (an async snapshot while
the device keeps training), the on-disk artifact must survive a crash at
ANY byte (atomic commit + checksums), and a restart must resume
bit-deterministically (params, optimizer accumulators including fp32
masters, program ``random_seed``, the executor's per-step PRNG counter,
host RNG streams, and the data-loader cursor).  This module owns all of
that; `paddle_tpu/distributed/elastic.py` layers the preemption plane
(SIGTERM drain, resumable marker) on top.

Checkpoint layout (``docs/checkpointing.md`` has the full schema)::

    <root>/
      ckpt-00000042/
        manifest.json        # written LAST inside the tmp dir; commit is
                             # one atomic directory rename
        shard-00000.npz      # vars grouped up to FLAGS_checkpoint_shard_bytes
        shard-00001.npz
      ckpt-00000040/ ...
      RESUMABLE              # preemption marker (distributed/elastic.py)

Durability protocol: every shard is staged into ``.tmp-ckpt-*`` with
``write → flush → fsync``; the manifest (carrying a sha256 per shard) is
written last; the tmp directory is fsynced and committed with one
``os.rename`` onto the final name, then the parent directory is fsynced.
A crash before the rename leaves only a tmp dir (ignored + garbage
collected); a crash after it leaves a fully valid checkpoint.  ``restore``
re-verifies every checksum and silently falls back to the newest INTACT
checkpoint when the newest one is torn (counted in
``ckpt.restore_fallbacks``).

Donation safety (the PR-4 alias-guard path): an async snapshot must not
host-copy on the training thread, but with ``donate_buffers`` the next
dispatch donates the very scope buffers the snapshot references.  The
snapshot therefore wraps each state array in a ``FetchHandle`` with
``aliases_state=True`` registered on the executor's ``_alias_live`` list —
any donating dispatch persists them (host copy) first, and the background
writer's ``device_get`` happens off-thread either way, so the step window
never blocks on checkpoint IO.

Observability: ``ckpt.saves`` / ``ckpt.restores`` / ``ckpt.bytes`` /
``ckpt.save_errors`` / ``ckpt.save_retries`` / ``ckpt.restore_fallbacks``
counters, ``ckpt.save_seconds`` / ``ckpt.restore_seconds`` histograms and
``checkpoint::save`` / ``checkpoint::restore`` spans on the trace plane.
"""
from __future__ import annotations

import hashlib
import io as _io
import json
import os
import queue
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import core
from . import trace

__all__ = [
    "CheckpointManager", "CheckpointState", "CheckpointError",
    "CorruptCheckpointError", "InjectedCrash", "faults",
    "atomic_write_bytes", "list_checkpoint_steps", "latest_checkpoint_step",
]

FORMAT_VERSION = 1
MANIFEST = "manifest.json"
CKPT_PREFIX = "ckpt-"
TMP_PREFIX = ".tmp-ckpt-"


class CheckpointError(RuntimeError):
    """Base for checkpoint failures (missing state, exhausted retries)."""


class CorruptCheckpointError(CheckpointError):
    """Every on-disk checkpoint failed validation — nothing to resume."""


class InjectedCrash(RuntimeError):
    """Raised by the fault harness to simulate a process death mid-save.
    Deliberately NOT an OSError: the retry loop must not absorb it."""


# ---------------------------------------------------------------------------
# fault-injection harness (used by tests/ and tools/ci_smoke.py)
# ---------------------------------------------------------------------------

class FaultInjector:
    """Process-global switchboard for simulated storage failures.  Kinds:

    - ``io_error``        — ``atomic_write_bytes`` raises a *transient*
      OSError (consumed per armed count; the save retry loop absorbs it)
    - ``crash_after_tmp_write`` — raise :class:`InjectedCrash` after the
      shards are staged but BEFORE the manifest/commit (a death mid-save:
      no new checkpoint may appear)
    - ``torn_manifest``   — after commit, truncate the manifest mid-byte
      (a torn write from a non-atomic writer / bad disk)
    - ``partial_shard``   — after commit, truncate the first shard
      (silent data loss the checksums must catch)
    - ``slow_disk``       — sleep ``delay`` seconds inside every write

    Arm with ``faults.arm(kind, times=1, delay=...)``; each firing
    consumes one count.  ``faults.clear()`` between tests.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, Dict[str, Any]] = {}

    def arm(self, kind: str, times: int = 1, **kw) -> None:
        with self._lock:
            self._armed[kind] = dict(kw, times=int(times))

    def clear(self) -> None:
        with self._lock:
            self._armed.clear()

    def fire(self, kind: str) -> Optional[Dict[str, Any]]:
        """Consume one armed count of ``kind``; None when not armed."""
        with self._lock:
            ent = self._armed.get(kind)
            if not ent or ent["times"] <= 0:
                return None
            ent["times"] -= 1
            if ent["times"] <= 0:
                self._armed.pop(kind, None)
            return ent


faults = FaultInjector()


# ---------------------------------------------------------------------------
# durable-write primitives
# ---------------------------------------------------------------------------

def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss.
    Best-effort on filesystems that refuse O_RDONLY dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, do_fsync: bool = True) -> None:
    """The commit idiom shared with PR-2's PersistentCache: write to a
    same-directory tmp file, flush+fsync, then one atomic ``os.replace``.
    A reader never observes a half-written file; a crash leaves the old
    content (or nothing) — never a torn new one."""
    slow = faults.fire("slow_disk")
    if slow:
        time.sleep(float(slow.get("delay", 0.05)))
    if faults.fire("io_error"):
        raise OSError(f"injected transient IO error writing {path}")
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(d, f".tmp-{os.path.basename(path)}.{os.getpid()}"
                          f".{threading.get_ident()}")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if do_fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if do_fsync:
        _fsync_dir(d)


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# bf16 (and any other dtype numpy's npz format can't round-trip natively)
# is stored as its same-width unsigned view; the manifest records the true
# dtype so restore views it back bit-exactly
_DTYPE_ENCODE = {"bfloat16": "uint16", "float8_e4m3fn": "uint8",
                 "float8_e5m2": "uint8"}


def _encode_array(arr: np.ndarray):
    dt = str(arr.dtype)
    enc = _DTYPE_ENCODE.get(dt)
    if enc is not None:
        return arr.view(np.dtype(enc)), dt
    return arr, dt


def _decode_array(arr: np.ndarray, true_dtype: str) -> np.ndarray:
    if str(arr.dtype) != true_dtype and true_dtype in _DTYPE_ENCODE:
        import ml_dtypes
        return arr.view(np.dtype(getattr(ml_dtypes, true_dtype)))
    return arr


# ---------------------------------------------------------------------------
# per-shard (addressable-shard) IO — the sharding plane's checkpoint
# customer (parallel/sharding.py, docs/sharding.md).  A param sharded over
# the mesh is saved as its UNIQUE device shards (each an ordinary
# device_get of one device's slice) with the global index recorded per
# piece; the full array is never gathered to host.  Restore reassembles
# any requested slice from the pieces, so a checkpoint written on one mesh
# restores onto a different one (DP-8 save -> DP-4 restore) or onto a
# meshless single-chip scope, bit-exactly either way.
# ---------------------------------------------------------------------------

def _to_host(h) -> np.ndarray:
    """THE single full-array host-materialisation point of the save path
    (the tests' gather-spy seam): unsharded state and already-persisted
    handles come through here; multi-device-sharded state must not."""
    return h.persist() if hasattr(h, "persist") else np.asarray(h)


def _is_sharded_array(raw) -> bool:
    """True for a live multi-device jax.Array (the per-shard IO case)."""
    sharding = getattr(raw, "sharding", None)
    if sharding is None or not hasattr(raw, "addressable_shards"):
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:               # noqa: BLE001 — exotic sharding objs
        return False


def _sharded_value(h):
    """The live multi-device jax.Array behind a snapshot handle, or None
    when the value is host/single-device (or was already host-persisted
    by the donation alias guard — a gather that already happened)."""
    if hasattr(h, "is_materialized") and h.is_materialized():
        return None
    raw = getattr(h, "raw", h)
    return raw if _is_sharded_array(raw) else None


def _norm_index(index, shape):
    """Shard index (tuple of slices) -> hashable ((start, stop), ...)."""
    out = []
    for i, d in enumerate(tuple(int(x) for x in shape)):
        sl = index[i] if i < len(index) else slice(None)
        out.append((int(sl.start or 0),
                    d if sl.stop is None else int(sl.stop)))
    return tuple(out)


def _shard_pieces(arr):
    """Unique addressable shards of a sharded array, as
    ``[(index, np_piece), ...]`` sorted by index.  Replicated axes
    produce duplicate indices — saved once.  Each ``np.asarray`` is a
    device_get of ONE device's slice, never a cross-device gather."""
    shape = np.shape(arr)
    seen = {}
    for s in arr.addressable_shards:
        idx = _norm_index(s.index, shape)
        if idx not in seen:
            seen[idx] = np.asarray(s.data)
    return [(idx, seen[idx]) for idx in sorted(seen)]


class _ShardedState:
    """One sharded var staged for writing: global shape/dtype + pieces."""

    __slots__ = ("shape", "dtype", "pieces")

    def __init__(self, arr):
        self.shape = tuple(int(d) for d in np.shape(arr))
        self.pieces = _shard_pieces(arr)
        self.dtype = str(self.pieces[0][1].dtype)

    @property
    def nbytes(self) -> int:
        return sum(int(p.nbytes) for _, p in self.pieces)


_shard_handle_cls = None


def _snapshot_handle_cls():
    """Donation-safe snapshot handle for mesh-sharded state: when the
    executor's pre-donation alias guard calls ``persist()``, the handle
    materialises its UNIQUE addressable shards (one device_get per local
    shard) instead of gathering the full array to host — so the
    per-shard no-gather guarantee holds even on donating (TPU) runs
    where a dispatch overtakes the background writer.  Defined lazily:
    checkpoint stays importable without the async plane."""
    global _shard_handle_cls
    if _shard_handle_cls is None:
        from .async_pipeline import FetchHandle

        class _ShardSnapshotHandle(FetchHandle):
            __slots__ = ("sharded_pieces",)

            def __init__(self, value, name=None):
                super().__init__(value, name=name, aliases_state=True)
                self.sharded_pieces = None

            def persist(self):
                raw = self._raw     # local ref: FetchHandle's race idiom
                if self.sharded_pieces is None and self._np is None \
                        and raw is not None and _is_sharded_array(raw):
                    pieces = _ShardedState(raw)
                    self.sharded_pieces = pieces   # publish BEFORE the
                    self._raw = None               # buffer ref drops
                    return None
                return super().persist()

        _shard_handle_cls = _ShardSnapshotHandle
    return _shard_handle_cls


def _snapshot_handle(value, name):
    """Factory for one snapshot handle: sharded values get the
    per-shard-persisting handle, everything else a plain state-aliasing
    FetchHandle."""
    if _is_sharded_array(value):
        return _snapshot_handle_cls()(value, name)
    from .async_pipeline import FetchHandle
    return FetchHandle(value, name=name, aliases_state=True)


def _assemble_slice(target, shape, dtype, pieces):
    """Reassemble the ``target`` index (tuple of slices) of a var from
    its saved pieces — reads only the overlapping pieces.  ``pieces`` is
    ``[(index, load_fn), ...]`` with lazy per-piece loaders."""
    tgt = _norm_index(target, shape)
    out_shape = tuple(e - s for s, e in tgt)
    out = np.empty(out_shape, dtype=np.dtype(dtype))
    filled = 0
    for idx, load in pieces:
        inter = tuple((max(s0, s1), min(e0, e1))
                      for (s0, e0), (s1, e1) in zip(idx, tgt))
        if any(s >= e for s, e in inter):
            continue
        src = load()
        src_sel = tuple(slice(s - ps, e - ps)
                        for (s, e), (ps, _) in zip(inter, idx))
        dst_sel = tuple(slice(s - ts, e - ts)
                        for (s, e), (ts, _) in zip(inter, tgt))
        out[dst_sel] = src[src_sel]
        filled += int(np.prod([e - s for s, e in inter]) or 1)
    if filled != int(np.prod(out_shape) or 1):
        raise CorruptCheckpointError(
            f"sharded var pieces do not cover the requested slice "
            f"{tgt} of shape {shape} ({filled} of "
            f"{int(np.prod(out_shape) or 1)} elements)")
    return out


# ---------------------------------------------------------------------------
# directory scan helpers
# ---------------------------------------------------------------------------

def _step_dirname(step: int) -> str:
    return f"{CKPT_PREFIX}{int(step):08d}"


def list_checkpoint_steps(root: str) -> List[int]:
    """Committed checkpoint steps under ``root`` (unvalidated), ascending."""
    try:
        entries = os.listdir(root)
    except OSError:
        return []
    out = []
    for e in entries:
        if e.startswith(CKPT_PREFIX):
            try:
                out.append(int(e[len(CKPT_PREFIX):]))
            except ValueError:
                continue
    return sorted(out)


def latest_checkpoint_step(root: str) -> Optional[int]:
    steps = list_checkpoint_steps(root)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# snapshot source
# ---------------------------------------------------------------------------

def _collect_state_names(program, scope) -> List[str]:
    """Vars a checkpoint covers: the program's persistables that have a
    value in the scope (params, optimizer accumulators incl. fp32
    masters, learning-rate var, BN stats), or — with no program — every
    array-valued var in the scope."""
    if program is not None:
        prog = getattr(program, "_program", program)
        return sorted(
            v.name for v in prog.global_block().vars.values()
            if v.persistable and scope.find_var(v.name) is not None)
    out = []
    for n in scope.local_var_names():
        v = scope.find_var(n)
        if v is not None and hasattr(v, "dtype") and hasattr(v, "shape"):
            out.append(n)
    return sorted(out)


def _snapshot_handles(names: Sequence[str], scope, executor=None):
    """Point-in-time references to the scope's device arrays, wrapped as
    state-aliasing FetchHandles.  With an executor, each handle rides the
    PR-4 donation alias guard (``Executor._alias_live``): a later dispatch
    that donates the scope's buffers host-persists these first — for
    mesh-sharded state that persist is PER SHARD (``_snapshot_handle``),
    never a full gather — so the background writer always reads valid
    data and the training thread itself never pays a device_get."""
    if executor is not None and hasattr(executor, "snapshot_vars"):
        return executor.snapshot_vars(names, scope=scope,
                                      handle_factory=_snapshot_handle)
    return {n: _snapshot_handle(scope.find_var(n), n)
            for n in names if scope.find_var(n) is not None}


class CheckpointState:
    """What ``restore`` hands back: resume-relevant metadata."""

    def __init__(self, step: int, path: str, manifest: Dict[str, Any]):
        self.step = int(step)
        self.path = path
        self.manifest = manifest
        self.cursor: Dict[str, Any] = manifest.get("cursor") or {}
        self.extra: Dict[str, Any] = manifest.get("extra") or {}
        self.reason: str = manifest.get("reason", "periodic")
        self.var_names: List[str] = sorted(
            n for s in manifest.get("shards", []) for n in s.get("vars", {}))

    def __repr__(self):
        return (f"CheckpointState(step={self.step}, reason={self.reason!r}, "
                f"vars={len(self.var_names)}, cursor={self.cursor})")


class _SaveJob:
    __slots__ = ("step", "handles", "meta", "done", "error", "sync")

    def __init__(self, step, handles, meta, sync=False):
        self.step = step
        self.handles = handles
        self.meta = meta
        self.sync = bool(sync)
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class CheckpointManager:
    """Asynchronous, fault-tolerant checkpoint save/restore for one
    training job.

    ``save()`` snapshots full training state — program persistables
    (params, optimizer accumulators including fp32 masters), the
    program's ``random_seed``, the executor's per-step PRNG counter, the
    host numpy RNG stream, and a caller-supplied loader cursor — and, by
    default, hands the write to a background thread (one in-flight save;
    a second ``save`` while one is writing waits for it, bounding
    memory).  ``sync=True`` (the preemption path) writes inline.

    ``restore()`` validates manifest + per-shard sha256 checksums, falls
    back to the newest intact checkpoint on corruption, loads every var
    back into the scope (strict by default: a persistable the program
    declares but the checkpoint lacks, or a shape/dtype mismatch, raises
    naming the offenders), and restores the RNG/seed/step-counter plane
    so the continuation is bit-identical to an uninterrupted run.
    """

    def __init__(self, root: str, keep_last: Optional[int] = None,
                 keep_every: Optional[int] = None,
                 async_save: Optional[bool] = None,
                 max_retries: int = 3, retry_backoff: float = 0.05,
                 shard_bytes: Optional[int] = None):
        self.root = os.path.abspath(str(root))
        os.makedirs(self.root, exist_ok=True)
        self.keep_last = int(core.get_flag("checkpoint_keep_last", 3)
                             if keep_last is None else keep_last)
        self.keep_every = int(core.get_flag("checkpoint_keep_every", 0)
                              if keep_every is None else (keep_every or 0))
        self.async_save = bool(core.get_flag("checkpoint_async", True)
                               if async_save is None else async_save)
        self.max_retries = max(0, int(max_retries))
        self.retry_backoff = float(retry_backoff)
        self.shard_bytes = int(core.get_flag("checkpoint_shard_bytes",
                                             64 << 20)
                               if shard_bytes is None else shard_bytes)
        self._gc_stale_tmp()
        self._lock = threading.Lock()
        self._queue: "queue.Queue[Optional[_SaveJob]]" = queue.Queue(
            maxsize=1)
        self._worker: Optional[threading.Thread] = None
        self._pending: List[_SaveJob] = []
        self._error: Optional[BaseException] = None

    # -- save ---------------------------------------------------------------
    def save(self, program=None, scope=None, executor=None, optimizer=None,
             step: Optional[int] = None, cursor: Optional[Dict] = None,
             extra: Optional[Dict] = None, rng_state=None,
             sync: bool = False, reason: str = "periodic") -> int:
        """Snapshot and (a)synchronously commit one checkpoint; returns
        the checkpoint's step id.  The snapshot itself is cheap (no host
        copy on this thread); a previous async save that FAILED surfaces
        here, so durability errors are never silently dropped."""
        self._raise_pending_error()
        from .core import global_scope
        scope = scope or global_scope()
        prog = getattr(program, "_program", program) if program is not None \
            else None
        if step is None:
            step = int(getattr(executor, "_step", 0) or 0)
        names = _collect_state_names(prog, scope)
        if not names:
            raise CheckpointError(
                "checkpoint.save: nothing to save — no persistable var has "
                "a value in the scope (run the startup program first)")
        handles = _snapshot_handles(names, scope, executor)
        from .generator import rng_state_to_jsonable
        if rng_state is None:
            rng_state = np.random.get_state()
        meta = {
            "format_version": FORMAT_VERSION,
            "step": int(step),
            "wall_time": time.time(),
            "reason": reason,
            "random_seed": (prog.random_seed if prog is not None else None),
            "executor_step": (int(getattr(executor, "_step", 0))
                              if executor is not None else None),
            "numpy_rng": rng_state_to_jsonable(rng_state),
            "cursor": dict(cursor or {}),
            "extra": dict(extra or {}),
            "optimizer_state": (sorted(optimizer.state_var_names())
                                if optimizer is not None
                                and hasattr(optimizer, "state_var_names")
                                else None),
        }
        job = _SaveJob(int(step), handles, meta,
                       sync=sync or not self.async_save)
        _t0 = trace.now()
        if job.sync:
            self._run_job(job)
            # step-window stall truth for the goodput plane: a sync save
            # blocks the caller for its whole duration...
            trace.metrics().histogram("ckpt.stall_seconds").observe(
                (trace.now() - _t0) / 1e9)
            if job.error is not None:
                raise job.error
            return job.step
        self._ensure_worker()
        with self._lock:
            self._pending.append(job)
        _sp = trace.now() if trace.enabled() else 0
        self._queue.put(job)        # maxsize=1: bounds snapshot retention
        if _sp:
            # ...while an async save only stalls for the enqueue (which
            # blocks when a previous save is still writing) — this span
            # is the slice goodput charges to checkpoint_stall, and its
            # near-zero duration is the async-checkpointing win made
            # visible
            trace.complete("checkpoint::submit", _sp, cat="step",
                           args={"step": job.step})
        trace.metrics().histogram("ckpt.stall_seconds").observe(
            (trace.now() - _t0) / 1e9)
        return job.step

    def wait(self) -> None:
        """Block until every queued async save committed; re-raise the
        first failure.  Call before relying on durability (preemption
        final save, end of training)."""
        with self._lock:
            pending = list(self._pending)
        for job in pending:
            job.done.wait()
        self._raise_pending_error()

    def close(self) -> None:
        """Flush + stop the background writer (idempotent)."""
        try:
            self.wait()
        finally:
            w = self._worker
            if w is not None and w.is_alive():
                self._queue.put(None)
                w.join(timeout=30)
            self._worker = None

    def _raise_pending_error(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def _ensure_worker(self):
        w = self._worker
        if w is None or not w.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, name="ckpt-writer", daemon=True)
            self._worker.start()

    def _worker_loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            self._run_job(job)

    def _run_job(self, job: _SaveJob):
        m = trace.metrics()
        t0 = trace.now()
        try:
            with trace.span("checkpoint::save", cat="step",
                            args={"step": job.step, "sync": job.sync,
                                  "reason": job.meta.get("reason")}):
                nbytes = self._write_checkpoint(job)
            m.counter("ckpt.saves").inc()
            m.counter("ckpt.bytes").inc(nbytes)
            m.histogram("ckpt.save_seconds").observe(
                (trace.now() - t0) / 1e9)
        except BaseException as exc:    # noqa: BLE001 — stored, surfaced
            m.counter("ckpt.save_errors").inc()
            job.error = exc
            if not job.sync:
                # async failure: park it for the next save()/wait() to
                # raise.  Sync jobs raise at the call site — parking too
                # would double-raise on the NEXT save.
                with self._lock:
                    self._error = exc
        finally:
            job.done.set()
            with self._lock:
                if job in self._pending:
                    self._pending.remove(job)

    # -- the durable write --------------------------------------------------
    def _write_checkpoint(self, job: _SaveJob) -> int:
        """Materialise shards and commit atomically, retrying TRANSIENT
        IO errors with backoff (a flaky NFS mount mid-save must not kill
        the trainer); InjectedCrash and non-IO errors propagate."""
        arrays = {}
        for n, h in job.handles.items():
            pieces = getattr(h, "sharded_pieces", None)
            if pieces is None:
                sharded = _sharded_value(h)
                if sharded is not None:
                    pieces = _ShardedState(sharded)
            if pieces is not None:
                # addressable-shard IO: per-device slices, no host
                # gather — either extracted here or already persisted
                # per-shard by the donation alias guard
                arrays[n] = pieces
                trace.metrics().counter("ckpt.sharded_vars").inc()
            else:
                arrays[n] = _to_host(h)
        attempt = 0
        while True:
            try:
                return self._commit_once(job, arrays)
            except OSError:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                trace.metrics().counter("ckpt.save_retries").inc()
                time.sleep(self.retry_backoff * (2 ** (attempt - 1)))

    def _commit_once(self, job: _SaveJob, arrays: Dict[str, np.ndarray]
                     ) -> int:
        final = os.path.join(self.root, _step_dirname(job.step))
        tmp = os.path.join(self.root, f"{TMP_PREFIX}{job.step}-{os.getpid()}"
                                      f"-{threading.get_ident()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        total = 0
        try:
            shards = []
            for si, group in enumerate(self._shard_groups(arrays)):
                fname = f"shard-{si:05d}.npz"
                buf = _io.BytesIO()
                var_meta = {}
                enc = {}
                for n in group:
                    v = arrays[n]
                    if isinstance(v, _ShardedState):
                        # one npz entry per device shard; the manifest
                        # records each piece's global index so restore
                        # reassembles any slice on any mesh
                        pieces_meta = []
                        for k, (idx, piece) in enumerate(v.pieces):
                            a, true_dt = _encode_array(piece)
                            key = f"{n}@@p{k}"
                            enc[key] = a
                            pieces_meta.append(
                                {"key": key,
                                 "index": [[s, e] for s, e in idx]})
                        var_meta[n] = {"shape": list(v.shape),
                                       "dtype": true_dt,
                                       "pieces": pieces_meta}
                        continue
                    a, true_dt = _encode_array(np.asarray(v))
                    enc[n] = a
                    var_meta[n] = {"shape": list(np.shape(v)),
                                   "dtype": true_dt}
                np.savez(buf, **enc)
                data = buf.getvalue()
                atomic_write_bytes(os.path.join(tmp, fname), data)
                total += len(data)
                shards.append({"file": fname, "bytes": len(data),
                               "sha256": _sha256(data), "vars": var_meta})
            if faults.fire("crash_after_tmp_write"):
                raise InjectedCrash(
                    f"injected crash after tmp write of step {job.step}")
            manifest = dict(job.meta, shards=shards, complete=True)
            atomic_write_bytes(os.path.join(tmp, MANIFEST),
                               json.dumps(manifest, indent=1).encode())
            _fsync_dir(tmp)
            if os.path.exists(final):
                # re-save of the same step (rare; e.g. periodic + preempt
                # racing on one step id): replace wholesale.  The retired
                # dir gets a TMP_PREFIX name so a crash between the two
                # renames is recoverable — _gc_stale_tmp ADOPTS a tmp dir
                # whose manifest validates when the final name is free,
                # so the previously durable checkpoint is never lost
                old = os.path.join(
                    self.root, f"{TMP_PREFIX}old-{job.step}-{os.getpid()}"
                               f"-{threading.get_ident()}")
                os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.rename(tmp, final)
            _fsync_dir(self.root)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        # post-commit fault hooks: simulate torn/partial artifacts that
        # restore() must detect and skip
        if faults.fire("torn_manifest"):
            p = os.path.join(final, MANIFEST)
            with open(p, "r+b") as f:
                f.truncate(max(os.path.getsize(p) // 2, 1))
        if faults.fire("partial_shard"):
            p = os.path.join(final, "shard-00000.npz")
            with open(p, "r+b") as f:
                f.truncate(max(os.path.getsize(p) // 2, 1))
        self._apply_retention()
        return total

    def _shard_groups(self, arrays: Dict[str, Any]):
        """Deterministic name-ordered grouping, cut at shard_bytes.  A
        sharded var's pieces stay in one file (its total size counts)."""
        group, size = [], 0
        for n in sorted(arrays):
            v = arrays[n]
            nb = int(v.nbytes if isinstance(v, _ShardedState)
                     else np.asarray(v).nbytes)
            if group and size + nb > self.shard_bytes:
                yield group
                group, size = [], 0
            group.append(n)
            size += nb
        if group:
            yield group

    def _apply_retention(self):
        """keep-last-K ∪ keep-every-N; everything else is deleted.  Runs
        after every successful commit, best-effort."""
        steps = list_checkpoint_steps(self.root)
        if not steps:
            return
        keep = set(steps[-max(1, self.keep_last):])
        if self.keep_every > 0:
            keep.update(s for s in steps if s % self.keep_every == 0)
        for s in steps:
            if s not in keep:
                shutil.rmtree(os.path.join(self.root, _step_dirname(s)),
                              ignore_errors=True)

    def _gc_stale_tmp(self):
        """Tmp staging dirs left by a crashed writer: ADOPT one that is
        fully intact (complete manifest, every checksum valid) when its
        final name is free — that is the crash window between the two
        renames of a same-step re-save, where the retired-but-valid old
        checkpoint must not be lost — and delete the rest (a mid-write
        stage was never committed, so deleting it is always safe)."""
        try:
            entries = os.listdir(self.root)
        except OSError:
            return
        for e in entries:
            if not e.startswith(TMP_PREFIX):
                continue
            p = os.path.join(self.root, e)
            manifest = self._validate_dir(p)
            if manifest is not None and manifest.get("step") is not None:
                final = os.path.join(self.root,
                                     _step_dirname(manifest["step"]))
                if not os.path.exists(final):
                    try:
                        os.rename(p, final)
                        _fsync_dir(self.root)
                        continue
                    except OSError:
                        pass
            shutil.rmtree(p, ignore_errors=True)

    # -- restore ------------------------------------------------------------
    def validate(self, step: int) -> Optional[Dict[str, Any]]:
        """Manifest of checkpoint ``step`` iff it is fully intact
        (manifest parses, complete flag set, every shard present with a
        matching sha256); None otherwise."""
        return self._validate_dir(os.path.join(self.root,
                                               _step_dirname(step)))

    @staticmethod
    def _validate_dir(d: str) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(d, MANIFEST), "rb") as f:
                manifest = json.loads(f.read().decode())
        except (OSError, ValueError):
            return None
        if not manifest.get("complete") \
                or manifest.get("format_version") != FORMAT_VERSION:
            return None
        for sh in manifest.get("shards", []):
            p = os.path.join(d, sh.get("file", ""))
            try:
                with open(p, "rb") as f:
                    data = f.read()
            except OSError:
                return None
            if len(data) != sh.get("bytes") \
                    or _sha256(data) != sh.get("sha256"):
                return None
        return manifest

    def restore(self, program=None, scope=None, executor=None,
                strict: bool = True, step: Optional[int] = None,
                plan=None) -> Optional[CheckpointState]:
        """Load the newest intact checkpoint (or ``step``) into the scope
        and restore the determinism plane.  Returns None when the root
        holds no checkpoints at all (cold start); raises
        :class:`CorruptCheckpointError` when checkpoints exist but none
        validates.

        ``plan`` (a ``parallel.sharding.ShardingPlan``, defaulting to the
        program's own) reshards per-shard-saved vars straight onto the
        target mesh: each device materialises only its slice of the saved
        pieces (``jax.make_array_from_callback``), so a checkpoint
        written under one mesh restores under another — or, with no
        plan, reassembles to ordinary single-device arrays."""
        m = trace.metrics()
        steps = list_checkpoint_steps(self.root)
        if step is not None:
            steps = [s for s in steps if s == int(step)]
        if not steps:
            return None
        t0 = trace.now()
        chosen = manifest = None
        for s in reversed(steps):
            manifest = self.validate(s)
            if manifest is not None:
                chosen = s
                break
            m.counter("ckpt.restore_fallbacks").inc()
        if chosen is None:
            raise CorruptCheckpointError(
                f"no intact checkpoint under {self.root}: all of "
                f"{steps} failed manifest/checksum validation")
        d = os.path.join(self.root, _step_dirname(chosen))
        if plan is None and program is not None:
            plan = getattr(program, "_sharding_plan", None)
        with trace.span("checkpoint::restore", cat="step",
                        args={"step": chosen}):
            self._load_into_scope(d, manifest, program, scope,
                                  strict=strict, plan=plan)
            self._restore_determinism(manifest, program, executor)
        m.counter("ckpt.restores").inc()
        m.histogram("ckpt.restore_seconds").observe((trace.now() - t0) / 1e9)
        return CheckpointState(chosen, d, manifest)

    def _load_into_scope(self, d, manifest, program, scope, strict,
                         plan=None):
        import jax.numpy as jnp
        from .core import global_scope
        scope = scope or global_scope()
        prog = getattr(program, "_program", program) if program is not None \
            else None
        loaded: Dict[str, Dict[str, Any]] = {}
        for sh in manifest.get("shards", []):
            with np.load(os.path.join(d, sh["file"]),
                         allow_pickle=False) as data:
                for n, vm in sh.get("vars", {}).items():
                    if vm.get("pieces"):
                        scope.set_var(
                            n, self._load_sharded(n, vm, data, plan))
                    else:
                        arr = _decode_array(
                            data[n], vm.get("dtype", str(data[n].dtype)))
                        scope.set_var(n, jnp.asarray(arr))
                    loaded[n] = vm
        if strict and prog is not None:
            wanted = {v.name: v for v in prog.global_block().vars.values()
                      if v.persistable}
            missing = sorted(set(wanted) - set(loaded))
            mismatched = []
            for n, v in wanted.items():
                vm = loaded.get(n)
                if vm is None:
                    continue
                shp = list(v.shape or [])
                if shp and all(int(x) >= 0 for x in shp) \
                        and vm.get("shape") is not None \
                        and list(vm["shape"]) != shp:
                    mismatched.append(
                        f"{n}: checkpoint shape {vm['shape']} != program "
                        f"shape {shp}")
                try:
                    if v.dtype is not None and vm.get("dtype") and \
                            np.dtype(_DTYPE_ENCODE.get(vm["dtype"])
                                     or vm["dtype"]).name \
                            != _np_dtype_name(v.dtype):
                        mismatched.append(
                            f"{n}: checkpoint dtype {vm['dtype']} != "
                            f"program dtype {v.dtype}")
                except TypeError:
                    pass
            opt_names = manifest.get("optimizer_state")
            if opt_names:
                missing += sorted(n for n in opt_names
                                  if n not in loaded and n not in missing
                                  and n in wanted)
            if missing or mismatched:
                raise CheckpointError(
                    "checkpoint restore (strict): state does not cover the "
                    "program.  Missing vars: "
                    + (", ".join(missing) or "none")
                    + ".  Mismatches: " + ("; ".join(mismatched) or "none")
                    + ".  Pass strict=False to load best-effort")

    @staticmethod
    def _load_sharded(n, vm, data, plan):
        """One per-shard-saved var -> a scope value.  With a plan, each
        target-mesh device pulls exactly its slice out of the saved
        pieces (resharded restore: the piece layout and the target
        sharding need not match); without one, the pieces reassemble to
        a plain array."""
        import jax
        import jax.numpy as jnp
        shape = tuple(int(x) for x in vm["shape"])
        true_dt = vm.get("dtype", "float32")
        # assemble in the ENCODED dtype (bf16 rides as its uint16 view,
        # manifest-recorded) and view back after — bit-exact
        pieces = [
            (tuple((int(s), int(e)) for s, e in p["index"]),
             (lambda key=p["key"]: data[key]))
            for p in vm["pieces"]]
        np_dt = np.dtype(_DTYPE_ENCODE.get(true_dt) or true_dt)

        def _block(index):
            return _decode_array(
                _assemble_slice(index, shape, np_dt, pieces), true_dt)

        if plan is not None:
            sharding = plan.sharding_for(n, shape)
            return jax.make_array_from_callback(shape, sharding, _block)
        return jnp.asarray(_block(tuple(slice(0, d) for d in shape)))

    @staticmethod
    def _restore_determinism(manifest, program, executor):
        """RNG + counters: program.random_seed, the executor step counter
        the per-step PRNG fold_in consumes, and the host numpy stream
        (loader shuffles, dygraph seeds)."""
        from .generator import rng_state_from_jsonable
        prog = getattr(program, "_program", program) if program is not None \
            else None
        if prog is not None and manifest.get("random_seed") is not None:
            prog.random_seed = manifest["random_seed"]
        if executor is not None and manifest.get("executor_step") is not None:
            executor._step = int(manifest["executor_step"])
        st = manifest.get("numpy_rng")
        if st is not None:
            try:
                np.random.set_state(rng_state_from_jsonable(st))
            except (ValueError, KeyError, TypeError):
                pass            # foreign bit-generator: leave stream as-is


def _np_dtype_name(dt) -> str:
    """Program var dtype (string or np dtype) -> canonical numpy name;
    bf16 stays 'bfloat16' (not an np builtin)."""
    s = str(dt)
    if s in _DTYPE_ENCODE:
        return np.dtype(_DTYPE_ENCODE[s]).name
    try:
        return np.dtype(s).name
    except TypeError:
        return s
