"""paddle_tpu.fluid.autotune — the profile-guided self-tuning runtime.

The reference fork ships ~50 runtime gflags plus BuildStrategy /
ExecutionStrategy knobs and leaves their values to operator folklore;
this repro grew an even larger surface (bucket edges, inflight depth,
``steps_per_dispatch``, allreduce bucket size, serving ``max_batch`` /
``max_wait``, ``FLAGS_pallas_min_seq``) while PRs 1/2/9/16 built exactly
the measurement plane needed to set them automatically.  This module
closes that loop (ROADMAP item 4):

* **propose** — candidate configs over a declared :class:`KnobSpace`
  (deterministic given a seed: a seeded run replays the same search).
* **price** — each candidate is costed FOR FREE via the AOT
  ``device_stats`` analysis (the SNIPPETS pjit idiom:
  ``lower().compile()`` then ``cost_analysis``/``memory_analysis``
  without ever executing a step).  Candidates whose predicted
  per-device peak exceeds the HBM budget are rejected outright —
  ``memory_analysis`` says OOM before the device does — and survivors
  are ranked by a FLOPs/HBM-bytes roofline model so the cheapest-looking
  configs probe first.
* **probe** — survivors run short flight-recorder-instrumented windows
  (``FLAGS_auto_tune_probe_steps`` real steps under an
  ``autotune::probe`` span) scored by the recorder's step durations and
  the goodput ratio; the serving tuner scores the live window-p99 the
  SLO watchdog computes.
* **commit / revert** — the winner is applied (program hints + flags,
  or live engine knobs); a serving candidate whose probe window
  breached the p99 SLO is ALWAYS reverted, never committed.

Winning configs persist in the PR-2 persistent cache keyed by
``(program fingerprint, jax version, backend, device count)`` so a
restarted process starts tuned with ZERO probe cost, and every decision
is observable: ``autotune.probes/accepts/rejects/reverts`` instruments,
``autotune.speedup`` gauge, decisions in ``/stats`` and in watchdog
diagnostic bundles.  See docs/performance.md "Auto-tuning".

Two surfaces:

* training — ``BuildStrategy.auto_tune = True`` (or ``FLAGS_auto_tune``)
  tunes a program ONCE per fingerprint on its first ``Executor.run``:
  bucket edges, ``steps_per_dispatch``, inflight depth, and (for
  kernel-tier programs) the ``FLAGS_pallas_min_seq`` flash-attention
  crossover.
* serving — ``ServingEngine(auto_tune=True)`` (or the flag, reconciled
  by :func:`apply_flags` exactly like the PR-9 metrics-export pattern)
  hill-climbs ``max_batch``/``max_wait_us`` online against the live
  windowed p99.

Everything here degrades, never raises into the training loop or the
batcher: a failed price, probe, or store read falls back to the
untuned defaults and counts itself.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence

from . import core, trace, compile_cache, flight_recorder

SCHEMA = 1                       # persisted-config schema; bump = stale
MAX_DECISIONS = 256              # bounded in-process decision log
DEFAULT_PROBE_STEPS = 8
DEFAULT_INTERVAL_S = 2.0         # serving tuner tick period
MIN_TRAIN_GAIN = 1.02            # commit a non-baseline only if >=2% faster
MIN_SERVE_GAIN = 1.02            # commit only if >=2% more throughput
SERVE_P99_GUARD = 1.25           # no-SLO fallback: p99 may grow <=25%

# roofline constants for the pricing model (ranking only — relative
# order is what matters, so one generic accelerator profile is enough)
_PEAK_FLOPS = 100e12
_PEAK_BYTES = 1e12

__all__ = [
    "Knob", "KnobSpace", "training_space", "serving_space", "candidates",
    "config_key", "save_config", "load_config",
    "maybe_tune_executor", "ServingAutoTuner", "attach_engine",
    "register_engine", "apply_flags", "enabled",
    "decisions", "state", "bench_block", "hbm_budget_bytes",
    "reset_for_tests",
]

_lock = threading.Lock()
_decisions: List[Dict[str, Any]] = []
_tuned: set = set()              # (fingerprint, fetch_names) memo
_engines: "weakref.WeakSet" = weakref.WeakSet()


def enabled() -> bool:
    return bool(core.get_flag("auto_tune"))


def probe_steps() -> int:
    return int(core.get_flag("auto_tune_probe_steps",
                             DEFAULT_PROBE_STEPS) or DEFAULT_PROBE_STEPS)


# ---------------------------------------------------------------------------
# knob space
# ---------------------------------------------------------------------------

class Knob:
    """One tunable: a name, where it lives (``kind``), and the candidate
    values the search may propose.  Kinds:

    * ``"flag"``   — a ``FLAGS_*`` value applied via :func:`core.set_flags`
    * ``"hint"``   — a ``program._hints`` entry (per-program)
    * ``"engine"`` — a live :class:`ServingEngine` attribute
    """

    def __init__(self, name: str, values: Sequence, kind: str = "flag"):
        if kind not in ("flag", "hint", "engine"):
            raise ValueError(f"unknown knob kind {kind!r}")
        self.name = name
        self.kind = kind
        # dedup preserving order; the FIRST value is the baseline
        seen, vals = set(), []
        for v in values:
            k = repr(v)
            if k not in seen:
                seen.add(k)
                vals.append(v)
        self.values = vals

    def current(self, program=None, engine=None):
        if self.kind == "hint":
            return (program._hints.get(self.name)
                    if program is not None else None)
        if self.kind == "engine":
            return getattr(engine, self.name, None) \
                if engine is not None else None
        return core.get_flag(self.name)

    def apply(self, value, program=None, engine=None) -> None:
        if self.kind == "hint":
            if program is None:
                return
            if value is None:
                program._hints.pop(self.name, None)
            else:
                program._hints[self.name] = value
        elif self.kind == "engine":
            if engine is not None:
                setattr(engine, self.name, value)
        else:
            # plain flag write — NOT core.set_flags: the reconciliation
            # dispatch there may restart surfaces, which a probe loop
            # must never do
            core._FLAGS[self.name] = value

    def __repr__(self):
        return f"Knob({self.name}, {self.kind}, {self.values})"


class KnobSpace:
    """An ordered set of :class:`Knob`\\ s.  ``candidates()`` is the
    deterministic proposal stream: the full cartesian product when it is
    small, otherwise a seeded sample — either way the baseline (every
    knob at its first value) is candidate 0 and the same seed replays
    the same sequence."""

    def __init__(self, knobs: Sequence[Knob]):
        self.knobs = [k for k in knobs if k.values]

    def baseline(self) -> Dict[str, Any]:
        return {k.name: k.values[0] for k in self.knobs}

    def candidates(self, seed: int = 0,
                   limit: Optional[int] = None) -> List[Dict[str, Any]]:
        if not self.knobs:
            return []
        prod = 1
        for k in self.knobs:
            prod *= len(k.values)
        cap = int(limit or core.get_flag("auto_tune_max_candidates", 16)
                  or 16)
        names = [k.name for k in self.knobs]
        if prod <= cap:
            out = [dict(zip(names, vals)) for vals in
                   itertools.product(*(k.values for k in self.knobs))]
        else:
            rng = random.Random(int(seed))
            seen = {repr(sorted(self.baseline().items()))}
            out = [self.baseline()]
            while len(out) < cap:
                cand = {k.name: rng.choice(k.values) for k in self.knobs}
                key = repr(sorted(cand.items()))
                if key not in seen:
                    seen.add(key)
                    out.append(cand)
        base = self.baseline()
        out.sort(key=lambda c: (c != base,
                                repr(sorted(c.items()))))
        return out[:cap]

    def apply(self, config: Dict[str, Any], program=None,
              engine=None) -> None:
        for k in self.knobs:
            if k.name in config:
                k.apply(config[k.name], program=program, engine=engine)

    def snapshot(self, program=None, engine=None) -> Dict[str, Any]:
        return {k.name: k.current(program=program, engine=engine)
                for k in self.knobs}


def candidates(space: KnobSpace, seed: int = 0,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
    return space.candidates(seed=seed, limit=limit)


def training_space(program=None, feed=None) -> KnobSpace:
    """The executor-side knob space for one program: bucket edges (when
    bucketing is active), ``steps_per_dispatch`` + inflight depth (the
    async-pipeline pair, probed through ``run_async``), and — for
    programs the kernel tier rewrote — the ``FLAGS_pallas_min_seq``
    flash-attention crossover, the sweep the round-3 BERT measurements
    asked a future auto-tuner to own."""
    knobs: List[Knob] = []
    hints = getattr(program, "_hints", {}) if program is not None else {}
    want_bucketing = hints.get("shape_bucketing")
    if want_bucketing is None:
        want_bucketing = core.get_flag("shape_bucketing")
    n = 0
    if feed:
        try:
            import numpy as np
            dims = {np.shape(v)[0] for v in feed.values()
                    if np.ndim(v) >= 1}
            n = int(next(iter(dims))) if len(dims) == 1 else 0
        except Exception:               # noqa: BLE001
            n = 0
    if want_bucketing and n:
        cur = compile_cache.normalize_edges(
            hints.get("bucket_edges")
            or core.get_flag("shape_bucket_edges"))
        vals: List[Any] = [cur]
        vals.append(compile_cache.pow2_edges(max(n, 2)))
        # exact-fit single edge: zero padding waste for a stable loader
        vals.append((compile_cache.bucket_for(
            n, compile_cache.pow2_edges(max(n, 2))),))
        if cur:
            # coarser variant: half the edges -> fewer executables
            vals.append(tuple(cur[1::2]) or cur)
        knobs.append(Knob("bucket_edges",
                          [compile_cache.normalize_edges(v) for v in vals],
                          kind="hint"))
    cur_k = int(hints.get("steps_per_dispatch") or 1)
    knobs.append(Knob("steps_per_dispatch",
                      [cur_k] + [k for k in (1, 2, 4) if k != cur_k],
                      kind="hint"))
    cur_in = int(core.get_flag("max_inflight_steps", 2) or 2)
    knobs.append(Knob("max_inflight_steps",
                      [cur_in] + [d for d in (1, 2, 4) if d != cur_in]))
    if program is not None and _has_fused_attention(program):
        cur_seq = int(core.get_flag("pallas_min_seq", 1024) or 1024)
        knobs.append(Knob("pallas_min_seq",
                          [cur_seq] + [s for s in (512, 1024, 2048)
                                       if s != cur_seq]))
    if program is not None and (
            getattr(program, "_sharding_plan", None) is not None
            or hints.get("sharding")):
        # gradient-coalescing bucket width only matters once a sharding
        # plan makes the all-reduce ring real — without one the knob is
        # dead weight in the cartesian product
        cur_fg = int(hints.get("fuse_grad_size_in_num") or 32)
        knobs.append(Knob("fuse_grad_size_in_num",
                          [cur_fg] + [v for v in (8, 32, 128)
                                      if v != cur_fg],
                          kind="hint"))
    return KnobSpace(knobs)


def _has_fused_attention(program) -> bool:
    try:
        return any(op.type == "fused_multihead_attention"
                   for b in program.blocks for op in b.ops)
    except Exception:                   # noqa: BLE001
        return False


def serving_space(engine) -> KnobSpace:
    """The live serving pair: ``max_batch`` (clamped to the engine's
    largest declared bucket) and ``max_wait_us``."""
    mb = int(engine.max_batch)
    cap = int(engine.bucket_edges[-1]) if engine.bucket_edges else mb * 4
    mb_vals = [mb] + [v for v in (mb * 2, max(1, mb // 2))
                      if 1 <= v <= cap and v != mb]
    mw = int(engine.max_wait_us)
    mw_vals = [mw] + [v for v in (mw * 2, max(200, mw // 2))
                      if v != mw and 200 <= v <= 100_000]
    return KnobSpace([Knob("max_batch", mb_vals, kind="engine"),
                      Knob("max_wait_us", mw_vals, kind="engine")])


# ---------------------------------------------------------------------------
# persisted-config store (the PR-2 PersistentCache, new key namespace)
# ---------------------------------------------------------------------------

def config_key(fingerprint: str, surface: str = "train") -> str:
    """Stable store key: program fingerprint + jax version + backend +
    device count + surface.  A different backend, device topology, or
    schema never reuses a tuned config that was measured elsewhere."""
    import jax
    raw = "|".join(["autotune", str(SCHEMA), str(fingerprint),
                    jax.__version__, jax.default_backend(),
                    str(jax.device_count()), surface])
    return "at-" + hashlib.sha256(raw.encode()).hexdigest()


def save_config(fingerprint: str, config: Dict[str, Any],
                surface: str = "train",
                extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Persist a winning config; returns the store key (None when no
    store is configured).  Never raises — persistence is an optimisation,
    not a correctness dependency."""
    store = compile_cache.config_store()
    if store is None:
        return None
    import jax
    key = config_key(fingerprint, surface)
    meta = {"schema": SCHEMA, "fingerprint": str(fingerprint),
            "backend": jax.default_backend(),
            "n_devices": jax.device_count(),
            "surface": surface, "config": dict(config),
            "ts": time.time()}
    if extra:
        meta.update(extra)
    try:
        store.record(key, meta)
    except Exception:                   # noqa: BLE001
        trace.metrics().counter("autotune.store_errors").inc()
        return None
    return key


def load_config(fingerprint: str,
                surface: str = "train") -> Optional[Dict[str, Any]]:
    """Load + validate a persisted config.  A corrupt, stale-schema, or
    mismatched entry (fingerprint/backend/device count) returns None —
    the executor falls back to untuned defaults, never crashes."""
    store = compile_cache.config_store()
    if store is None:
        return None
    import jax
    meta = store.get(config_key(fingerprint, surface))
    if meta is None:
        return None
    try:
        ok = (int(meta.get("schema", -1)) == SCHEMA
              and meta.get("fingerprint") == str(fingerprint)
              and meta.get("backend") == jax.default_backend()
              and int(meta.get("n_devices", -1)) == jax.device_count()
              and meta.get("surface") == surface
              and isinstance(meta.get("config"), dict))
    except Exception:                   # noqa: BLE001
        ok = False
    if not ok:
        trace.metrics().counter("autotune.stale_configs").inc()
        return None
    return meta


# ---------------------------------------------------------------------------
# decision log + observability
# ---------------------------------------------------------------------------

def _record_decision(d: Dict[str, Any]) -> Dict[str, Any]:
    d = dict(d)
    d.setdefault("ts", time.time())
    with _lock:
        _decisions.append(d)
        del _decisions[:-MAX_DECISIONS]
    if trace.enabled():
        trace.instant("autotune_decision", cat="autotune",
                      args={k: d.get(k) for k in
                            ("surface", "action", "reason", "config",
                             "speedup", "source")})
    return d


def decisions(n: Optional[int] = None) -> List[Dict[str, Any]]:
    with _lock:
        out = list(_decisions)
    return out[-int(n):] if n else out


def state() -> Dict[str, Any]:
    """Compact tuner state for ``/stats`` and diagnostic bundles:
    instrument totals plus the last few decisions."""
    out = {
        "enabled": enabled(),
        "probes": trace.counter_value("autotune.probes"),
        "accepts": trace.counter_value("autotune.accepts"),
        "rejects": trace.counter_value("autotune.rejects"),
        "reverts": trace.counter_value("autotune.reverts"),
        "warm_starts": trace.counter_value("autotune.warm_starts"),
        "speedup": round(trace.gauge_value("autotune.speedup"), 4),
    }
    last = decisions(3)
    if last:
        out["last_decisions"] = [
            {k: d.get(k) for k in ("surface", "action", "reason",
                                   "config", "speedup", "source",
                                   "probe_steps", "mesh")}
            for d in last]
    return out


def bench_block() -> Dict[str, Any]:
    """The ``autotune`` block every bench leg reports: the chosen
    config, what the search cost in probe steps, and the tuned-vs-
    untuned delta.  ``{"enabled": False}`` when the tuner never ran in
    this process — the block is always present so BENCH rounds carry
    the evidence either way."""
    commits = [d for d in decisions()
               if d.get("action") == "accept"]
    if not commits:
        return {"enabled": enabled(), "decisions": len(decisions())}
    last = commits[-1]
    probes = sum(int(d.get("probe_steps") or 0) for d in decisions())
    return {
        "enabled": True,
        "surface": last.get("surface"),
        "chosen": last.get("config"),
        "source": last.get("source", "probe"),
        "probe_cost_steps": probes,
        "speedup": round(float(last.get("speedup") or 1.0), 4),
        "decisions": len(decisions()),
    }


def hbm_budget_bytes() -> Optional[int]:
    """Per-device memory budget the OOM filter prices against:
    ``FLAGS_auto_tune_hbm_budget_mb`` when set (tests pin it), else the
    backend's reported ``bytes_limit``, else None (no rejection)."""
    mb = float(core.get_flag("auto_tune_hbm_budget_mb", 0) or 0)
    if mb > 0:
        # float-valued: a test can pin a sub-MB budget to discriminate
        # between demo-scale candidates deterministically
        return int(mb * (1 << 20))
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit") or 0)
        return limit or None
    except Exception:                   # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# training surface — tune once per (fingerprint, fetch set) on first run
# ---------------------------------------------------------------------------

def maybe_tune_executor(exe, program, feed, fetch_names, scope) -> None:
    """Called by ``Executor.run`` when a program opted in
    (``BuildStrategy.auto_tune`` hint or ``FLAGS_auto_tune``).  Tunes at
    most once per (fingerprint, fetch set): a persisted winner applies
    with ZERO probe windows; otherwise the propose→price→probe→commit
    search runs here, re-entering ``run``/``run_async`` under the
    ``_in_autotune`` guard.  Never raises into the training loop."""
    try:
        from .executor import _fingerprint
        fp = _fingerprint(program)
        memo = (fp, tuple(fetch_names))
        with _lock:
            if memo in _tuned:
                return
            _tuned.add(memo)            # claim even on failure: a broken
            # search must not retry (and re-pay probes) on every step
        persisted = load_config(fp, "train")
        space = training_space(program, feed)
        if persisted is not None:
            space.apply(persisted["config"], program=program)
            trace.metrics().counter("autotune.warm_starts").inc()
            _record_decision({
                "surface": "train", "action": "accept",
                "source": "persisted", "fingerprint": fp[:12],
                "config": persisted["config"], "probe_steps": 0,
                "speedup": persisted.get("speedup")})
            return
        _tune_training(exe, program, feed, fetch_names, scope, fp, space)
    except Exception as e:              # noqa: BLE001 — degrade, count
        trace.metrics().counter("autotune.errors").inc()
        import sys
        print(f"paddle_tpu.autotune: WARNING: tuning skipped: "
              f"{type(e).__name__}: {e} — running untuned",
              file=sys.stderr)


def _price_training(exe, program, feed, fetch_names, scope, space, cands):
    """AOT-price every candidate WITHOUT executing: apply, lower+compile,
    read ``memory_analysis``/``cost_analysis`` (Executor.analyze), and
    restore.  Returns ``[(config, info|None, est_seconds|None)]`` with
    OOM candidates dropped (counted + logged, ``executed: False``).
    Prices are memoized on the compile-relevant knob values — candidates
    that only differ in dispatch knobs share one analysis."""
    budget = hbm_budget_bytes()
    memo: Dict[str, Any] = {}
    orig = space.snapshot(program=program)
    priced = []
    try:
        for cand in cands:
            sig = repr(sorted((k, v) for k, v in cand.items()
                              if k in ("bucket_edges", "pallas_min_seq")))
            if sig not in memo:
                space.apply(cand, program=program)
                memo[sig] = exe.analyze(program, feed=feed,
                                        fetch_list=list(fetch_names),
                                        scope=scope)
            info = memo[sig]
            peak = int(info.get("per_device_peak_bytes") or 0) \
                if info else 0
            if budget and info and peak > budget:
                trace.metrics().counter("autotune.rejects").inc()
                _record_decision({
                    "surface": "train", "action": "reject",
                    "reason": "oom_predicted", "config": cand,
                    "executed": False, "probe_steps": 0,
                    "peak_bytes": peak, "budget_bytes": budget})
                continue
            est = None
            if info:
                est = max(float(info.get("flops") or 0) / _PEAK_FLOPS,
                          float(info.get("bytes_accessed") or 0)
                          / _PEAK_BYTES)
            priced.append((cand, info, est))
    finally:
        space.apply(orig, program=program)
    # cheapest predicted cost probes first; un-analysable candidates last
    priced.sort(key=lambda t: (t[2] is None, t[2] or 0.0))
    return priced


def _probe_training(exe, program, feed, fetch_names, scope, space,
                    cand) -> Optional[float]:
    """One probe window: apply the candidate and run
    ``FLAGS_auto_tune_probe_steps`` REAL steps through the async runner
    (which exercises ``steps_per_dispatch``/inflight exactly as a tuned
    run would), under an ``autotune::probe`` span.  Scored by the flight
    recorder's step durations (median ``dur_us``) with wall clock as the
    fallback.  Returns per-step seconds, or None when the window failed
    (the candidate is rejected, the loop continues)."""
    steps = max(1, probe_steps())
    space.apply(cand, program=program)
    rec = flight_recorder.recorder()
    mark = rec.total
    try:
        with trace.span("autotune::probe", cat="autotune",
                        args={"surface": "train", "config": repr(cand),
                              "steps": steps}):
            t0 = time.perf_counter()
            k = int(cand.get("steps_per_dispatch") or 1)
            depth = int(cand.get("max_inflight_steps") or 1)
            if k > 1 or depth > 1:
                for _ in range(steps):
                    exe.run_async(program, feed=feed,
                                  fetch_list=list(fetch_names),
                                  scope=scope, max_inflight=depth,
                                  steps_per_dispatch=k)
                exe.drain_async()
            else:
                for _ in range(steps):
                    exe.run(program, feed=feed,
                            fetch_list=list(fetch_names), scope=scope,
                            return_numpy=False)
            wall = time.perf_counter() - t0
    except Exception:                   # noqa: BLE001 — a candidate that
        # cannot execute is a rejection, not a crash
        trace.metrics().counter("autotune.rejects").inc()
        _record_decision({"surface": "train", "action": "reject",
                          "reason": "probe_error", "config": cand,
                          "probe_steps": steps})
        return None
    trace.metrics().counter("autotune.probes").inc()
    # recorder truth: median in-executor step time of this window (the
    # first step of a window carries the candidate's compile; median is
    # robust to it, wall/steps is not)
    durs = sorted(e["dur_us"] for e in rec.snapshot(rec.total - mark)
                  if e.get("kind") == "step" and e.get("dur_us"))
    if durs:
        return durs[len(durs) // 2] / 1e6
    return wall / steps


def _tune_training(exe, program, feed, fetch_names, scope, fp,
                   space) -> None:
    cands = space.candidates(
        seed=int(getattr(program, "random_seed", 0) or 0))
    if len(cands) < 2:
        return
    gp0 = trace.elapsed_us()
    priced = _price_training(exe, program, feed, fetch_names, scope,
                             space, cands)
    if not priced:
        return                          # everything predicted OOM: keep
        # the baseline the user configured — it is their explicit choice
    baseline = space.baseline()
    exe._in_autotune = True
    scores: List[Dict[str, Any]] = []
    try:
        for cand, info, est in priced:
            s = _probe_training(exe, program, feed, fetch_names, scope,
                                space, cand)
            if s is not None:
                scores.append({"config": cand, "step_seconds": s,
                               "est_seconds": est,
                               "analysis": {k: info.get(k) for k in
                                            ("flops", "bytes_accessed",
                                             "per_device_peak_bytes")}
                               if info else None})
    finally:
        exe._in_autotune = False
    if not scores:
        space.apply(baseline, program=program)
        return
    base_s = next((s["step_seconds"] for s in scores
                   if s["config"] == baseline), None)
    best = min(scores, key=lambda s: s["step_seconds"])
    # commit guard: the tuned loop must never end below the untuned
    # baseline — a non-baseline winner needs a real margin, anything
    # less keeps the measured status quo
    if (base_s is not None and best["config"] != baseline
            and base_s / best["step_seconds"] < MIN_TRAIN_GAIN):
        best = next(s for s in scores if s["config"] == baseline)
    space.apply(best["config"], program=program)
    speedup = (base_s / best["step_seconds"]
               if base_s else 1.0)
    trace.metrics().counter("autotune.accepts").inc()
    trace.metrics().gauge("autotune.speedup").set(round(speedup, 4))
    gp_ratio = None
    try:
        from . import goodput
        rep = goodput.snapshot(t0_us=gp0) if gp0 is not None else None
        gp_ratio = rep.get("ratio") if rep else None
    except Exception:                   # noqa: BLE001
        pass
    d = _record_decision({
        "surface": "train", "action": "accept", "source": "probe",
        "fingerprint": fp[:12], "config": best["config"],
        "baseline": baseline,
        "baseline_step_seconds": base_s,
        "step_seconds": best["step_seconds"],
        "speedup": round(speedup, 4),
        "probe_steps": probe_steps() * len(scores),
        "candidates": [{"config": s["config"],
                        "step_seconds": round(s["step_seconds"], 6)}
                       for s in scores],
        "goodput_ratio": gp_ratio})
    save_config(fp, best["config"], "train",
                extra={"speedup": d["speedup"],
                       "probe_steps": d["probe_steps"]})


# ---------------------------------------------------------------------------
# serving surface — online hill climbing against the live window p99
# ---------------------------------------------------------------------------

class ServingAutoTuner:
    """Online tuner for one :class:`ServingEngine`: every tick it either
    (a) observes the current committed config's window, proposes a
    neighbour of ``(max_batch, max_wait_us)`` and applies it, or (b)
    judges the pending candidate's probe window and commits or reverts.
    The windowed stats come from the flight recorder's request records
    (completions + p99 latency); the SLO guard reverts ANY candidate
    whose probe window breached p99 — a breaching config is never
    committed.  ``tick()`` is public so tests (and the fleet drill)
    drive the state machine deterministically; ``start()`` wraps it in
    an interval thread for production."""

    def __init__(self, engine, slo_ms: Optional[float] = None,
                 interval_s: Optional[float] = None, seed: int = 0,
                 flag_started: bool = False, persist: bool = True):
        self.engine = engine
        self._slo_ms = slo_ms
        self.interval_s = float(interval_s or DEFAULT_INTERVAL_S)
        self.seed = int(seed)
        self.flag_started = bool(flag_started)
        self.persist = bool(persist)
        self._rng = random.Random(self.seed)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._pending: Optional[Dict[str, Any]] = None
        self._cursor = 0
        self._baseline_window: Optional[Dict[str, Any]] = None
        self._fp = _engine_fingerprint(engine)
        self.committed = {"max_batch": int(engine.max_batch),
                          "max_wait_us": int(engine.max_wait_us)}
        self.warm_started = False
        if self.persist:
            self._warm_start()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingAutoTuner":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="autotune-serving", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
        self._stop.set()
        if t is not None:
            t.join(timeout=5.0)

    def running(self) -> bool:
        return self._thread is not None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:           # noqa: BLE001 — the batcher must
                trace.metrics().counter("autotune.errors").inc()

    # -- signals -------------------------------------------------------------
    def slo_ms(self) -> float:
        if self._slo_ms is not None:
            return float(self._slo_ms)
        return float(core.get_flag("watchdog_p99_ms", 0) or 0)

    def _window(self) -> Dict[str, Any]:
        """Stats since the last cursor: completed requests + windowed
        p99 from the flight recorder's request records, falling back to
        the watchdog's live ``window_p99_ms`` gauge when the ring holds
        no requests (recorder disabled)."""
        rec = flight_recorder.recorder()
        total = rec.total
        new = rec.snapshot(max(0, total - self._cursor)) \
            if total > self._cursor else []
        self._cursor = total
        lats = sorted(e["latency_us"] for e in new
                      if e.get("kind") == "request"
                      and e.get("outcome") == "ok"
                      and e.get("latency_us"))
        if lats:
            p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] / 1e3
            return {"completed": len(lats), "p99_ms": round(p99, 3)}
        wd_p99 = trace.gauge_value("watchdog.window_p99_ms")
        done = self.engine._ins.hist_stats("latency_seconds").get(
            "count", 0)
        prev = getattr(self, "_done_prev", 0)
        self._done_prev = done
        return {"completed": max(0, done - prev),
                "p99_ms": round(wd_p99, 3)}

    # -- the state machine ---------------------------------------------------
    def _neighbours(self) -> List[Dict[str, Any]]:
        space = serving_space(self.engine)
        base = {"max_batch": int(self.engine.max_batch),
                "max_wait_us": int(self.engine.max_wait_us)}
        out = []
        for k in space.knobs:
            for v in k.values:
                cand = dict(base)
                if cand.get(k.name) != v:
                    cand[k.name] = v
                    out.append(cand)
        # deterministic given the seed: the decision log replays
        out.sort(key=lambda c: repr(sorted(c.items())))
        self._rng.shuffle(out)
        return out

    def _apply(self, cfg: Dict[str, Any]) -> None:
        eng = self.engine
        eng.max_batch = int(cfg["max_batch"])
        eng.max_wait_us = int(cfg["max_wait_us"])

    def tick(self) -> Optional[Dict[str, Any]]:
        """One transition.  Returns the decision recorded this tick (a
        judge tick), or None (an observe/propose tick)."""
        eng = self.engine
        if getattr(eng, "_closed", False) or eng.paused():
            return None
        if self._pending is None:
            self._baseline_window = self._window()
            neigh = self._neighbours()
            if not neigh:
                return None
            cand = neigh[0]
            self._apply(cand)
            self._pending = {"config": cand, "t0_ns": trace.now()}
            trace.metrics().counter("autotune.probes").inc()
            return None
        pend, self._pending = self._pending, None
        win = self._window()
        base = self._baseline_window or {"completed": 0, "p99_ms": 0.0}
        slo = self.slo_ms()
        breached = bool(slo and win["p99_ms"] > slo)
        trace.complete("autotune::probe", pend["t0_ns"], cat="autotune",
                       args={"surface": "serving",
                             "engine": eng.name,
                             "config": repr(pend["config"]),
                             "completed": win["completed"],
                             "p99_ms": win["p99_ms"],
                             "breached": breached})
        better = (not breached
                  and win["completed"] > 0
                  and win["completed"]
                  >= base.get("completed", 0) * MIN_SERVE_GAIN
                  and (slo or base.get("p99_ms", 0) <= 0
                       or win["p99_ms"]
                       <= base["p99_ms"] * SERVE_P99_GUARD))
        if breached or not better:
            # the guard: a probe window that breached the SLO (or just
            # failed to win) is rolled back — the engine never keeps a
            # config it could not defend in its own window
            self._apply(self.committed)
            name = "reverts" if breached else "rejects"
            trace.metrics().counter(f"autotune.{name}").inc()
            return _record_decision({
                "surface": "serving", "engine": eng.name,
                "action": "revert" if breached else "reject",
                "reason": "slo_breach" if breached else "no_gain",
                "config": pend["config"], "window": win,
                "baseline_window": base, "slo_ms": slo,
                "mesh": _engine_mesh(eng)})
        self.committed = dict(pend["config"])
        speedup = (win["completed"] / base["completed"]
                   if base.get("completed") else 1.0)
        trace.metrics().counter("autotune.accepts").inc()
        trace.metrics().gauge("autotune.speedup").set(round(speedup, 4))
        d = _record_decision({
            "surface": "serving", "engine": eng.name,
            "action": "accept", "source": "probe",
            "config": dict(self.committed), "window": win,
            "baseline_window": base, "slo_ms": slo,
            "speedup": round(speedup, 4),
            "mesh": _engine_mesh(eng)})
        if self.persist and self._fp:
            save_config(self._fp, self.committed, "serving",
                        extra={"speedup": d["speedup"]})
        return d

    # -- persistence ---------------------------------------------------------
    def _warm_start(self) -> None:
        if not self._fp:
            return
        meta = load_config(self._fp, "serving")
        if meta is None:
            return
        cfg = meta["config"]
        try:
            self._apply({"max_batch": int(cfg["max_batch"]),
                         "max_wait_us": int(cfg["max_wait_us"])})
        except Exception:               # noqa: BLE001 — stale shape
            trace.metrics().counter("autotune.stale_configs").inc()
            return
        self.committed = dict(cfg)
        self.warm_started = True
        trace.metrics().counter("autotune.warm_starts").inc()
        _record_decision({"surface": "serving", "engine": self.engine.name,
                          "action": "accept", "source": "persisted",
                          "config": dict(cfg), "probe_steps": 0,
                          "speedup": meta.get("speedup"),
                          "mesh": _engine_mesh(self.engine)})

    def state(self) -> Dict[str, Any]:
        return {"running": self.running(),
                "flag_started": self.flag_started,
                "committed": dict(self.committed),
                "pending": dict(self._pending["config"])
                if self._pending else None,
                "warm_started": self.warm_started,
                "slo_ms": self.slo_ms()}


def _engine_mesh(engine) -> Optional[str]:
    """The replica's mesh shape (``"tp:4"``-style) when its frozen
    program carries a sharding plan — lets fleet rollups attribute
    tuner decisions per topology instead of flattening 1-chip and
    8-chip replicas into one bucket."""
    try:
        plan = getattr(getattr(getattr(engine, "_backend", None),
                               "program", None), "_sharding_plan", None)
        if plan is None:
            return None
        shape = plan.describe().get("mesh_shape")
        if isinstance(shape, dict):
            return ",".join(f"{k}:{v}" for k, v in sorted(shape.items()))
        return str(shape) if shape else None
    except Exception:                   # noqa: BLE001
        return None


def _engine_fingerprint(engine) -> Optional[str]:
    """Program identity for the serving store: the executor fingerprint
    of the frozen program when the engine runs one, else a hash of the
    AOT artifact's IO signature."""
    try:
        prog = getattr(engine._backend, "program", None)
        if prog is not None and hasattr(prog, "blocks"):
            from .executor import _fingerprint
            return _fingerprint(prog)
        raw = repr((sorted(engine.feed_names), sorted(engine.fetch_names),
                    tuple(engine.bucket_edges or ())))
        return hashlib.sha1(raw.encode()).hexdigest()
    except Exception:                   # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# engine registry + flag reconciliation (the PR-9 metrics-export pattern)
# ---------------------------------------------------------------------------

def register_engine(engine) -> None:
    _engines.add(engine)


def attach_engine(engine, programmatic: bool = False,
                  slo_ms: Optional[float] = None,
                  seed: int = 0) -> Optional[ServingAutoTuner]:
    """Called from ``ServingEngine.__init__``: build the engine's tuner.
    ``programmatic=True`` (the ``auto_tune=True`` ctor arg) always gets
    one; otherwise only when ``FLAGS_auto_tune`` is set — and that one
    is marked flag-started so :func:`apply_flags` may stop it later."""
    register_engine(engine)
    if programmatic:
        return ServingAutoTuner(engine, slo_ms=slo_ms, seed=seed)
    if enabled():
        return ServingAutoTuner(engine, slo_ms=slo_ms, seed=seed,
                                flag_started=True)
    return None


def apply_flags() -> None:
    """Reconcile running tuners with the current ``FLAGS_auto_tune*``
    values (mirrors ``metrics_export.apply_flags``): flipping the flag
    on mid-run starts a flag-started tuner on every live registered
    engine that lacks one; flipping it off stops ONLY flag-started
    tuners — a tuner the caller created with ``auto_tune=True`` belongs
    to its engine and is never stopped from here.
    ``FLAGS_auto_tune_dir`` re-roots the config store lazily (the next
    load/save reads the flag); ``FLAGS_auto_tune_probe_steps`` is read
    at probe time, so a new value applies to the next window."""
    on = enabled()
    for eng in list(_engines):
        tuner = getattr(eng, "_autotuner", None)
        if on:
            if tuner is None and not getattr(eng, "_closed", False):
                tuner = ServingAutoTuner(eng, flag_started=True)
                eng._autotuner = tuner
                if getattr(eng, "_started", False):
                    tuner.start()
        else:
            if tuner is not None and tuner.flag_started:
                tuner.stop()
                eng._autotuner = None


def reset_for_tests() -> None:
    """Forget every in-process tuning memo and decision (NOT the
    persisted store): the 'second process' half of a warm-restart test
    without actually forking one."""
    with _lock:
        _decisions.clear()
        _tuned.clear()
