"""Optimizers: minimize = append_backward + per-param update ops.

Reference: python/paddle/fluid/optimizer.py:56 `Optimizer` —
`minimize:907` = `backward:733` + `apply_gradients:799`, accumulators per
param, regularization and grad-clip hooks.  Same structure here; the update
ops are ops/optimizer_ops.py lowerings and XLA fuses the whole update phase
(the effect of fuse_adam_op_pass/fuse_sgd_op_pass is implicit).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .framework import (Program, Variable, Parameter, default_main_program,
                        default_startup_program, in_dygraph_mode, unique_name)
from .backward import append_backward
from .layer_helper import LayerHelper
from . import layers


class _EagerOptHelper:
    """LayerHelper stand-in for dygraph minimize: runs an optimizer op's
    lowering eagerly and writes every produced output back into the VarBase
    passed in that output slot (in-place update semantics)."""

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        from ..ops.registry import get_op
        from ..dygraph.base import _dygraph_tracer
        ins_arr = {s: [getattr(v, "_value", v) for v in vs]
                   for s, vs in (inputs or {}).items() if vs}
        ctx = _dygraph_tracer()._ctx()
        outs = get_op(type).fn(ins_arr, attrs or {}, ctx)
        for slot, vbs in (outputs or {}).items():
            arrs = outs.get(slot)
            if not arrs:
                continue
            for vb, arr in zip(vbs, arrs):
                if vb is not None and hasattr(vb, "_value"):
                    # never let a promoting lowering flip the param/acc
                    # dtype (bf16 param + f32 lr would otherwise widen)
                    if arr.dtype != vb._value.dtype:
                        arr = arr.astype(vb._value.dtype)
                    vb._value = arr
        return outs


class Optimizer:
    _accumulator_defaults: Dict[str, float] = {}
    # subclasses whose update op wires MasterParam/MasterParamOut
    # (ops/optimizer_ops.py) flip this on; everyone else REJECTS
    # multi_precision=True instead of silently ignoring it
    _supports_multi_precision = False

    def __init__(self, learning_rate=0.001, parameter_list=None,
                 regularization=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or type(self).__name__
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var: Optional[Variable] = None
        # fp32 master weights (reference optimizer.py multi_precision on
        # SGD/Momentum/Adam/AdamW/Lamb): low-precision params keep an fp32
        # master copy the update computes on; the param is a bf16 VIEW of
        # the master.  Master + moments are ordinary persistable
        # accumulators, so they ride the executor's written-names set and
        # the PR-4 donation path like every other optimizer state —
        # master copies never defeat buffer donation.
        if multi_precision and not self._supports_multi_precision:
            raise NotImplementedError(
                f"{type(self).__name__} has no fp32 master-weight path; "
                f"multi_precision=True is only supported on "
                f"SGD/Momentum/Adam/AdamW/Lamb")
        self._multi_precision = bool(multi_precision)
        self.helper = LayerHelper(self._name)

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if callable(self._learning_rate):
            self._lr_var = self._learning_rate()
            return
        if self._lr_var is None:
            self._lr_var = layers.create_global_var(
                [1], float(self._learning_rate), "float32", persistable=True,
                name=unique_name("learning_rate"))

    @property
    def current_lr(self):
        return self._lr_var

    def set_lr(self, value):
        self._learning_rate = value

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        if in_dygraph_mode():
            accs = self._accumulators.setdefault(name, {})
            if param.name not in accs:
                import jax.numpy as jnp
                from ..dygraph.base import VarBase
                acc_dtype = jnp.dtype(dtype) if dtype is not None \
                    else param._value.dtype
                accs[param.name] = VarBase(
                    jnp.full(tuple(shape or param.shape), fill_value,
                             acc_dtype), stop_gradient=True)
            return accs[param.name]
        key = f"{self._name}_{name}_{param.name}"
        acc = layers.create_global_var(
            shape or list(param.shape), fill_value, dtype or param.dtype,
            persistable=True, name=key)
        self._accumulators.setdefault(name, {})[param.name] = acc
        return acc

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def state_var_names(self):
        """Every persistable var this optimizer owns in static mode —
        moment/velocity accumulators, beta-power counters, the fp32
        ``master_weight`` copies, and the learning-rate var.  The
        checkpoint plane (fluid/checkpoint.py) records these in the
        manifest so a strict restore can prove the optimizer state is
        fully covered, not just the params."""
        names = set()
        for accs in self._accumulators.values():
            for v in accs.values():
                n = getattr(v, "name", None)
                if isinstance(n, str):
                    names.add(n)
        if self._lr_var is not None:
            n = getattr(self._lr_var, "name", None)
            if isinstance(n, str):
                names.add(n)
        return sorted(names)

    # -- fp32 master weights ------------------------------------------------
    def _mp_active(self, param) -> bool:
        dtype = (str(param._value.dtype) if hasattr(param, "_value")
                 else param.dtype)
        return self._multi_precision and dtype in ("float16", "bfloat16")

    def _master_weight(self, param):
        """The fp32 master accumulator for a low-precision param,
        initialised FROM the param's value (a startup-program cast in
        static mode, an eager astype in dygraph) — not zero-filled like
        ordinary accumulators."""
        accs = self._accumulators.setdefault("master_weight", {})
        if param.name in accs:
            return accs[param.name]
        if in_dygraph_mode():
            from ..dygraph.base import VarBase
            import jax.numpy as jnp
            mv = VarBase(param._value.astype(jnp.float32),
                         stop_gradient=True)
            accs[param.name] = mv
            return mv
        key = f"{self._name}_master_weight_{param.name}"
        block = default_main_program().global_block()
        var = block.create_var(name=key, shape=list(param.shape or []),
                               dtype="float32", persistable=True,
                               stop_gradient=True)
        sb = default_startup_program().global_block()
        sb.create_var(name=key, shape=list(param.shape or []),
                      dtype="float32", persistable=True)
        sb.append_op("cast", inputs={"X": [param.name]},
                     outputs={"Out": [key]},
                     attrs={"out_dtype": "float32"})
        accs[param.name] = var
        return var

    def _mp_io(self, param, inputs, outputs):
        """Wire MasterParam/MasterParamOut into an update op's slots when
        multi_precision applies to this param."""
        if self._mp_active(param):
            master = self._master_weight(param)
            inputs["MasterParam"] = [master]
            outputs["MasterParamOut"] = [master]
        return inputs, outputs

    def _acc_dtype(self, param):
        """Moment accumulators follow the COMPUTE dtype: fp32 under
        multi_precision, the param dtype otherwise."""
        return "float32" if self._mp_active(param) else None

    # -- main entry points --------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list or self._parameter_list,
                               no_grad_set)

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        params_grads = self._append_regularization(params_grads)
        self._create_global_learning_rate()
        self._create_accumulators([p for p, g in params_grads])
        ops = []
        for p, g in params_grads:
            if g is None:
                continue
            ops.append(self._append_optimize_op(p, g))
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._minimize_dygraph(loss, parameter_list)
        # ops append to the LOSS's program even when the caller is outside
        # program_guard (reference optimizer.py minimize wraps
        # program_guard(program, startup_program) the same way — without
        # it, update ops silently land in the global default program)
        from .framework import program_guard
        with program_guard(loss.block.program, startup_program):
            params_grads = self.backward(loss, startup_program,
                                         parameter_list, no_grad_set)
            ops = self.apply_gradients(params_grads)
        return ops, params_grads

    def _minimize_dygraph(self, loss, parameter_list=None):
        """Dygraph minimize (reference optimizer.py:907 imperative branch):
        collect tape gradients for the parameter list, then run each
        subclass's update op EAGERLY — the same `_append_optimize_op`
        declaration executes through an eager helper that calls the op
        lowering and writes ParamOut/…Out back into the passed VarBases
        (the aliasing the static executor gets from shared var names)."""
        import jax.numpy as jnp
        from ..dygraph.base import VarBase
        from .regularizer import L1DecayRegularizer

        params = list(parameter_list or self._parameter_list or [])
        if not params:
            raise ValueError(
                "fluid Optimizer.minimize in dygraph mode needs parameters: "
                "construct the optimizer with parameter_list=layer"
                ".parameters()")
        if all(p.gradient_var is None for p in params):
            loss.backward()
        params_grads = []
        for p in params:
            g = p.gradient_var
            if g is None or not getattr(p, "trainable", True):
                continue
            params_grads.append((p, VarBase(g, stop_gradient=True)))
        # Reference order (fluid/optimizer.py:825-831): clip the raw tape
        # gradients FIRST, then append regularization — so weight decay is
        # NOT included in the clipped norm (same as apply_gradients).
        if self._grad_clip is not None:
            params_grads = self._clip_eager(params_grads)
        regged = []
        for p, g in params_grads:
            reg = getattr(p, "regularizer", None) or self.regularization
            if reg is not None and hasattr(reg, "_coeff"):
                gv = g._value
                if isinstance(reg, L1DecayRegularizer):
                    gv = gv + reg._coeff * jnp.sign(p._value)
                else:
                    gv = gv + reg._coeff * p._value
                g = VarBase(gv, stop_gradient=True)
            regged.append((p, g))
        params_grads = regged

        lr = self._learning_rate
        lr = lr() if callable(lr) else lr
        lr = float(getattr(lr, "_value", lr))
        saved_helper, saved_lr = self.helper, self._lr_var
        self.helper = _EagerOptHelper()
        self._lr_var = VarBase(jnp.asarray([lr], jnp.float32),
                               stop_gradient=True)
        try:
            self._create_accumulators([p for p, _ in params_grads])
            for p, g in params_grads:
                self._append_optimize_op(p, g)
        finally:
            self.helper, self._lr_var = saved_helper, saved_lr
        return None, params_grads

    def _clip_eager(self, params_grads):
        import jax.numpy as jnp
        from ..dygraph.base import VarBase
        from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                           GradientClipByValue)
        gc = self._grad_clip
        arrs = [(p, g._value) for p, g in params_grads]
        if isinstance(gc, GradientClipByGlobalNorm):
            norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for _, g in arrs))
            scale = gc.clip_norm / jnp.maximum(norm, gc.clip_norm)
            arrs = [(p, g * scale) for p, g in arrs]
        elif isinstance(gc, GradientClipByNorm):
            out = []
            for p, g in arrs:
                n = jnp.sqrt(jnp.sum(jnp.square(g)))
                out.append((p, jnp.where(n > gc.clip_norm,
                                         g * (gc.clip_norm / n), g)))
            arrs = out
        elif isinstance(gc, GradientClipByValue):
            arrs = [(p, jnp.clip(g, gc.min, gc.max)) for p, g in arrs]
        else:
            return params_grads
        return [(p, VarBase(g, stop_gradient=True)) for p, g in arrs]

    # -- hooks for subclasses ----------------------------------------------
    def _create_accumulators(self, params):
        pass

    def _append_optimize_op(self, param, grad):
        raise NotImplementedError

    def _append_regularization(self, params_grads):
        out = []
        for p, g in params_grads:
            reg = getattr(p, "regularizer", None) or self.regularization
            if reg is None or g is None:
                out.append((p, g))
                continue
            out.append((p, reg._append(p, g)))
        return out

    # dygraph API
    def clear_gradients(self):
        for p in (self._parameter_list or []):
            p.clear_gradient()

    def state_dict(self):
        state = {}
        from .core import global_scope
        for acc_name, accs in self._accumulators.items():
            for param_name, var in accs.items():
                if hasattr(var, "_value"):      # dygraph VarBase accumulator
                    state[f"{self._name}_{acc_name}_{param_name}"] = \
                        np.asarray(var._value)
                else:
                    state[var.name] = np.asarray(
                        global_scope().find_var(var.name))
        return state


class SGDOptimizer(Optimizer):
    _supports_multi_precision = True

    def _append_optimize_op(self, param, grad):
        ins, outs = self._mp_io(
            param,
            {"Param": [param], "Grad": [grad],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [param]})
        return self.helper.append_op("sgd", inputs=ins, outputs=outs,
                                     attrs={"multi_precision":
                                            self._mp_active(param)})


class MomentumOptimizer(Optimizer):
    _supports_multi_precision = True

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("velocity", p, dtype=self._acc_dtype(p))

    def _append_optimize_op(self, param, grad):
        v = self._get_accumulator("velocity", param)
        ins, outs = self._mp_io(
            param,
            {"Param": [param], "Grad": [grad], "Velocity": [v],
             "LearningRate": [self._lr_var]},
            {"ParamOut": [param], "VelocityOut": [v]})
        return self.helper.append_op(
            "momentum", inputs=ins, outputs=outs,
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov,
                   "multi_precision": self._mp_active(param)})


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, param, grad):
        v = self._get_accumulator("velocity", param)
        return self.helper.append_op(
            "lars_momentum",
            inputs={"Param": [param], "Grad": [grad], "Velocity": [v],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [param], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class AdamOptimizer(Optimizer):
    _supports_multi_precision = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment1", p, dtype=self._acc_dtype(p))
            self._add_accumulator("moment2", p, dtype=self._acc_dtype(p))
            self._add_accumulator("beta1_pow", p, self._beta1, [1],
                                  dtype="float32")
            self._add_accumulator("beta2_pow", p, self._beta2, [1],
                                  dtype="float32")

    def _append_optimize_op(self, param, grad):
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow", param)
        b2p = self._get_accumulator("beta2_pow", param)
        ins = {"Param": [param], "Grad": [grad], "Moment1": [m1],
               "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
               "LearningRate": [self._lr_var]}
        outs = {"ParamOut": [param], "Moment1Out": [m1],
                "Moment2Out": [m2], "Beta1PowOut": [b1p],
                "Beta2PowOut": [b2p]}
        attrs = dict(self._op_attrs())
        ins, outs = self._mp_io(param, ins, outs)
        attrs["multi_precision"] = self._mp_active(param)
        return self.helper.append_op(self._op_type(), inputs=ins,
                                     outputs=outs, attrs=attrs)

    def _op_type(self):
        return "adam"

    def _op_attrs(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _op_type(self):
        return "adamw"

    def _op_attrs(self):
        return {**super()._op_attrs(), "coeff": self._coeff}


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, param, grad):
        m = self._get_accumulator("moment", param)
        return self.helper.append_op(
            "adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [param], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("moment", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, param, grad):
        ins = {"Param": [param], "Grad": [grad],
               "MeanSquare": [self._get_accumulator("mean_square", param)],
               "Moment": [self._get_accumulator("moment", param)],
               "LearningRate": [self._lr_var]}
        outs = {"ParamOut": [param],
                "MeanSquareOut": ins["MeanSquare"],
                "MomentOut": ins["Moment"]}
        if self._centered:
            ins["MeanGrad"] = [self._get_accumulator("mean_grad", param)]
            outs["MeanGradOut"] = ins["MeanGrad"]
        return self.helper.append_op(
            "rmsprop", inputs=ins, outputs=outs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class LambOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay

    def _op_type(self):
        return "lamb"

    def _op_attrs(self):
        return {**super()._op_attrs(), "weight_decay": self._weight_decay}


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, param, grad):
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return self.helper.append_op(
            "ftrl",
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._sigma = clip, sigma

    def _append_optimize_op(self, param, grad):
        return self.helper.append_op(
            "dpsgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [param]},
            attrs={"clip": self._clip, "sigma": self._sigma,
                   "op_seed": default_main_program().next_op_seed()})


# 2.0-style aliases (python/paddle/optimizer)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adagrad = AdagradOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer


class ExponentialMovingAverage:
    """EMA of parameters (optimizer.py:3441).  apply()/restore() swap
    shadow params in the scope."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}

    def update(self):
        from .core import global_scope
        from .framework import default_main_program
        import jax.numpy as jnp
        scope = global_scope()
        for p in default_main_program().all_parameters():
            val = scope.find_var(p.name)
            if val is None:
                continue
            prev = self._shadow.get(p.name, val)
            self._shadow[p.name] = (self._decay * prev
                                    + (1 - self._decay) * val)

    def apply(self, executor=None, need_restore=True):
        from .core import global_scope
        scope = global_scope()
        for name, val in self._shadow.items():
            self._backup[name] = scope.find_var(name)
            scope.set_var(name, val)
        return _EmaGuard(self)

    def restore(self, executor=None):
        from .core import global_scope
        scope = global_scope()
        for name, val in self._backup.items():
            scope.set_var(name, val)
        self._backup = {}


class _EmaGuard:
    def __init__(self, ema):
        self.ema = ema

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.ema.restore()
        return False


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging (optimizer.py:3132 +
    operators/average_accumulates_op.h).  Construct AFTER the training
    optimizer's minimize(): appends one `average_accumulates` op per param
    to the main program; `apply()` swaps params for
    (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates) and
    `restore()` swaps back."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._backup = {}
        self._params = [p for p in default_main_program().all_parameters()
                        if getattr(p, "do_model_average", None) is not False]
        for p in self._params:
            self._append_average_accumulate_op(p)

    def _append_average_accumulate_op(self, param):
        s1 = self._add_accumulator("sum_1", param)
        s2 = self._add_accumulator("sum_2", param)
        s3 = self._add_accumulator("sum_3", param)
        na = self._add_accumulator("num_accumulates", param, 0.0, [1])
        ona = self._add_accumulator("old_num_accumulates", param, 0.0, [1])
        nu = self._add_accumulator("num_updates", param, 0.0, [1])
        return self.helper.append_op(
            "average_accumulates",
            inputs={"param": [param], "in_sum_1": [s1], "in_sum_2": [s2],
                    "in_sum_3": [s3], "in_num_accumulates": [na],
                    "in_old_num_accumulates": [ona], "in_num_updates": [nu]},
            outputs={"out_sum_1": [s1], "out_sum_2": [s2], "out_sum_3": [s3],
                     "out_num_accumulates": [na],
                     "out_old_num_accumulates": [ona],
                     "out_num_updates": [nu]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window})

    def _append_optimize_op(self, param, grad):
        return None

    def apply(self, executor=None, need_restore=True):
        from .core import global_scope
        scope = global_scope()
        for p in self._params:
            cur = scope.find_var(p.name)
            if cur is None:
                continue
            s = (np.asarray(scope.find_var(self._acc_name("sum_1", p)))
                 + np.asarray(scope.find_var(self._acc_name("sum_2", p)))
                 + np.asarray(scope.find_var(self._acc_name("sum_3", p))))
            n = (np.asarray(scope.find_var(
                    self._acc_name("num_accumulates", p))).reshape(-1)[0]
                 + np.asarray(scope.find_var(
                    self._acc_name("old_num_accumulates", p))).reshape(-1)[0])
            if n > 0:
                if need_restore:
                    self._backup[p.name] = np.asarray(cur).copy()
                scope.set_var(p.name, (s / n).astype(np.asarray(cur).dtype))
        return _EmaGuard(self)   # no-op exit when nothing was backed up

    def _acc_name(self, kind, param):
        return self._accumulators[kind][param.name].name

    def restore(self, executor=None):
        from .core import global_scope
        scope = global_scope()
        for name, val in self._backup.items():
            scope.set_var(name, val)
        self._backup = {}


class RecomputeOptimizer(Optimizer):
    """Wrap an optimizer with recompute checkpoints (optimizer.py:4491).
    On TPU, recompute maps to jax.checkpoint boundaries annotated in the
    program; the executor applies rematerialisation hints."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        loss.block.program._hints["recompute_checkpoints"] = [
            v.name if isinstance(v, Variable) else v
            for v in (self._checkpoints or [])]
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        pg = self.backward(loss, startup_program, parameter_list, no_grad_set)
        return self._optimizer.apply_gradients(pg), pg


class GradientMergeOptimizer(Optimizer):
    """Accumulate grads over k steps then apply (optimizer.py:4969).
    Implemented with accumulator vars + a step-counter cond."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self._k = k_steps
        self._avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._k <= 1:
            return self._inner.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)
        pg = self._inner.backward(loss, startup_program, parameter_list,
                                  no_grad_set)
        step = layers.create_global_var([1], 0.0, "float32", persistable=True,
                                        name=unique_name("gm_step"))
        helper = LayerHelper("gradient_merge")
        helper.append_op("increment", inputs={"X": [step]},
                         outputs={"Out": [step]}, attrs={"step": 1.0})
        merged = []
        for p, g in pg:
            acc = layers.create_global_var(list(p.shape), 0.0, p.dtype,
                                           persistable=True,
                                           name=unique_name("gm_acc"))
            gsum = layers.sums([acc, g])
            layers.assign(gsum, acc)
            merged.append((p, acc))
        # apply every k steps: scaled accumulated grads; on the k-1 other
        # steps the update ops are SKIPPED outright via the SkipUpdate
        # gate (reference optimizer.py:4969 runs them under a conditional
        # block) — feeding zero grads instead would still decay Adam's
        # moments and advance beta powers on every step
        k_const = layers.fill_constant([1], "float32", float(self._k))
        from .layers.control_flow import less_than
        skip_v = less_than(step, k_const)
        gate = 1.0 - layers.cast(skip_v, "float32")
        scale = 1.0 / self._k if self._avg else 1.0
        applied_pg = [(p, layers.scale(a, scale=scale)) for p, a in merged]
        ops = self._inner.apply_gradients(applied_pg)
        for op in ops:
            if op is not None and hasattr(op, "inputs"):
                op.inputs["SkipUpdate"] = [skip_v.name]
        # reset: acc *= (1 - gate); step *= (1 - gate)
        for p, a in merged:
            layers.assign(a * (1.0 - gate), a)
        layers.assign(step * (1.0 - gate), step)
        return ops, applied_pg


class LookaheadOptimizer:
    """Lookahead (optimizer.py:5174): fast weights step every iteration;
    every k steps the slow weights move toward the fast ones
    (slow += alpha * (fast - slow)) and the fast weights reset to slow.
    The k-step gate is branch-free: where(apply, new, old) on both copies."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert k >= 1
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        ops, pg = self.inner_optimizer.minimize(loss, startup_program)
        step = layers.create_global_var([1], 0.0, "float32",
                                        persistable=True,
                                        name=unique_name("la_step"))
        helper = LayerHelper("lookahead")
        helper.append_op("increment", inputs={"X": [step]},
                         outputs={"Out": [step]}, attrs={"step": 1.0})
        k_const = layers.fill_constant([1], "float32", float(self.k))
        from .layers.control_flow import greater_equal
        apply_v = greater_equal(step, k_const)
        gate = layers.cast(apply_v, "float32")     # 1.0 on sync steps
        sb = default_startup_program().global_block()
        for p, g in pg:
            slow = layers.create_global_var(
                list(p.shape), 0.0, p.dtype, persistable=True,
                name=unique_name(p.name + "_la_slow"))
            # slow weights start AT the initial params (reference lookahead
            # startup assign), not at zero
            sb.append_op("assign", inputs={"X": [p.name]},
                         outputs={"Out": [slow.name]})
            # slow' = slow + gate*alpha*(fast - slow); fast' = gated slow'
            delta = layers.scale(p - slow, scale=self.alpha)
            new_slow = slow + delta * gate
            layers.assign(new_slow, slow)
            layers.assign(p + (new_slow - p) * gate, p)
        layers.assign(step * (1.0 - gate), step)
        return ops, pg


class PipelineOptimizer:
    """Program-splitting pipeline optimizer facade (optimizer.py:3693).
    The TPU implementation lives in parallel/pipeline.py (GPipe schedule
    over mesh stages); this class keeps the fluid API shape."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.block.program._hints["pipeline_microbatches"] = \
            self._num_microbatches
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)


class BoxPSOptimizer:
    """BoxPS pipeline optimizer facade (reference optimizer.py:5194): the
    reference splits the program at cut_list into host/device sections
    with per-section thread pools.  TPU-native redesign: the device
    section is ONE XLA step and the host sections are the BoxPS pass
    machinery — begin/end-pass double buffering (`exe.train_passes`) and
    the trainer's feed prefetcher supply the overlap the section threads
    provided.  cut_list/place_list/concurrency_list are accepted for API
    parity and recorded as hints; minimize delegates to the inner
    optimizer (sparse params train server-side in the box table)."""

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0):
        self._optimizer = optimizer
        self._cut_list = cut_list or []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        prog = loss.block.program
        prog._hints["boxps_pipeline"] = {"cuts": len(self._cut_list)}
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)


class DGCMomentumOptimizer(Optimizer):
    """Deep Gradient Compression momentum (optimizer.py:1183,
    operators/optimizers/dgc_momentum_op.cc).  Per-param state U (momentum
    correction) and V (error feedback); top-k sparsified grads all-reduced
    after rampup_begin_step.  See ops/optimizer_ops.py dgc_momentum for the
    ICI semantics."""

    def __init__(self, learning_rate, momentum=0.9, rampup_begin_step=0,
                 rampup_step=1, sparsity=None, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._rampup_begin_step = rampup_begin_step
        self._sparsity = (sparsity or [0.999])[-1]
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)
        self._step_var = layers.create_global_var(
            [1], 0.0, "float32", persistable=True,
            name=unique_name("dgc_step"))

    def _append_optimize_op(self, param, grad):
        u = self._get_accumulator("dgc_u", param)
        v = self._get_accumulator("dgc_v", param)
        return self.helper.append_op(
            "dgc_momentum",
            inputs={"Param": [param], "Grad": [grad], "U": [u], "V": [v],
                    "LearningRate": [self._lr_var],
                    "CurrentStep": [self._step_var]},
            outputs={"ParamOut": [param], "UOut": [u], "VOut": [v]},
            attrs={"mu": self._momentum, "sparsity": self._sparsity,
                   "rampup_begin_step": float(self._rampup_begin_step),
                   "use_nesterov": self._use_nesterov, "ring_id": 0})

    def apply_gradients(self, params_grads):
        ops = super().apply_gradients(params_grads)
        self.helper.append_op("increment", inputs={"X": [self._step_var]},
                              outputs={"Out": [self._step_var]},
                              attrs={"step": 1.0})
        return ops


class AdamaxOptimizer(Optimizer):
    """optimizers/adamax kernel (reference optimizer.py Adamax tier)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, self._beta1, [1])

    def _append_optimize_op(self, param, grad):
        m = self._get_accumulator("moment", param)
        u = self._get_accumulator("inf_norm", param)
        b1p = self._get_accumulator("beta1_pow", param)
        op = self.helper.append_op(
            "adamax",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "InfNorm": [u], "Beta1Pow": [b1p],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [param], "MomentOut": [m],
                     "InfNormOut": [u]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})
        # beta1_pow update (the reference does this as a scale op too)
        self.helper.append_op(
            "scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
            attrs={"scale": self._beta1})
        return op


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, param, grad):
        g2 = self._get_accumulator("avg_squared_grad", param)
        u2 = self._get_accumulator("avg_squared_update", param)
        return self.helper.append_op(
            "adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [g2], "AvgSquaredUpdate": [u2]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [g2],
                     "AvgSquaredUpdateOut": [u2]},
            attrs={"rho": self._rho, "epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, param, grad):
        m = self._get_accumulator("moment", param)
        return self.helper.append_op(
            "decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [param], "MomentOut": [m]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


# reference fluid.optimizer short aliases (optimizer.py __all__ head)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Dpsgd = DpsgdOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer
Lamb = LambOptimizer
