"""Weight-decay regularizers appended as grad ops (fluid regularizer.py)."""
from __future__ import annotations

from . import layers


class WeightDecayRegularizer:
    def _append(self, param, grad):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append(self, param, grad):
        return layers.elementwise_add(
            grad, layers.scale(param, scale=self._coeff))


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append(self, param, grad):
        from .layers import nn
        return layers.elementwise_add(
            grad, layers.scale(nn.sign(param), scale=self._coeff))


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
