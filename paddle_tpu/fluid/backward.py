"""Op-level autodiff over the Program IR.

Reference: python/paddle/fluid/backward.py:1276 `append_backward` reverse-walks
the ops of a ProgramDesc and asks each op's C++ GradOpDescMaker
(backward.py:984 -> core.get_grad_op_desc) for its grad OpDescs, inserting
`sum` ops for fan-in.  TPU-native difference: there are no hand-written grad
ops.  One *generic* grad op (`generic_grad`) computes input cotangents with
`jax.vjp` over the forward op's own lowering rule — correctness is inherited
from JAX's AD instead of 676 hand-derived kernels, and XLA's CSE dedups the
vjp-recomputed forward with the original forward in the same compiled block.
Ops with special grad semantics register `custom_grad` (registry.py).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp

from ..ops.registry import register_op, get_op, has_op
from .framework import Program, Block, Variable, Parameter

GRAD_SUFFIX = "@GRAD"


def _grad_name(name: str) -> str:
    return name + GRAD_SUFFIX


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype if not hasattr(x, "dtype")
                          else x.dtype, jnp.floating)


# ---------------------------------------------------------------------------
# the generic grad op
# ---------------------------------------------------------------------------
@register_op("generic_grad", differentiable=False)
def _generic_grad(ins, attrs, ctx):
    """ins:  I_<slot> forward inputs, G_<slot> output cotangents.
    outs: GI_<slot> input cotangents (only for slots listed in grad_slots).
    """
    fwd_def = get_op(attrs["fwd_type"])
    fwd_attrs = attrs["fwd_attrs"]
    grad_slots: List[str] = attrs["grad_slots"]         # slots needing grads
    in_slots: List[str] = attrs["in_slots"]

    fwd_ins = {s: list(ins.get("I_" + s, [])) for s in in_slots}

    # split differentiable vs closed-over inputs (per-arg, by runtime dtype)
    diff_tree, closed = {}, {}
    for s in in_slots:
        args = fwd_ins[s]
        if s in fwd_def.nondiff_inputs or s not in grad_slots:
            closed[s] = args
            continue
        diff_tree[s] = [a if _is_float(a) else None for a in args]
        closed[s] = [None if _is_float(a) else a for a in args]

    def merge(diff):
        out = {}
        for s in in_slots:
            ca = closed[s]
            da = diff.get(s, [None] * len(ca))
            out[s] = [d if d is not None else c for d, c in zip(da, ca)]
        return out

    def fwd_fn(diff):
        outs = fwd_def.fn(merge(diff), fwd_attrs, ctx)
        return {s: [o if _is_float(o) else None for o in v]
                for s, v in outs.items() if s not in fwd_def.nondiff_outputs}

    if fwd_def.custom_grad is not None:
        fwd_outs = fwd_def.fn(merge(diff_tree), fwd_attrs, ctx)
        out_grads = {}
        for s in fwd_outs:
            gs = ins.get("G_" + s)
            out_grads[s] = gs[0] if gs else None
        in_grads = fwd_def.custom_grad(merge(diff_tree), fwd_outs, out_grads,
                                       fwd_attrs, ctx)
        return {"GI_" + s: v for s, v in in_grads.items() if s in grad_slots}

    primal_outs, vjp_fn = jax.vjp(fwd_fn, diff_tree)
    cotangents = {}
    for s, outs_ in primal_outs.items():
        gs = ins.get("G_" + s, [])
        cts = []
        for i, o in enumerate(outs_):
            if o is None:
                cts.append(None)
            elif i < len(gs) and gs[i] is not None:
                cts.append(gs[i].astype(o.dtype)
                           if gs[i].dtype != o.dtype else gs[i])
            else:
                cts.append(jnp.zeros_like(o))
        cotangents[s] = cts
    (in_grads,) = vjp_fn(cotangents)

    result = {}
    for s in grad_slots:
        grads = in_grads.get(s, [])
        result["GI_" + s] = [g if g is not None
                             else jnp.zeros((), jnp.float32) for g in grads]
    return result


# ---------------------------------------------------------------------------
# append_backward
# ---------------------------------------------------------------------------
def _forward_requires(block: Block, targets: Set[str],
                      no_grad: Set[str]) -> Set[str]:
    """Forward propagate 'requires grad' from trainable leaves."""
    req = set()
    for v in block.program.global_block().vars.values():
        if isinstance(v, Parameter) and v.trainable and v.name not in no_grad:
            req.add(v.name)
    for v in block.vars.values():
        if v.is_data and not v.stop_gradient and v.name not in no_grad:
            req.add(v.name)
    for op in block.ops:
        opdef = get_op(op.type) if has_op(op.type) else None
        if opdef is None or not opdef.differentiable:
            continue
        if any(n in req for n in op.input_arg_names):
            for n in op.output_arg_names:
                var = block._find_var_recursive(n)
                if var is None or not var.stop_gradient:
                    req.add(n)
    return req


def _relevant_to(block: Block, loss_name: str) -> Set[str]:
    """Backward reachability: vars that influence the loss."""
    rel = {loss_name}
    for op in reversed(block.ops):
        if any(n in rel for n in op.output_arg_names):
            rel.update(op.input_arg_names)
    return rel


def append_backward(loss: Variable, parameter_list=None,
                    no_grad_set: Optional[Set[str]] = None,
                    callbacks=None, checkpoints=None) -> List:
    """Append grad ops computing d(loss)/d(param) for every trainable param.

    Returns [(param, grad_var)] like the reference (backward.py:1276).
    `checkpoints` (recompute segments) are honored by the executor via
    jax.checkpoint boundaries (see RecomputeOptimizer).
    """
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())
    requires = _forward_requires(block, {loss.name}, no_grad)
    relevant = _relevant_to(block, loss.name)

    # loss cotangent = 1 (fill_constant, like fluid's fill op for loss@GRAD)
    loss_grad = _grad_name(loss.name)
    block.append_op(
        "fill_constant", outputs={"Out": [loss_grad]},
        attrs={"shape": list(loss.shape or ()), "value": 1.0,
               "dtype": loss.dtype or "float32", "op_role": 1})
    block.var(loss_grad).stop_gradient = True

    # var -> list of partial grad var names (summed at the end)
    grads: Dict[str, List[str]] = {loss.name: [loss_grad]}

    fwd_ops = [op for op in block.ops[:-1]]  # exclude the fill we just added
    for op in reversed(fwd_ops):
        if not has_op(op.type) or op.type == "generic_grad":
            continue
        opdef = get_op(op.type)
        if not opdef.differentiable:
            continue
        if not any(n in relevant and n in grads for n in op.output_arg_names):
            continue
        grad_slots = []
        for slot, names in op.inputs.items():
            if slot in opdef.nondiff_inputs:
                continue
            if any(n in requires and n not in no_grad for n in names):
                grad_slots.append(slot)
        if not grad_slots:
            continue

        g_ins: Dict[str, List[str]] = {}
        for slot, names in op.inputs.items():
            g_ins["I_" + slot] = list(names)
        has_any_outgrad = False
        for slot, names in op.outputs.items():
            if slot in opdef.nondiff_outputs:
                continue
            gnames = []
            ok = False
            for n in names:
                if n in grads:
                    gnames.append(_sum_partials(block, n, grads))
                    ok = True
                else:
                    gnames = None
                    break
            if ok and gnames is not None:
                g_ins["G_" + slot] = gnames
                has_any_outgrad = True
        if not has_any_outgrad:
            continue

        g_outs: Dict[str, List[str]] = {}
        for slot in grad_slots:
            outs = []
            for n in op.input(slot):
                gname = _grad_name(n)
                if n in grads or gname in {x for v in grads.values() for x in v}:
                    gname = gname + "@RENAME_" + str(len(grads.get(n, [])))
                outs.append(gname)
                grads.setdefault(n, []).append(gname)
            g_outs["GI_" + slot] = outs

        block.append_op(
            "generic_grad", inputs=g_ins, outputs=g_outs,
            attrs={"fwd_type": op.type, "fwd_attrs": dict(op.attrs),
                   "in_slots": list(op.inputs.keys()),
                   "grad_slots": grad_slots, "op_role": 1})
        for slot_outs in g_outs.values():
            for n in slot_outs:
                block.var(n).stop_gradient = True

    # build (param, grad) list
    params = (list(parameter_list) if parameter_list
              else [p for p in program.all_parameters() if p.trainable])
    result = []
    for p in params:
        p_obj = p if isinstance(p, Variable) else block.var(p)
        if p_obj.name in no_grad or p_obj.name not in grads:
            continue
        gname = _sum_partials(block, p_obj.name, grads)
        gvar = block.var(gname)
        gvar.shape = p_obj.shape
        gvar.dtype = p_obj.dtype
        result.append((p_obj, gvar))
    return result


def _sum_partials(block: Block, name: str, grads: Dict[str, List[str]]) -> str:
    """Collapse accumulated partial grads into one var (fluid's inserted
    `sum` op for fan-in, backward.py _addup_repetitive_outputs_)."""
    parts = grads[name]
    if len(parts) == 1:
        final = parts[0]
    else:
        final = _grad_name(name)
        if final in parts:
            tmp = final + "@SUM"
            block.append_op("sum", inputs={"X": parts},
                            outputs={"Out": [tmp]}, attrs={"op_role": 1})
            final = tmp
        else:
            block.append_op("sum", inputs={"X": parts},
                            outputs={"Out": [final]}, attrs={"op_role": 1})
        block.var(final).stop_gradient = True
    grads[name] = [final]
    return final


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients / fluid calc_gradient (backward.py:1729)."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    pairs = append_backward(t, parameter_list=None, no_grad_set=no_grad_set)
    gmap = {p.name: g for p, g in pairs}
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    out = []
    for v in ins:
        gname = _grad_name(v.name)
        out.append(t.block.var(gname) if t.block.has_var(gname) else None)
    return out
