"""fluid.distributed.fleet analog (reference fluid/distributed/fleet.py
Fleet) — the oldest PS facade, aliasing the incubate fleet adapter."""
from ...incubate.fleet.base.fleet_base import LegacyFleetAdapter as Fleet

__all__ = ["Fleet"]
