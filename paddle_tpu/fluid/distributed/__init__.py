"""fluid.distributed namespace (reference fluid/distributed/: the
pre-fleet downpour python tier) — served by the incubate fleet shims."""
from .fleet import Fleet  # noqa: F401
