"""fluid.entry_attr analog (reference entry_attr.py): admission policies
for large-scale sparse tables — the CTR accessor tier
(distributed/ps/table.py) consumes these thresholds."""
from __future__ import annotations

__all__ = ["ProbabilityEntry", "CountFilterEntry"]


class EntryAttr:
    def _to_attr(self):
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._name = "probability_entry"
        self._probability = float(probability)

    def _to_attr(self):
        return f"{self._name}:{self._probability}"


class CountFilterEntry(EntryAttr):
    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._name = "count_filter_entry"
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"{self._name}:{self._count_filter}"
