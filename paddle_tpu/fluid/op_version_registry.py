"""Op semantic-version registry — what a saved op's attrs MEAN.

Reference analog: paddle/fluid/framework/op_version_registry.h (each op
registers a version; saved programs carry an OpVersionMap; loaders use it
for compatibility decisions).  Here the registry does two jobs:

* on SAVE, `snapshot()` records the current version of every op type that
  appears in the program into ProgramDesc.op_version_map;
* on LOAD, `check_and_convert()` compares each saved op's version with the
  running registry: older versions are upgraded through registered
  attr-level converters (applied in sequence v, v+1, ... current-1), a
  NEWER version than the runtime knows is a hard error (the attrs could
  silently mean something else), and an op absent from the saved map is
  treated as version 0 (pre-versioning save).

Register a version bump together with its converter so old artifacts keep
loading:

    register_op_version("dropout", 1)

    @register_converter("dropout", from_version=0)
    def _(attrs):  # mutate attrs in place to version-1 meaning
        attrs.setdefault("dropout_implementation", "downgrade_in_infer")
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

__all__ = ["register_op_version", "register_converter", "current_version",
           "snapshot", "check_and_convert", "OpVersionError"]


class OpVersionError(RuntimeError):
    """Saved op version is ahead of what this runtime understands."""


_VERSIONS: Dict[str, int] = {}
_CONVERTERS: Dict[Tuple[str, int], Callable] = {}


def register_op_version(op_type: str, version: int) -> None:
    if version < 0:
        raise ValueError("op version must be >= 0")
    _VERSIONS[op_type] = max(version, _VERSIONS.get(op_type, 0))


def register_converter(op_type: str, from_version: int):
    """Decorator: register fn(attrs_dict) upgrading `op_type` attrs from
    `from_version` to `from_version + 1` semantics (mutates in place)."""
    def deco(fn):
        _CONVERTERS[(op_type, from_version)] = fn
        return fn
    return deco


def current_version(op_type: str) -> int:
    return _VERSIONS.get(op_type, 0)


def snapshot(op_types) -> Dict[str, int]:
    """Current version of every op type in the iterable (for save)."""
    return {t: current_version(t) for t in set(op_types)}


def check_and_convert(op_type: str, attrs: dict, saved_version: int) -> None:
    """Upgrade `attrs` in place from saved_version to the current version.

    Raises OpVersionError only for ops THIS registry tracks when the
    artifact is ahead of the known history — for untracked ops any saved
    version is accepted, because real reference exports pin versions for
    many ops (their registry, op_version_registry.h) whose current
    semantics are exactly what this framework implements; refusing those
    would reject every genuine reference model."""
    cur = current_version(op_type)
    if saved_version > cur:
        if op_type in _VERSIONS:
            raise OpVersionError(
                f"op '{op_type}' was saved at version {saved_version} but "
                f"this runtime only understands version {cur}; upgrade "
                f"paddle_tpu or re-export the model")
        return  # untracked op: implementation follows the reference head
    for v in range(saved_version, cur):
        conv = _CONVERTERS.get((op_type, v))
        if conv is not None:
            conv(attrs)


# --- registered version history -------------------------------------------
# dropout v1: `dropout_implementation` attr became load-bearing (upscale vs
# downgrade semantics, reference dropout_op.cc); v0 saves predate the attr
# and meant the historical default.
register_op_version("dropout", 1)


@register_converter("dropout", from_version=0)
def _dropout_v0_to_v1(attrs):
    attrs.setdefault("dropout_implementation", "downgrade_in_infer")


# --- the reference's own REGISTER_OP_VERSION pins (all 26 sites under
# operators/; each has one checkpoint = version 1).  Attr-adding
# checkpoints get converters injecting the checkpoint's defaults so a v0
# artifact means exactly what it meant; input/output additions and
# behavior bugfixes need no attr conversion (missing inputs are optional
# in the lowerings, and this framework implements the POST-fix behavior).

def _defaults(op_type, **kv):
    register_op_version(op_type, 1)

    @register_converter(op_type, from_version=0)
    def _conv(attrs, _kv=kv):
        for k, v in _kv.items():
            # copy list defaults: a shared mutable would alias across ops
            attrs.setdefault(k, list(v) if isinstance(v, list) else v)


_defaults("arg_max", flatten=False)                # arg_max_op.cc:35
_defaults("arg_min", flatten=False)                # arg_min_op.cc
_defaults("cumsum", flatten=False)                 # cumsum_op.cc
_defaults("softplus", beta=1.0, threshold=20.0)    # activation_op.cc:1375
_defaults("momentum", regularization_method="",    # momentum_op.cc
          regularization_coeff=0.0)
_defaults("conv2d", use_addto=False)               # conv_op.cc
_defaults("conv3d", use_addto=False)
_defaults("depthwise_conv2d", use_addto=False)
_defaults("conv2d_transpose", output_padding=[])   # conv_transpose_op.cc
_defaults("unique", return_index=False,            # unique_op.cc
          return_inverse=False, return_counts=False)

for _op in ("leaky_relu", "hard_shrink", "lookup_table_v2", "clip",
            "gather", "roi_align", "roi_pool", "fill_constant",
            "gaussian_random", "cudnn_lstm", "data_norm", "matrix_nms",
            "generate_proposals", "collect_fpn_proposals",
            "distribute_fpn_proposals", "quantize"):
    register_op_version(_op, 1)
