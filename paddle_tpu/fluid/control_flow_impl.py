"""Control-flow op execution: sub-blocks -> lax.cond / lax.while_loop.

Reference: paddle/fluid/operators/controlflow/{while_op,conditional_block_op}.cc
run their BLOCK-attr sub-blocks with a nested Executor over a kid Scope
(SURVEY §2.5 controlflow/).  TPU-native: a sub-block is lowered into the SAME
jaxpr as structured control flow — `lax.while_loop` / `lax.cond` — with an
explicit var->loop-carry analysis (SURVEY §7 hard part #2).  The carry is the
set of vars the sub-block writes that are visible outside, plus everything it
reads from the enclosing env.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax


def _block_reads_writes(block):
    """Reads/writes of a block INCLUDING nested control-flow ops' sub-
    blocks — an inner conditional_block's dependencies live in BLOCK attrs,
    not its input/output arg lists, and must still ride the outer closure.
    The per-op analysis (sub-block recursion + the pass-through false
    path's prior-value reads) is framework._op_reads — ONE shared
    implementation with the pruner, so the two can't drift."""
    from .framework import _op_reads
    reads, writes = [], set()
    for op in block.ops:
        for n in _op_reads(block, op):
            if n not in writes and n not in reads:
                reads.append(n)
        writes.update(op.output_arg_names)
    return reads, sorted(writes)


def run_control_flow_op(op, block, env: Dict[str, Any], ctx):
    from .executor import run_block_ops
    program = block.program

    if op.type == "while":
        cond_block = program.blocks[op.attr("cond_block")]
        body_block = program.blocks[op.attr("sub_block")]
        c_reads, _ = _block_reads_writes(cond_block)
        b_reads, b_writes = _block_reads_writes(body_block)
        cond_out = op.attr("cond_var")

        # carry: everything the body writes (visible after the loop) plus all
        # external reads so the traced closures stay pure
        carried = sorted(set(b_writes) | {
            n for n in (c_reads + b_reads) if n in env})
        carry0 = tuple(env[n] if n in env else jnp.zeros((), jnp.float32)
                       for n in carried)

        def to_env(carry):
            e = dict(env)
            e.update(zip(carried, carry))
            return e

        def cond_fn(carry):
            e = run_block_ops(cond_block, to_env(carry), ctx)
            return e[cond_out].reshape(()).astype(bool)

        def body_fn(carry):
            e = run_block_ops(body_block, to_env(carry), ctx)
            return tuple(e[n] for n in carried)

        final = lax.while_loop(cond_fn, body_fn, carry0)
        env.update(zip(carried, final))
        return

    if op.type == "conditional_block":
        # native design: TWO sub-blocks (true/false) + unified outputs, unlike
        # the reference's conditional_block+select_input pair — maps 1:1 onto
        # lax.cond's requirement that both branches exist
        true_block = program.blocks[op.attr("true_block")]
        false_idx = op.attr("false_block", -1)
        out_names = op.output("Out")
        cond = env[op.input("Cond")[0]].reshape(()).astype(bool)
        t_reads, _ = _block_reads_writes(true_block)
        reads = [n for n in t_reads if n in env]
        t_outs = op.attr("true_outs")
        if false_idx < 0:
            # no false block: the false path passes PRIOR values of the
            # outputs through, so they must ride in the closure even when
            # the true block never reads them (e.g. a pure assign body)
            missing = [n for n in t_outs if n not in env]
            if missing:
                raise KeyError(
                    f"conditional_block outputs {missing} have no prior "
                    f"value — define them before the conditional")
            reads = sorted(set(reads) | set(t_outs))
        if false_idx >= 0:
            false_block = program.blocks[false_idx]
            f_reads, _ = _block_reads_writes(false_block)
            reads = sorted(set(reads) | {n for n in f_reads if n in env})
            f_outs = op.attr("false_outs")
        closure = {n: env[n] for n in reads}

        def true_fn(cl):
            e = dict(env)
            e.update(cl)
            e = run_block_ops(true_block, e, ctx)
            return tuple(e[n] for n in t_outs)

        def false_fn(cl):
            if false_idx < 0:
                return tuple(cl[n] for n in t_outs)
            e = dict(env)
            e.update(cl)
            e = run_block_ops(false_block, e, ctx)
            return tuple(e[n] for n in f_outs)

        result = lax.cond(cond, true_fn, false_fn, closure)
        env.update(zip(out_names, result))
        return

    if op.type == "select_input":
        mask = env[op.input("Mask")[0]].reshape(()).astype(jnp.int32)
        xs = [env[n] for n in op.input("X")]
        out = xs[0]
        for i in range(1, len(xs)):
            out = lax.cond(mask == i, lambda a, b: b, lambda a, b: a, out, xs[i])
        env[op.output("Out")[0]] = out
        return

    raise NotImplementedError(f"control flow op {op.type}")
