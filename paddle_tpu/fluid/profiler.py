"""Profiler facade over the JAX/XLA profiler.

Reference: python/paddle/fluid/profiler.py context manager ->
platform/profiler.cc RAII spans + CUPTI device tracer (SURVEY §5 tracing).
TPU-native: jax.profiler emits XPlane traces viewable in TensorBoard /
Perfetto — the chrome://tracing role of tools/timeline.py.  RecordEvent maps
to jax.profiler.TraceAnnotation (host spans visible alongside device ops).
"""
from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        print(f"[profiler] trace written to {profile_path} "
              f"(wall {time.time() - t0:.3f}s); view with tensorboard "
              f"--logdir {profile_path}")


def start_profiler(state="All", tracer_option="Default",
                   profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.stop_trace()


class RecordEvent:
    """platform/profiler.h:127 RecordEvent analog — host span annotation."""

    def __init__(self, name):
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ann.__exit__(*exc)


record_event = RecordEvent


@contextlib.contextmanager
def cuda_profiler(*a, **k):  # API parity; no CUDA on TPU
    yield


def reset_profiler():
    """Clear accumulated profile events (profiler.py reset_profiler)."""
    import jax
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass                          # no trace running


def start_gperf_profiler():
    """dygraph/profiler.py analog — gperftools has no TPU role; the JAX
    trace profiler (start_profiler) is the supported path."""
    start_profiler()


def stop_gperf_profiler():
    stop_profiler()
