"""Profiler facade: host observability plane + optional JAX/XLA profiler.

Reference: python/paddle/fluid/profiler.py context manager ->
platform/profiler.cc RAII spans + CUPTI device tracer (SURVEY §5 tracing).
TPU-native, two tiers:

* the framework-native host plane (fluid/trace.py) — always available:
  per-op dispatch spans, compile-cache events, step timing, the sorted
  calls/total/min/max/ave summary, Chrome-trace export;
* ``jax.profiler`` XPlane traces (TensorBoard / Perfetto) for device-side
  op time — best effort: on backends/headless setups where
  ``start_trace`` raises, the profiler DEGRADES to host-only tracing
  instead of crashing the training run.

``RecordEvent`` spans land in both tiers, so host annotations line up with
device ops in either viewer.
"""
from __future__ import annotations

import contextlib
import os
import sys
import time

import jax

from . import trace

_DEFAULT_PATH = "/tmp/paddle_tpu_profile"

# whether a jax.profiler trace session is live (start/stop must pair)
_jax_trace_active = False


def _start_jax_trace(profile_path: str) -> bool:
    """Best-effort device trace.  Headless/CPU-CI/odd backends can make
    ``start_trace`` raise — degrade to the host plane, never propagate."""
    global _jax_trace_active
    if _jax_trace_active:
        return True
    try:
        jax.profiler.start_trace(profile_path)
        _jax_trace_active = True
        return True
    except Exception as e:          # noqa: BLE001 — degrade by contract
        print(f"paddle_tpu.profiler: device trace unavailable "
              f"({type(e).__name__}: {e}); continuing with host-only "
              f"tracing", file=sys.stderr)
        return False


def _stop_jax_trace() -> None:
    global _jax_trace_active
    if not _jax_trace_active:
        return
    _jax_trace_active = False
    try:
        jax.profiler.stop_trace()
    except Exception:               # noqa: BLE001 — stop must not raise
        pass


def start_profiler(state="All", tracer_option="Default",
                   profile_path=_DEFAULT_PATH):
    """Begin profiling: host plane on, device trace if the backend
    supports it (reference start_profiler semantics, no-crash)."""
    trace.enable()
    _start_jax_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path=_DEFAULT_PATH):
    """Stop profiling; print the reference-style sorted op-time summary and
    export the host timeline next to the device trace."""
    _stop_jax_trace()
    if trace.get_events():
        out = os.path.join(profile_path, "paddle_tpu_timeline.json")
        trace.export_chrome_trace(out)
        print(trace.summary_table(sorted_key or "total"))
        print(f"[profiler] host timeline: {out} "
              f"(chrome://tracing / ui.perfetto.dev)")


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=_DEFAULT_PATH):
    was_enabled = trace.enabled()
    start_profiler(state, profile_path=profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
        print(f"[profiler] trace under {profile_path} "
              f"(wall {time.time() - t0:.3f}s); device view: tensorboard "
              f"--logdir {profile_path}")
        if not was_enabled:
            trace.disable()         # restore caller's gating


class RecordEvent:
    """platform/profiler.h:127 RecordEvent analog — host span annotation.
    Emits into the host plane always (when enabled) and into the device
    trace when one is live; TraceAnnotation failures never propagate."""

    def __init__(self, name):
        self.name = name
        self._t0 = None
        self._ann = None

    def __enter__(self):
        if trace.enabled():
            self._t0 = trace.now()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:           # noqa: BLE001 — annotation best-effort
            self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:       # noqa: BLE001
                pass
        if self._t0 is not None:
            trace.complete(self.name, self._t0, cat="annotation")
            self._t0 = None
        return False


record_event = RecordEvent


@contextlib.contextmanager
def cuda_profiler(*a, **k):  # API parity; no CUDA on TPU
    yield


def reset_profiler():
    """Clear accumulated profile events (profiler.py reset_profiler):
    stops any live device trace and empties the host event buffer."""
    _stop_jax_trace()
    trace.reset()


def start_gperf_profiler():
    """dygraph/profiler.py analog — gperftools has no TPU role; the JAX
    trace profiler (start_profiler) is the supported path."""
    start_profiler()


def stop_gperf_profiler():
    stop_profiler()
