"""fluid.evaluator analog (reference evaluator.py — the pre-metrics
evaluator tier, deprecated in the reference in favor of fluid.metrics):
the classes ARE the metrics implementations."""
from .metrics import ChunkEvaluator, EditDistance, DetectionMAP

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]
