"""Gradient clipping (fluid clip.py: GradientClipByValue/Norm/GlobalNorm)."""
from __future__ import annotations

from . import layers


class GradientClipBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        return [(p, layers.clip(g, self.min, self.max) if g is not None else g)
                for p, g in params_grads]


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        return [(p, layers.clip_by_norm(g, self.clip_norm)
                 if g is not None else g) for p, g in params_grads]


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        from .layers import nn
        sq = [nn.reduce_sum(nn.square(g)) for _, g in params_grads
              if g is not None]
        if not sq:
            return params_grads
        global_norm = nn.sqrt(layers.sums(sq))
        max_norm = layers.fill_constant([1], "float32", self.clip_norm)
        scale = layers.elementwise_div(
            max_norm, layers.elementwise_max(global_norm, max_norm))
        return [(p, layers.elementwise_mul(g, scale) if g is not None else g)
                for p, g in params_grads]


# legacy API names
set_gradient_clip = None
ErrorClipByValue = GradientClipByValue


# 2.0 names for the same classes (reference clip.py __all__ carries both)
ClipGradByValue = GradientClipByValue
ClipGradByNorm = GradientClipByNorm
ClipGradByGlobalNorm = GradientClipByGlobalNorm
