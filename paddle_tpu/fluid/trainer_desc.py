"""fluid.trainer_desc analog (reference trainer_desc.py over
trainer_desc.proto): pure-config descriptions of the trainer/worker pair
used by train_from_dataset.  On this stack the executor's dataset path
(fluid/executor.py train_from_dataset + distributed/trainer.py) reads
these as plain attributes — there is no proto round-trip to C++."""
from __future__ import annotations

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer", "HeterXpuTrainer", "HeterBoxWorker",
           "BoxPSTrainer"]


class TrainerDesc:
    def __init__(self):
        self._thread_num = 1
        self._device_worker = None
        self._fleet_desc = None
        self._program = None
        self._infer = False

    def set_thread(self, n):
        self._thread_num = int(n)

    def set_device_worker(self, dw):
        self._device_worker = dw

    def set_fleet_desc(self, d):
        self._fleet_desc = d

    def set_program(self, p):
        self._program = p

    def set_infer(self, infer):
        self._infer = bool(infer)

    def _desc(self):
        return {"class": type(self).__name__,
                "thread_num": self._thread_num,
                "device_worker": type(self._device_worker).__name__
                if self._device_worker else None,
                "infer": self._infer}


class MultiTrainer(TrainerDesc):
    pass


class DistMultiTrainer(TrainerDesc):
    pass


class PipelineTrainer(TrainerDesc):
    pass


class HeterXpuTrainer(TrainerDesc):
    """CPU<->accelerator heterogeneous trainer config (trainer.h:163).
    The runtime analog is the heter-style batch pipeline
    (distributed/ps/program_pass.py train_ps_pipelined)."""


class BoxPSTrainer(TrainerDesc):
    pass


class HeterBoxWorker(TrainerDesc):
    """qingshui HeterBox trainer tier (heterbox_trainer.cc:32)."""
