"""Live metrics export plane: Prometheus endpoint + JSONL snapshots.

Reference: the reference fleet scrapes metrics off running trainers
(monitor.h counters exposed to the production monitoring plane).
TPU-native, the analog is two stdlib-only surfaces over the unified
metrics registry (fluid/trace.py):

* **HTTP endpoint** (``FLAGS_metrics_port``): a daemon-thread
  ``http.server`` serving

  - ``/metrics`` — the full registry in Prometheus text exposition
    format (counters/gauges as-is, histograms as summaries with
    p50/p95/p99 quantile lines from the bucket estimates);
  - ``/goodput`` — the goodput attribution report as JSON (exact
    span-based when tracing is on, the metrics-totals estimate
    otherwise);
  - ``/healthz`` — liveness.

  Every scrape renders from a point-in-time ``registry.items()`` list
  with each instrument read under its own lock, so concurrent training
  threads never produce torn lines.  ``port=0`` binds an ephemeral port
  (tests); the bound port is on ``MetricsServer.port``.

* **JSONL snapshot writer** (``FLAGS_metrics_snapshot_path`` /
  ``FLAGS_metrics_snapshot_interval_s``): for headless runs with no
  scraper, a background thread appends one JSON line per interval —
  ``{"ts", "uptime_s", "metrics": {...}, "goodput": {...}}`` — and a
  final line at shutdown.  Lines are self-contained (json.loads
  round-trips each).

Both degrade to exact no-ops when their flags are unset: nothing is
imported on the training path, no thread starts, and the hot path keeps
its single-boolean-off contract.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from . import goodput
from . import trace

__all__ = [
    "prometheus_text", "sanitize_metric_name", "goodput_payload",
    "stats_payload", "parse_prometheus_text",
    "register_fleet_provider", "unregister_fleet_provider",
    "MetricsServer", "SnapshotWriter", "write_snapshot",
    "start_http", "stop_http", "start_snapshots", "stop_snapshots",
    "apply_flags", "shutdown",
]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _uptime_s() -> float:
    """Process wall so far, measured against the TRACE epoch (trace.py
    is imported with fluid, at process start) — not this module's import
    time, which can be hours later when the export plane is enabled
    mid-run via set_flags."""
    return trace.elapsed_us() / 1e6


def sanitize_metric_name(name: str) -> str:
    """Registry names use dots/slashes; Prometheus wants
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = _NAME_RE.sub("_", str(name))
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


def prometheus_text(registry: Optional[trace.MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus text exposition format (0.0.4).

    Counters/gauges are single samples; histograms render as summaries
    (quantile lines from the bucket-estimated p50/p95/p99 plus
    ``_sum``/``_count``).  The instrument list is snapshotted first and
    each read is lock-guarded by the instrument itself, so a scrape
    racing a training loop sees consistent individual values and never a
    torn line."""
    reg = registry or trace.metrics()
    lines = []
    for name, inst in reg.items():
        pname = sanitize_metric_name(name)
        if isinstance(inst, trace.Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {inst.value}")
        elif isinstance(inst, trace.Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(inst.value)}")
        elif isinstance(inst, trace.Histogram):
            s = inst.stats()
            lines.append(f"# TYPE {pname} summary")
            for q, key in (("0.5", "p50"), ("0.95", "p95"),
                           ("0.99", "p99")):
                lines.append(
                    f'{pname}{{quantile="{q}"}} {_fmt(s[key])}')
            lines.append(f"{pname}_sum {_fmt(s['total'])}")
            lines.append(f"{pname}_count {int(s['count'])}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:                          # NaN — Prometheus spells both
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


def goodput_payload() -> Dict[str, Any]:
    """The /goodput JSON body: exact span attribution when the trace
    plane is on, the metrics-totals estimate otherwise (both refresh
    the ``goodput.*`` gauges so the Prometheus view agrees)."""
    try:
        if trace.enabled():
            rep = goodput.update_gauges()
        else:
            rep = goodput.publish_gauges(
                goodput.from_metrics(_uptime_s()))
    except Exception as e:              # noqa: BLE001 — a scrape must
        return {"error": f"{type(e).__name__}: {e}"}       # never crash
    return rep


def _watchdog_health() -> Dict[str, Any]:
    """The SLO watchdog's state (``{"status": "ok"}`` when none runs) —
    a scrape must never crash on a half-imported forensic plane."""
    try:
        from . import watchdog
        return watchdog.health()
    except Exception as e:              # noqa: BLE001
        return {"status": "ok", "error": f"{type(e).__name__}: {e}"}


def stats_payload() -> Dict[str, Any]:
    """The compact ``/stats`` body a fleet router polls every interval:
    the watchdog verdict, serving queue depth, window p99, and the core
    serving counters in ONE small JSON payload — one cheap request per
    scrape instead of parsing the full Prometheus text.  Named engines
    (``serving.<name>.*``) appear under ``engines``; a process running
    the decode plane reports a ``decode`` block too.  Deliberately does
    NOT refresh goodput (a control-plane poll at router frequency must
    stay O(registry lookup))."""
    m = trace.metrics()
    wd = _watchdog_health()
    _gauge = trace.gauge_value          # shared defensive reads — the
    _counter = trace.counter_value      # watchdog uses the same pair

    def _p99_ms(hist_name):
        inst = m.get(hist_name)
        if isinstance(inst, trace.Histogram):
            return round(inst.percentile(0.99) * 1e3, 3)
        return 0.0

    out: Dict[str, Any] = {
        "status": wd.get("status", "ok"),
        "uptime_s": round(_uptime_s(), 3),
        # a decode replica's backlog lives on decode.queue_depth — fold
        # it in so least_queue routing sees one comparable number no
        # matter which engine kind the replica hosts
        "queue_depth": (_gauge("serving.queue_depth")
                        + _gauge("decode.queue_depth")),
        "p99_ms": _p99_ms("serving.latency_seconds"),
        "window_p99_ms": round(_gauge("watchdog.window_p99_ms"), 3),
        "requests": _counter("serving.requests"),
        "batches": _counter("serving.batches"),
        "rejected": _counter("serving.rejected"),
        "timeouts": _counter("serving.timeouts"),
    }
    # named engines: serving.<name>.queue_depth marks a namespace
    engines: Dict[str, Any] = {}
    for name, _ in m.items():
        parts = name.split(".")
        if len(parts) == 3 and parts[0] == "serving" \
                and parts[2] == "queue_depth":
            eng = parts[1]
            engines[eng] = {
                "queue_depth": _gauge(name),
                "p99_ms": _p99_ms(f"serving.{eng}.latency_seconds"),
                "requests": _counter(f"serving.{eng}.requests"),
                "batches": _counter(f"serving.{eng}.batches"),
            }
    if engines:
        out["engines"] = engines
    # self-tuning decisions (fluid/autotune.py): the fleet monitor and
    # diagnose tooling see what the tuner did from the same cheap poll.
    # Only present once the tuner has actually acted — the payload stays
    # small for untuned processes.
    try:
        from . import autotune
        at = autotune.state()
        if (at.get("enabled") or at.get("accepts") or at.get("rejects")
                or at.get("reverts") or at.get("warm_starts")):
            out["autotune"] = at
    except Exception:                   # noqa: BLE001 — a scrape never
        pass                            # crashes on a half-imported tuner
    if m.get("decode.requests") is not None:
        out["decode"] = {
            "requests": _counter("decode.requests"),
            "tokens": _counter("decode.tokens"),
            "steps": _counter("decode.steps"),
            "active_slots": _gauge("decode.active_slots"),
            "queue_depth": _gauge("decode.queue_depth"),
            # paged-KV / prefix-cache / speculative instruments (0 when
            # the engine runs the dense path — cheap, stable schema)
            "kv_pages_in_use": _gauge("decode.kv_pages_in_use"),
            "kv_page_pool_free": _gauge("decode.kv_page_pool_free"),
            "prefix_hits": _counter("decode.prefix_hits"),
            "prefix_evictions": _counter("decode.prefix_evictions"),
            "prefix_drops": _counter("decode.prefix_drops"),
            "spec_proposed": _counter("decode.spec_proposed"),
            "spec_accepted": _counter("decode.spec_accepted"),
        }
    # per-device HBM truth (fluid/device_stats.py): the worst resident
    # executable's per-shard peak + the widest mesh it compiled for —
    # how the router and autotuner see that a sharded replica fits a
    # batch one chip could not hold (FLAGS_device_cost_analysis)
    _suffix = ".per_device_peak_bytes"
    peak = 0.0
    mesh_devices = 1
    for name, _inst in m.items():
        if name.startswith("xla.mem.exe.") and name.endswith(_suffix):
            v = _gauge(name)
            label = name[len("xla.mem.exe."):-len(_suffix)]
            md = _gauge(f"xla.mem.exe.{label}.mesh_devices")
            if v > peak:
                peak = v
            if md > mesh_devices:
                mesh_devices = int(md)
    if peak > 0:
        out["hbm"] = {"per_device_peak_bytes": int(peak),
                      "mesh_devices": mesh_devices}
    # transport-robustness truth (docs/robustness.md): checksum-caught
    # corruptions, retries, deadline sheds, and injected faults — how a
    # chaos drill audits "every corruption detected" across the fleet
    # without reaching into replica processes
    rpc = {k: _counter(f"rpc.{k}")
           for k in ("corrupt_frames", "oversized_frames", "retries",
                     "reconnects", "deadline_shed", "dedup_hits")}
    if any(rpc.values()):
        out["rpc"] = rpc
    injected = _counter("fault.injected")
    if injected:
        out["faults"] = {"injected": injected}
        for k in ("latency", "drop", "reset", "partition", "corrupt",
                  "trickle"):
            n = _counter(f"fault.{k}")
            if n:
                out["faults"][k] = n
    # PS-tier health: worker liveness (start_heartbeat_monitor), the
    # sharded tier's storage/latency-hiding instruments, and per-shard
    # breaker state — surfaced in the compact payload so the fleet
    # aggregator and chaos drills see the PS plane without a full
    # /metrics scrape
    ps = {"dead_workers": int(_gauge("ps.dead_workers")),
          "worker_deaths": _counter("ps.worker_deaths"),
          "shards_up": int(_gauge("ps.shards_up")),
          "breaker_open": int(_gauge("ps.breaker_open")),
          "shard_restarts": _counter("ps.shard_restarts"),
          "hot_rows": int(_gauge("ps.hot_rows")),
          "cold_rows": int(_gauge("ps.cold_rows")),
          "evictions": _counter("ps.evictions"),
          "promotions": _counter("ps.promotions"),
          "prefetch_hits": _counter("ps.prefetch_hits"),
          "prefetch_misses": _counter("ps.prefetch_misses"),
          "prefetch_patched": _counter("ps.prefetch_patched"),
          "fence_stalls": _counter("ps.fence_stalls"),
          "outstanding_pushes": int(_gauge("ps.outstanding_pushes")),
          "snapshots": _counter("ps.snapshots"),
          "restores": _counter("ps.restores"),
          "wal_records": _counter("ps.wal_records"),
          "pull_wait_p99_ms": _p99_ms("ps.pull_wait_seconds")}
    if any(ps.values()):
        out["ps"] = ps
    return out


def parse_prometheus_text(text: str) -> List[Dict[str, Any]]:
    """Parse the exposition format :func:`prometheus_text` renders back
    into families: ``[{"name", "type", "samples": [(sample_name,
    labels_dict, value), ...]}, ...]``.  Summary families carry their
    quantile lines plus ``_sum``/``_count`` samples.  The fleet
    aggregator uses this to re-label and roll up replica scrapes;
    unknown/malformed lines are skipped, never fatal."""
    fams: List[Dict[str, Any]] = []
    by_name: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                fam = {"name": parts[2], "type": parts[3], "samples": []}
                fams.append(fam)
                by_name[parts[2]] = fam
            continue
        try:
            sample, value_s = line.rsplit(None, 1)
            value = float(value_s)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        sname = sample
        if sample.endswith("}") and "{" in sample:
            sname, _, lab = sample.partition("{")
            for item in lab[:-1].split(","):
                if "=" in item:
                    k, _, v = item.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
        fam = by_name.get(sname)
        if fam is None and (sname.endswith("_sum")
                            or sname.endswith("_count")):
            fam = by_name.get(sname.rsplit("_", 1)[0])
        if fam is None:
            fam = {"name": sname, "type": "untyped", "samples": []}
            fams.append(fam)
            by_name[sname] = fam
        fam["samples"].append((sname, labels, value))
    return fams


# -- fleet provider ----------------------------------------------------------
# A ServingFleet registers its FleetMetricsAggregator here; the handler
# then serves the aggregated views on /fleet/metrics + /fleet/stats.
# One provider at a time (latest registration wins).
_fleet_provider = None


def register_fleet_provider(provider) -> None:
    """``provider`` must expose ``fleet_metrics_text() -> str`` and
    ``fleet_stats() -> dict``."""
    global _fleet_provider
    _fleet_provider = provider


def unregister_fleet_provider(provider) -> None:
    global _fleet_provider
    if _fleet_provider is provider:
        _fleet_provider = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-metrics/1.0"
    protocol_version = "HTTP/1.1"

    def do_GET(self):                   # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path in ("/", "/metrics"):
            # refresh the goodput gauges so a plain Prometheus scrape
            # carries goodput_ratio without a second endpoint, and the
            # trace-drop gauge so attribution blindness is scrapeable
            # live (not just in export metadata at run end)
            goodput_payload()
            trace.metrics().gauge("trace.dropped_events").set(
                trace.dropped_count())
            body = prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/goodput":
            body = json.dumps(goodput_payload(), default=str).encode()
            ctype = "application/json"
        elif path == "/healthz":
            # liveness + the SLO watchdog's verdict: a fleet router
            # reads the status word (ok / stalled / breached) as its
            # ejection signal; /watchdog has the full state
            body = (_watchdog_health().get("status", "ok") + "\n").encode()
            ctype = "text/plain"
        elif path == "/watchdog":
            body = json.dumps(_watchdog_health(), default=str).encode()
            ctype = "application/json"
        elif path == "/stats":
            # the fleet router's control-plane poll: verdict + queue
            # depth + window p99 in one compact payload (docs/serving.md
            # "Serving fleet")
            body = json.dumps(stats_payload(), default=str).encode()
            ctype = "application/json"
        elif path == "/bundle":
            # the process's diagnostic-bundle document, built on demand
            # — what a fleet parent embeds in its incident bundle (HTTP
            # rather than RPC: no frame-size cap, and a wedged engine's
            # RPC plane may be the very thing being diagnosed)
            reason = "fetch"
            if "reason=" in self.path:
                reason = self.path.split("reason=", 1)[1].split("&")[0] \
                    or "fetch"
            try:
                from . import watchdog
                doc = watchdog.build_bundle_doc(reason)
            except Exception as e:      # noqa: BLE001 — a diagnostic
                doc = {"error": f"{type(e).__name__}: {e}"}  # never 500s
            body = json.dumps(doc, default=str).encode()
            ctype = "application/json"
        elif path in ("/fleet/metrics", "/fleet/stats"):
            p = _fleet_provider
            if p is None:
                body = b"no fleet registered\n"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                if path == "/fleet/metrics":
                    body = p.fleet_metrics_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    body = json.dumps(p.fleet_stats(),
                                      default=str).encode()
                    ctype = "application/json"
            except Exception as e:      # noqa: BLE001 — a scrape must
                body = json.dumps(                         # never crash
                    {"error": f"{type(e).__name__}: {e}"}).encode()
                ctype = "application/json"
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):       # scrapes are not stderr news
        pass


class MetricsServer:
    """The /metrics HTTP surface on a daemon thread.  ``port=0`` binds
    ephemeral; read the real one from ``.port``."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http",
            daemon=True)
        self._thread.start()
        trace.metrics().gauge("metrics.export_port").set(self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


def write_snapshot(path: str) -> Dict[str, Any]:
    """Append one self-contained JSONL metrics snapshot (histograms as
    their full stats dicts incl. p50/p95/p99) and return the row."""
    trace.metrics().gauge("trace.dropped_events").set(
        trace.dropped_count())
    row = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "uptime_s": round(_uptime_s(), 3),
        "metrics": trace.metrics().snapshot(),
        "goodput": goodput_payload(),
        "watchdog": _watchdog_health(),
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(row, default=str) + "\n")
    return row


class SnapshotWriter:
    """Background JSONL snapshot loop for headless runs: one line every
    ``interval_s``, plus a final line at ``stop()`` so short runs always
    leave at least one record."""

    def __init__(self, path: str, interval_s: float = 60.0):
        self.path = str(path)
        self.interval_s = max(0.01, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="metrics-snapshot", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self._write()

    def _write(self):
        try:
            write_snapshot(self.path)
        except Exception:               # noqa: BLE001 — a full disk must
            trace.metrics().counter(    # not kill training
                "metrics.snapshot_errors").inc()

    def stop(self) -> None:
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=10)
            self._write()               # terminal snapshot


# ---------------------------------------------------------------------------
# module-level lifecycle (flag-driven)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_server: Optional[MetricsServer] = None
_server_flagged = False             # started by apply_flags (vs direct)
_writer: Optional[SnapshotWriter] = None
_writer_flagged = False
_atexit_registered = False


def _register_atexit():
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        import atexit
        atexit.register(shutdown)


def start_http(port: Optional[int] = None,
               host: Optional[str] = None) -> MetricsServer:
    """Start (or return) the process metrics server.  ``port=None``
    reads FLAGS_metrics_port (and marks the server flag-managed, so
    later ``apply_flags`` reconciliation may stop/restart it — a server
    started with an explicit port is left alone).  ``host`` defaults to
    FLAGS_metrics_host (127.0.0.1: the registry names executables and
    checkpoints — exposing it beyond the host is an explicit opt-in via
    FLAGS_metrics_host=0.0.0.0)."""
    global _server, _server_flagged
    from . import core
    with _lock:
        if _server is not None:
            return _server
        flagged = port is None
        if port is None:
            port = int(core.get_flag("metrics_port", 0) or 0)
        if host is None:
            host = str(core.get_flag("metrics_host", "127.0.0.1")
                       or "127.0.0.1")
        _server = MetricsServer(int(port), host=host)
        _server_flagged = flagged
        _register_atexit()
        return _server


def stop_http() -> None:
    global _server, _server_flagged
    with _lock:
        srv, _server = _server, None
        _server_flagged = False
    if srv is not None:
        srv.stop()
        trace.metrics().gauge("metrics.export_port").set(0)


def start_snapshots(path: Optional[str] = None,
                    interval_s: Optional[float] = None) -> SnapshotWriter:
    """Start (or return) the process snapshot writer.  ``path=None``
    reads the flags and marks the writer flag-managed (like
    :func:`start_http`: only flag-started surfaces are reconciled by
    ``apply_flags``; a writer started with an explicit path belongs to
    its caller)."""
    global _writer, _writer_flagged
    with _lock:
        if _writer is not None:
            return _writer
        flagged = path is None
        if path is None or interval_s is None:
            from . import core
            path = path or core.get_flag("metrics_snapshot_path")
            if interval_s is None:
                interval_s = float(
                    core.get_flag("metrics_snapshot_interval_s", 60.0)
                    or 60.0)
        if not path:
            raise ValueError("start_snapshots needs a path "
                             "(FLAGS_metrics_snapshot_path)")
        _writer = SnapshotWriter(str(path), interval_s)
        _writer_flagged = flagged
        _register_atexit()
        return _writer


def stop_snapshots() -> None:
    global _writer, _writer_flagged
    with _lock:
        w, _writer = _writer, None
        _writer_flagged = False
    if w is not None:
        w.stop()


def apply_flags() -> None:
    """Reconcile the running surfaces with the current flags — called
    from ``fluid.core.set_flags`` and at import when the FLAGS_metrics_*
    env vars are set.  Unset flags stop the corresponding surface, so
    ``set_flags({"FLAGS_metrics_port": 0})`` is the off switch.  Only
    flag-started servers are reconciled: one started programmatically
    (``start_http(port=...)``, e.g. on an ephemeral port in tests)
    belongs to its caller and is never stopped from here."""
    from . import core
    port = int(core.get_flag("metrics_port", 0) or 0)
    host = str(core.get_flag("metrics_host", "127.0.0.1") or "127.0.0.1")
    path = core.get_flag("metrics_snapshot_path")
    interval = float(core.get_flag("metrics_snapshot_interval_s", 60.0)
                     or 60.0)
    with _lock:
        server, flagged = _server, _server_flagged
        writer, w_flagged = _writer, _writer_flagged
    if server is None:
        if port:
            start_http()            # port=None: reads flags, stays
    elif flagged:                   # flag-managed for later reconciles
        if not port or server.port != port or server.host != host:
            stop_http()
            if port:
                start_http()
    if writer is None:
        if path:
            start_snapshots()       # path=None: reads flags, stays
    elif w_flagged:
        if not path or writer.path != str(path) \
                or writer.interval_s != interval:
            stop_snapshots()
            if path:
                start_snapshots()


def shutdown() -> None:
    """Stop both surfaces (atexit hook; the writer flushes a final
    snapshot)."""
    try:
        stop_snapshots()
    finally:
        stop_http()
