"""Program -> Graphviz .dot rendering (BuildStrategy.debug_graphviz_path).

Reference: framework/ir/graph_viz_pass.cc — every pass stage can leave a
.dot of the graph it saw.  Ops render as boxes, vars as ellipses
(persistables shaded), edges follow the named slots.  Pure string
generation: no graphviz binary required, the files load in any dot
viewer."""
from __future__ import annotations

import re
from typing import List

__all__ = ["program_to_dot", "dump_program"]

_ID_RE = re.compile(r"[^A-Za-z0-9_]")


def _vid(bidx: int, name: str) -> str:
    return f"v{bidx}_{_ID_RE.sub('_', name)}"


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def program_to_dot(program, title: str = "program") -> str:
    lines: List[str] = [
        f'digraph "{_esc(title)}" {{',
        "  rankdir=TB;",
        '  node [fontsize=10, fontname="Helvetica"];',
    ]
    for b in program.blocks:
        lines.append(f"  subgraph cluster_block{b.idx} {{")
        lines.append(f'    label="block {b.idx}";')
        declared = set()

        def var_node(name: str) -> str:
            nid = _vid(b.idx, name)
            if nid not in declared:
                declared.add(nid)
                v = b._find_var_recursive(name)
                style = ', style=filled, fillcolor="lightgrey"' \
                    if (v is not None and v.persistable) else ""
                shape = f" {list(v.shape)}" if (
                    v is not None and v.shape is not None) else ""
                lines.append(
                    f'    {nid} [label="{_esc(name)}{_esc(shape)}", '
                    f'shape=ellipse{style}];')
            return nid

        for i, op in enumerate(b.ops):
            oid = f"op{b.idx}_{i}"
            lines.append(
                f'    {oid} [label="{_esc(op.type)}", shape=box, '
                f'style=filled, fillcolor="lightblue"];')
            for slot, names in op.inputs.items():
                for n in names:
                    lines.append(
                        f'    {var_node(n)} -> {oid} '
                        f'[label="{_esc(slot)}", fontsize=8];')
            for slot, names in op.outputs.items():
                for n in names:
                    lines.append(
                        f'    {oid} -> {var_node(n)} '
                        f'[label="{_esc(slot)}", fontsize=8];')
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines) + "\n"


def dump_program(program, path: str, title: str = None) -> str:
    with open(path, "w") as f:
        f.write(program_to_dot(program, title or path))
    return path
