"""Inference-preset passes: batch-norm folding into the preceding
conv/fc weights.

Reference: the OptimizeInferenceProgram pass list that AnalysisPredictor
runs over a loaded model (paddle/fluid/inference/analysis/, notably
conv_bn_fuse_pass.cc / fc_fuse_pass.cc).  TPU-native twist: folding BN
into the producer's weights is a *value* rewrite, not just an IR
rewrite — the folded weights are computed host-side from the scope's
parameter values and stored under fresh names, so the training scope's
originals are never touched and a freeze can share a live training
scope safely.

The `inference_passes()` preset is the freeze pipeline
(serving/freeze.py, docs/serving.md):

    constant_fold -> fold_batch_norm -> fuse_elewise_add_act
    -> fuse_bn_act -> prune_identity -> dce (fetch-seeded)

BN folding runs before the fusions so a foldable BN disappears into the
conv/fc entirely (zero extra ops at serving time); an *unfoldable* BN
(training-mode stats, multi-consumer edge, missing scope values) is left
for fuse_bn_act to at least pair with its activation.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..framework import _op_reads
from .core import Pass, PassContext, register_pass, create_pass
from .pattern import writer_index as _writer_idxs

__all__ = ["FoldBatchNormPass", "inference_passes",
           "INFERENCE_PASS_NAMES"]

# the freeze preset, in order (docs/passes.md catalog)
INFERENCE_PASS_NAMES = ("constant_fold", "fold_batch_norm",
                        "fuse_elewise_add_act", "fuse_bn_act",
                        "prune_identity", "dce")


def inference_passes(scope=None) -> List[Pass]:
    """Instantiate the inference/freeze pass preset.  ``scope`` holds the
    parameter values fold_batch_norm reads (defaults to the ambient
    global scope at apply time)."""
    out = []
    for name in INFERENCE_PASS_NAMES:
        kw = {"scope": scope} if name == "fold_batch_norm" else {}
        out.append(create_pass(name, **kw))
    return out


def _consumers(block, name):
    return [op for op in block.ops if name in _op_reads(block, op)]


@register_pass
class FoldBatchNormPass(Pass):
    """Fold an inference-mode ``batch_norm`` into the preceding
    conv2d/mul (fc) weights (conv_bn_fuse_pass.cc analog).

    ``y = (z - mean) * rsqrt(var + eps) * gamma + beta`` with
    ``z = W·x (+ b0)`` becomes ``W' = W * k`` (per out-channel
    ``k = gamma * rsqrt(var + eps)``) and ``b' = (b0 - mean) * k + beta``
    — the BN op vanishes and the bias add absorbs it.  Folded weight
    values are computed in float64 and stored in the scope under fresh
    ``@bn_fold`` names; the original params stay untouched (they may be
    live training state in a shared scope).

    Folds only when: the BN runs in inference mode (op-level ``is_test``
    / ``use_global_stats`` or the program's ``is_test`` hint), the
    conv/mul -> (bias add ->) bn chain is single-writer/single-consumer,
    none of the intermediate edges are protected (fetch targets,
    persistables, feeds), and every needed param value is in the scope.
    Anything else is skipped, never broken.
    """

    name = "fold_batch_norm"
    writes = frozenset({"ops", "vars"})

    # producer op -> (weight slot, out slot, weight out-channel axis fn)
    _PRODUCERS = {
        "conv2d": ("Filter", "Output", lambda w: 0),
        "mul": ("Y", "Out", lambda w: w.ndim - 1),
    }

    def __init__(self, scope=None, **options):
        super().__init__(**options)
        self.scope = scope

    def _scope(self):
        if self.scope is not None:
            return self.scope
        from ..core import global_scope
        return global_scope()

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        folded = 0
        for _ in range(len(block.ops) + 16):
            if not self._fold_one(block, ctx):
                break
            folded += 1
        return {"bn_folded": folded}

    # -- helpers -------------------------------------------------------------
    def _value(self, scope, name) -> Optional[np.ndarray]:
        v = scope.find_var(name)
        return None if v is None else np.asarray(v)

    def _is_inference_bn(self, block, op) -> bool:
        if op.type != "batch_norm":
            return False
        if op.attrs.get("is_test") or op.attrs.get("use_global_stats"):
            return True
        return bool(block.program._hints.get("is_test"))

    def _single_internal_edge(self, block, ctx, name, consumer) -> bool:
        """``name`` is written once, read only by ``consumer``, and not
        protected (fetch target / persistable / feed)."""
        if ctx.is_protected(block, name):
            return False
        if len(_writer_idxs(block, name)) != 1:
            return False
        return all(c is consumer for c in _consumers(block, name))

    def _writer(self, block, name):
        idx = _writer_idxs(block, name)
        return block.ops[idx[0]] if len(idx) == 1 else None

    def _fresh_param(self, block, scope, base, value):
        """Store ``value`` under a fresh persistable var; the original
        param keeps its value (shared-scope safety)."""
        from ..framework import unique_name
        name = unique_name(base + "@bn_fold")
        dtype = value.dtype.name
        block.create_var(name=name, shape=list(value.shape), dtype=dtype,
                         persistable=True)
        scope.set_var(name, value)
        return name

    # -- the fold ------------------------------------------------------------
    def _fold_one(self, block, ctx: PassContext) -> bool:
        scope = self._scope()
        for bn in list(block.ops):
            if not self._is_inference_bn(block, bn):
                continue
            if self._try_fold(block, ctx, scope, bn):
                return True
        return False

    def _try_fold(self, block, ctx, scope, bn) -> bool:
        x_name = (bn.inputs.get("X") or [None])[0]
        y_name = (bn.outputs.get("Y") or [None])[0]
        if x_name is None or y_name is None:
            return False
        if not self._single_internal_edge(block, ctx, x_name, bn):
            return False
        if len(_writer_idxs(block, y_name)) != 1:
            return False

        # resolve the producer chain: conv/mul [-> elementwise_add(bias)]
        writer = self._writer(block, x_name)
        if writer is None:
            return False
        add_op = None
        if writer.type == "elementwise_add":
            b_name = (writer.inputs.get("Y") or [None])[0]
            z_name = (writer.inputs.get("X") or [None])[0]
            bv = block._find_var_recursive(b_name) if b_name else None
            if bv is None or not bv.persistable or z_name is None:
                return False
            add_op = writer
            if not self._single_internal_edge(block, ctx, z_name, add_op):
                return False
            writer = self._writer(block, z_name)
            if writer is None:
                return False
        if writer.type not in self._PRODUCERS:
            return False
        w_slot, out_slot, ch_axis_of = self._PRODUCERS[writer.type]
        w_name = (writer.inputs.get(w_slot) or [None])[0]
        if w_name is None:
            return False
        wv = block._find_var_recursive(w_name)
        if wv is None or not wv.persistable:
            return False
        if any(w_name in op.output_arg_names for op in block.ops):
            return False             # weight rewritten at runtime: unsafe

        # param values (all must be resident in the scope)
        names = {k: (bn.inputs.get(k) or [None])[0]
                 for k in ("Scale", "Bias", "Mean", "Variance")}
        if any(n is None for n in names.values()):
            return False
        vals = {k: self._value(scope, n) for k, n in names.items()}
        w = self._value(scope, w_name)
        if w is None or any(v is None for v in vals.values()):
            return False
        b0_name = (add_op.inputs.get("Y") or [None])[0] if add_op else None
        b0 = self._value(scope, b0_name) if b0_name else None
        if add_op is not None and b0 is None:
            return False

        eps = float(bn.attrs.get("epsilon", 1e-5))
        k = (vals["Scale"].astype(np.float64)
             / np.sqrt(vals["Variance"].astype(np.float64) + eps))
        if k.ndim != 1:
            return False
        ch_axis = ch_axis_of(w)
        if w.shape[ch_axis] != k.shape[0]:
            return False
        shape = [1] * w.ndim
        shape[ch_axis] = k.shape[0]
        w_new = (w.astype(np.float64) * k.reshape(shape)).astype(w.dtype)
        b_prev = (b0.astype(np.float64) if b0 is not None
                  else np.zeros(k.shape[0]))
        b_new = ((b_prev - vals["Mean"].astype(np.float64)) * k
                 + vals["Bias"].astype(np.float64)).astype(
                     vals["Bias"].dtype)

        # splice: producer reads the folded weight; the bias add absorbs
        # the BN and writes the BN's output name; the BN op vanishes
        w_folded = self._fresh_param(block, scope, w_name, w_new)
        b_folded = self._fresh_param(block, scope,
                                     b0_name or (w_name + "_b"), b_new)
        writer.inputs[w_slot] = [w_folded]
        if add_op is not None:
            add_op.inputs["Y"] = [b_folded]
            add_op.outputs["Out"] = [y_name]
        else:
            fmt = bn.attrs.get("data_layout", "NCHW")
            x_var = block._find_var_recursive(x_name)
            ndim = len(x_var.shape) if (x_var is not None
                                        and x_var.shape) else 2
            axis = (1 if (writer.type == "conv2d" and fmt == "NCHW")
                    else ndim - 1)
            block._insert_op(
                block.ops.index(bn), "elementwise_add",
                inputs={"X": [x_name], "Y": [b_folded]},
                outputs={"Out": [y_name]},
                attrs={"axis": axis,
                       "op_role": bn.attrs.get("op_role", 0)})
        block._remove_op(block.ops.index(bn))
        return True
