"""paddle_tpu.fluid.passes — the Program-IR pass framework.

Reference: paddle/fluid/framework/ir/ (Pass/PassRegistry over ir::Graph,
134 registered passes) + build_strategy.cc wiring knobs to pass lists.
Here passes rewrite the Program/Block IR in place through the
version-bumping Block mutators, CompiledProgram applies the
BuildStrategy-selected pipeline before the Executor caches the lowered
function, and every pass run lands in the observability plane
(``pass::<name>`` spans, ``pass.<name>.*`` counters).

See docs/passes.md for the catalog and how to register a custom pass.
"""
from .core import (Pass, PassContext, PassRegistry, PassPipeline,
                   register_pass, create_pass, get_pass_names)
from .pattern import (Pattern, PVar, POp, Match, PatternRewritePass)
from .graphviz import program_to_dot, dump_program
from . import builtin  # registers the built-in pass catalog
from . import amp      # registers amp_bf16 + prune_redundant_casts
from . import inference as inference_preset  # registers fold_batch_norm
from . import kernel_tier  # registers the Pallas kernel-tier passes
from .builtin import passes_for_build_strategy
from .amp import AmpBf16Pass, PruneRedundantCastsPass
from .inference import (FoldBatchNormPass, inference_passes,
                        INFERENCE_PASS_NAMES)
from .kernel_tier import (FuseAttentionPass, FuseSparseEmbeddingPass,
                          FuseOptimizerPass)

__all__ = [
    "Pass", "PassContext", "PassRegistry", "PassPipeline",
    "register_pass", "create_pass", "get_pass_names",
    "Pattern", "PVar", "POp", "Match", "PatternRewritePass",
    "program_to_dot", "dump_program", "passes_for_build_strategy",
    "AmpBf16Pass", "PruneRedundantCastsPass",
    "FoldBatchNormPass", "inference_passes", "INFERENCE_PASS_NAMES",
    "FuseAttentionPass", "FuseSparseEmbeddingPass", "FuseOptimizerPass",
]
