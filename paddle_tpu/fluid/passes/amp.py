"""bf16 mixed precision as registered Program-IR passes.

Reference: python/paddle/fluid/contrib/mixed_precision/fp16_utils.py
`rewrite_program` (cast insertion per black/white lists over the
ProgramDesc) + decorator.py:253 `decorate`.  TPU-native: the fast dtype is
bfloat16 (MXU runs bf16 matmuls at ~2x fp32 FLOPs with f32 accumulation
via ``preferred_element_type`` in the matmul lowerings / XLA's bf16-conv
accumulator), the exponent range matches fp32 so loss scaling is optional,
and the rewrite is two first-class passes in the PR-3 framework instead of
a side-door program mutation:

* ``amp_bf16`` — a dtype-dataflow rewriter.  Walks the global block
  tracking the *runtime* dtype of every value (var metadata only seeds the
  walk), inserts a fresh ``cast`` per consumed edge: white-list ops get
  bf16 inputs, black-list ops (reductions, softmax, losses, grad ``sum``
  fan-in) get fp32 back, gray ops follow their inputs (a bf16 operand
  pulls fp32 float operands down so the bias-add after a bf16 matmul never
  promotes the activation back — 2x HBM traffic otherwise).  Grad halves:
  each forward op is paired with its ``generic_grad`` (the vjp recompute
  must see the SAME input dtypes as the forward), the ``I_<slot>`` mirrors
  get their own casts, and ``GI_<slot>`` cotangents are cast back to the
  original var dtype — so parameter gradients land in fp32 no matter how
  deep the bf16 region is, and multi-step training is numerically stable.
* ``prune_redundant_casts`` — the cleanup contract that lets amp_bf16 stay
  a dumb local rewriter: removes identity casts (dataflow dtype == target),
  dedupes identical casts of one var, collapses lossless cast chains
  (bf16->f32->bf16 is the identity; f32->bf16->f32 is NOT — it rounds, and
  cancelling it would change fetches), and finally *folds* surviving
  amp-inserted casts into their consumer ops as a ``__amp_cast__`` attr
  the executor applies inline (run_block_ops) — the cast disappears from
  the op stream entirely: one less host dispatch per trace, one less op in
  the jaxpr, same arithmetic.

Observability: ``amp.ops_cast`` / ``amp.casts_pruned`` counters plus a
program dtype histogram (``amp.dtype_hist.<dtype>`` gauges) on the trace
plane, and the usual per-pass spans/counters from the pipeline.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import trace
from ..framework import (Operator, unique_name, _op_reads,
                         _OPTIMIZER_OP_TYPES)
from .core import Pass, PassContext, register_pass
from .pattern import writer_index as _writer_idxs

__all__ = ["AmpBf16Pass", "PruneRedundantCastsPass"]

# ops the rewriter never touches: plumbing, control flow (sub-block
# captures can't be re-aliased safely), the loss-scaling machinery, and
# the optimizer update tail (master weights own that precision story)
_SKIP_TYPES = frozenset({
    "feed", "fetch", "cast", "fill_constant", "assign", "while",
    "conditional_block", "select_input", "select_output", "recurrent",
    "py_func", "print", "check_finite_and_unscale", "update_loss_scaling",
    "generic_grad",
}) | _OPTIMIZER_OP_TYPES

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
_LOW_DTYPES = ("float16", "bfloat16")


def _promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a is None:
        return b
    if b is None:
        return a
    import jax.numpy as jnp
    try:
        return str(jnp.promote_types(a, b))
    except TypeError:
        return a


@register_pass
class AmpBf16Pass(Pass):
    """Insert casts so white-list ops consume bf16 and black-list ops
    fp32, with the grad halves kept dtype-consistent (see module
    docstring).  Deliberately local: one fresh cast per consumed edge —
    global cleanup is prune_redundant_casts' job."""

    name = "amp_bf16"
    writes = frozenset({"ops", "vars", "attrs"})

    def __init__(self, dtype: str = "bfloat16", custom_white_list=None,
                 custom_black_list=None, **options):
        super().__init__(**options)
        self.dtype = str(dtype)
        self._custom_white = frozenset(custom_white_list or ())
        self._custom_black = frozenset(custom_black_list or ())
        self._warned: set = set()

    # -- grad pairing -------------------------------------------------------
    @staticmethod
    def _pair_grads(block) -> Dict[int, List[Operator]]:
        """id(forward op) -> its generic_grad ops: the grad's I_<slot>
        mirrors must equal the forward's input lists (how append_backward
        builds them), so the vjp recompute sees the forward's exact
        values."""
        pairs: Dict[int, List[Operator]] = {}
        grads = [op for op in block.ops if op.type == "generic_grad"]
        used: set = set()
        for f in block.ops:
            if f.type == "generic_grad":
                continue
            for g in grads:
                if id(g) in used or g.attrs.get("fwd_type") != f.type:
                    continue
                if all(g.inputs.get("I_" + s) == list(ns)
                       for s, ns in f.inputs.items()):
                    pairs.setdefault(id(f), []).append(g)
                    used.add(id(g))
                    break
        return pairs

    # -- the walk -----------------------------------------------------------
    def apply(self, program, ctx: PassContext) -> Dict[str, int]:
        block = program.global_block()
        stats = self._apply_block(block, ctx)
        program._amp_enabled = True
        program._amp_dtype = self.dtype
        program._hints["amp_dtype"] = self.dtype
        trace.metrics().counter("amp.ops_cast").inc(
            stats.get("casts_inserted", 0))
        # program dtype histogram: how much of the value plane actually
        # runs low-precision after the rewrite
        hist: Dict[str, int] = {}
        for v in block.vars.values():
            d = v.dtype or "unknown"
            hist[d] = hist.get(d, 0) + 1
        for d, n in hist.items():
            trace.metrics().gauge(f"amp.dtype_hist.{d}").set(n)
        return stats

    def _apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        env: Dict[str, str] = {}     # value name -> runtime dtype

        def dt_of(name: str) -> Optional[str]:
            if name in env:
                return env[name]
            v = block._find_var_recursive(name)
            return v.dtype if v is not None else None

        pairs = self._pair_grads(block)
        inserted = rewritten = 0
        for op in list(block.ops):
            role = int(op.attrs.get("op_role", 0) or 0)
            if op.type in _SKIP_TYPES or role != 0:
                self._flow_through(block, op, env, dt_of)
                continue
            kind = self._classify(op.type)
            in_dts = [dt_of(n) for n in op.input_arg_names]
            float_in = [d for d in in_dts if d in _FLOAT_DTYPES]
            target = None
            if kind == "white":
                target = self.dtype
                from_dts = ("float32", "float64")
            elif kind in ("black", "fp32", "unclassified"):
                if kind == "unclassified" and op.type not in self._warned:
                    # registry-audit escape hatch: a matmul/conv-family op
                    # nobody classified runs fp32, loudly, once
                    self._warned.add(op.type)
                    trace.metrics().counter("amp.unclassified_ops").inc()
                    trace.instant("amp_unclassified_op", cat="pass",
                                  args={"op": op.type})
                    import sys
                    print(f"paddle_tpu: WARNING: AMP found unclassified "
                          f"matmul/conv-family op '{op.type}' — running "
                          f"it fp32; add it to amp/lists.py "
                          f"WHITE_OPS/FP32_FAMILY_OPS", file=sys.stderr)
                if any(d in _LOW_DTYPES for d in float_in):
                    target = "float32"
                    from_dts = _LOW_DTYPES
            else:                                   # gray: follow inputs
                if (self.dtype in float_in
                        and any(d in ("float32", "float64")
                                for d in float_in)):
                    target = self.dtype
                    from_dts = ("float32", "float64")
            if target is not None:
                n_cast = self._rewrite_op(block, op, target, from_dts,
                                          env, dt_of, pairs)
                inserted += n_cast
                rewritten += 1 if n_cast else 0
            self._flow_through(block, op, env, dt_of,
                               forced=self.dtype if kind == "white"
                               else target)
        return {"casts_inserted": inserted, "ops_rewritten": rewritten}

    def _classify(self, op_type: str) -> str:
        # single source of truth for the taxonomy (and the union
        # semantics of the custom lists): amp.lists.classify
        from ...amp.lists import classify
        return classify(op_type, white=self._custom_white,
                        black=self._custom_black)

    def _flow_through(self, block, op, env, dt_of, forced=None) -> None:
        """Update the dtype env for ``op``'s outputs: forced compute dtype
        for rewritten ops, promotion of float inputs otherwise, var
        metadata as the fallback."""
        if op.type == "cast":
            for n in op.output_arg_names:
                env[n] = str(op.attrs.get("out_dtype", "float32"))
            return
        if op.type == "fill_constant":
            for n in op.output_arg_names:
                env[n] = str(op.attrs.get("dtype", "float32"))
            return
        flo = None
        for n in op.input_arg_names:
            d = dt_of(n)
            if d in _FLOAT_DTYPES:
                flo = _promote(flo, d)
        out_dt = forced or flo
        for n in op.output_arg_names:
            v = block._find_var_recursive(n)
            meta = v.dtype if v is not None else None
            if meta is not None and meta not in _FLOAT_DTYPES:
                env[n] = meta               # int/bool outputs keep dtype
                continue
            if out_dt is not None:
                env[n] = out_dt
                # keep IR metadata honest for downstream passes/fetch
                if v is not None and not v.persistable:
                    v.dtype = out_dt

    def _rewrite_op(self, block, op, target, from_dts, env, dt_of,
                    pairs) -> int:
        """Cast ``op``'s float inputs with dtypes in ``from_dts`` to
        ``target``; mirror onto paired generic_grads (fresh I_ casts, GI_
        cast-backs)."""
        n_cast = 0
        grads = pairs.get(id(op), [])
        for slot in list(op.inputs):
            names = op.inputs[slot]
            for j, name in enumerate(names):
                d = dt_of(name)
                if d not in from_dts or d == target:
                    continue
                if name in op.output_arg_names:
                    continue        # in-place state slot: never re-alias
                c = self._insert_cast(block, op, name, target)
                names[j] = c
                env[c] = target
                n_cast += 1
                for g in grads:
                    n_cast += self._rewrite_grad(block, g, slot, j, name,
                                                 c, d, target, env)
        if n_cast:
            block.program._bump_version()   # input rewires alone must
        return n_cast                       # never leave a stale digest

    def _insert_cast(self, block, before_op, name, to_dtype,
                     role: int = None) -> str:
        src = block._find_var_recursive(name)
        c = unique_name(f"{name}@amp.{to_dtype}")
        idx = block.ops.index(before_op)
        block._insert_op(
            idx, "cast", inputs={"X": [name]}, outputs={"Out": [c]},
            attrs={"out_dtype": to_dtype, "amp_inserted": True,
                   "op_role": int(before_op.attrs.get("op_role", 0)
                                  if role is None else role)})
        cv = block._find_var_recursive(c)
        cv.dtype = to_dtype
        if src is not None:
            if cv.shape is None:
                cv.shape = src.shape
            # differentiable-through (NOT stop_gradient): in the
            # pre-backward decorate flow append_backward must chain grads
            # through these casts, mirroring the source's own setting
            cv.stop_gradient = bool(src.stop_gradient)
        return c

    def _rewrite_grad(self, block, g, slot, j, name, cast_name, orig_dt,
                      target, env) -> int:
        """Keep a paired generic_grad dtype-consistent with its rewritten
        forward: fresh cast for the I_<slot> mirror (prune dedupes it
        against the forward's), and the GI_<slot> cotangent cast back to
        the original var dtype so downstream grad consumers (fan-in sum,
        the optimizer update) see what they saw before the rewrite."""
        n_cast = 0
        islot = "I_" + slot
        mirrors = g.inputs.get(islot)
        if mirrors is not None and j < len(mirrors) and mirrors[j] == name:
            c2 = self._insert_cast(block, g, name, target, role=1)
            mirrors[j] = c2
            env[c2] = target
            n_cast += 1
        gslot = "GI_" + slot
        gouts = g.outputs.get(gslot)
        if gouts is not None and j < len(gouts) and orig_dt != target:
            gname = gouts[j]
            tmp = unique_name(f"{gname}@amp.raw")
            gouts[j] = tmp
            tv = block.create_var(name=tmp, dtype=target,
                                  stop_gradient=True)
            gv = block._find_var_recursive(gname)
            if gv is not None:
                tv.shape = gv.shape
            idx = block.ops.index(g) + 1
            block._insert_op(
                idx, "cast", inputs={"X": [tmp]}, outputs={"Out": [gname]},
                attrs={"out_dtype": orig_dt, "amp_inserted": True,
                       "op_role": 1})
            if gv is not None:
                gv.dtype = orig_dt
            env[tmp] = target
            env[gname] = orig_dt
            n_cast += 1
        return n_cast


# ---------------------------------------------------------------------------
# cleanup: identity / duplicate / chain / fold
# ---------------------------------------------------------------------------

# precision-widening rank: a cast d0 -> d1 is LOSSLESS iff d1 represents
# every d0 value exactly (same dtype, or strictly wider).  bf16 and f16
# are mutually lossy (different mantissa/exponent splits).
_RANK = {"bfloat16": 1, "float16": 1, "float32": 2, "float64": 3}


def _lossless(d0: Optional[str], d1: Optional[str]) -> bool:
    if d0 is None or d1 is None:
        return False
    if d0 == d1:
        return True
    r0, r1 = _RANK.get(d0), _RANK.get(d1)
    return r0 is not None and r1 is not None and r1 > r0


# consumers a cast can be folded into: anything the executor dispatches
# through a plain lowering rule.  Control flow (sub-block captures),
# plumbing, and nested-program carriers stay out.
_UNFOLDABLE = frozenset({
    "feed", "fetch", "while", "conditional_block", "select_input",
    "select_output", "recurrent", "py_func", "print", "cast",
})


@register_pass
class PruneRedundantCastsPass(Pass):
    """Remove the redundancy amp_bf16's local rewrite leaves behind —
    without ever changing fetch values: every rule below is value-exact
    (identity casts, duplicate casts, LOSSLESS chain collapse) or a pure
    relocation (folding the astype into the consumer's dispatch)."""

    name = "prune_redundant_casts"
    writes = frozenset({"ops", "attrs"})

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        pruned = folded = 0
        # each sweep applies every currently-safe rewrite (not one per
        # full rescan — a BERT-scale block would pay O(casts * n^2)
        # otherwise); every rule strictly shrinks the op stream or a
        # cast chain, so the fixpoint loop terminates
        for _ in range(len(block.ops) + 8):
            n = self._prune_sweep(block, ctx)
            if not n:
                break
            pruned += n
        for _ in range(4):
            n = self._fold_all(block, ctx)
            if not n:
                break
            folded += n
        pruned += folded
        if pruned:
            trace.metrics().counter("amp.casts_pruned").inc(pruned)
        return {"casts_pruned": pruned, "casts_folded": folded}

    # -- shared safety checks ------------------------------------------------
    def _rewirable(self, block, ctx, out: str) -> bool:
        """May every consumer of ``out`` be pointed somewhere else?"""
        if ctx.is_protected(block, out):
            return False
        if len(_writer_idxs(block, out)) != 1:
            return False
        prog = block.program
        other = [o for b in prog.blocks for o in b.ops
                 if b is not block and out in _op_reads(b, o)]
        return not other and not any(
            out in repr(o.attrs.get("true_outs", ()))
            + repr(o.attrs.get("false_outs", ()))
            for b in prog.blocks for o in b.ops)

    @staticmethod
    def _src_stable(block, i0: int, i1: int, src: str) -> bool:
        """``src`` still holds the value op i0 read when op i1 runs."""
        return not any(src in op.output_arg_names
                       for op in block.ops[i0 + 1:i1])

    def _runtime_dtype(self, block, upto: int, name: str) -> Optional[str]:
        """Dataflow dtype of ``name`` as seen by ops[upto]: last writer's
        declared out dtype for casts/fills, var metadata otherwise."""
        for op in reversed(block.ops[:upto]):
            if name in op.output_arg_names:
                if op.type == "cast":
                    return str(op.attrs.get("out_dtype", "float32"))
                if op.type == "fill_constant":
                    return str(op.attrs.get("dtype", "float32"))
                break
        v = block._find_var_recursive(name)
        return v.dtype if v is not None else None

    # -- one SWEEP per call (fixpoint driver above): every rule re-checks
    # its safety conditions against the block's CURRENT state (indices
    # recomputed after each mutation), so batching rewrites is exactly as
    # conservative as one-rewrite-per-rescan — just O(casts * n) a sweep
    def _prune_sweep(self, block, ctx: PassContext) -> int:
        from ..framework import device_dtype
        n_rewrites = 0
        by_key: Dict[tuple, Operator] = {}      # (src, dt) -> kept cast
        for op in list(block.ops):
            if (op.type != "cast" or not op.inputs.get("X")
                    or not op.outputs.get("Out")):
                continue
            try:
                i = block.ops.index(op)
            except ValueError:
                continue        # removed earlier in this sweep
            src, out = op.inputs["X"][0], op.outputs["Out"][0]
            dt = str(op.attrs.get("out_dtype", "float32"))
            src_dt = self._runtime_dtype(block, i, src)

            # 1. identity cast: the value already IS the target dtype
            try:
                same = (src_dt is not None
                        and device_dtype(dt) == device_dtype(src_dt))
            except (ValueError, TypeError):
                same = False
            if same and self._rewire_and_remove(block, ctx, i, op, src):
                n_rewrites += 1
                continue

            # 2. duplicate: an earlier cast of the same src to the same
            # dtype whose output is still valid here
            key = (src, dt)
            prev = by_key.get(key)
            if prev is not None:
                try:
                    j = block.ops.index(prev)
                except ValueError:
                    j = None    # the kept cast was itself removed
                prev_out = prev.outputs["Out"][0]
                if (j is not None and j < i
                        and self._src_stable(block, j, i, src)
                        and self._rewire_and_remove(block, ctx, i, op,
                                                    prev_out)):
                    n_rewrites += 1
                    continue
            else:
                if len(_writer_idxs(block, src)) <= 1 \
                        and len(_writer_idxs(block, out)) == 1:
                    by_key[key] = op

            # 3. lossless chain collapse: cast(cast(x, wide), dt) ==
            # cast(x, dt) — and when dt == dtype(x), rule 1 finishes it
            widx = _writer_idxs(block, src)
            if len(widx) == 1 and widx[0] < i:
                inner = block.ops[widx[0]]
                if (inner.type == "cast" and inner.inputs.get("X")
                        and not ctx.is_protected(block, src)):
                    x = inner.inputs["X"][0]
                    x_dt = self._runtime_dtype(block, widx[0], x)
                    mid = str(inner.attrs.get("out_dtype", "float32"))
                    if (_lossless(x_dt, mid)
                            and self._src_stable(block, widx[0], i, x)):
                        op.inputs["X"] = [x]
                        block.program._bump_version()
                        n_rewrites += 1
                        continue

            # 4. dead amp cast (orphaned by earlier rules)
            if op.attrs.get("amp_inserted") \
                    and not ctx.is_protected(block, out) \
                    and not self._consumers(block, op, out):
                block._remove_op(i)
                n_rewrites += 1
        return n_rewrites

    def _fold_all(self, block, ctx: PassContext) -> int:
        """One sweep folding every foldable amp cast into its consumers'
        dispatch (the final prune stage)."""
        folded = 0
        for op in [op for op in list(block.ops)
                   if op.type == "cast" and op.attrs.get("amp_inserted")
                   and op.inputs.get("X") and op.outputs.get("Out")]:
            i = block.ops.index(op)
            if self._fold_into_consumers(block, ctx, i, op):
                folded += 1
        return folded

    @staticmethod
    def _consumers(block, cast_op, out: str):
        return [o for o in block.ops
                if o is not cast_op and out in _op_reads(block, o)]

    def _rewire_and_remove(self, block, ctx, i, op, repl: str) -> bool:
        out = op.outputs["Out"][0]
        if out == repl or not self._rewirable(block, ctx, out):
            return False
        consumers = [o for o in block.ops
                     if o is not op and out in _op_reads(block, o)]
        for o in consumers:
            # repl must still hold the value this cast read when the
            # consumer runs — an in-place writer of repl between them
            # (assign/check_finite/optimizer update) would change fetches
            if not self._src_stable(block, i, block.ops.index(o), repl):
                return False
        for o in consumers:
            for slot, names in o.inputs.items():
                if out in names:
                    o.inputs[slot] = [repl if n == out else n
                                      for n in names]
        block._remove_op(block.ops.index(op))
        return True

    def _fold_into_consumers(self, block, ctx, i, op) -> bool:
        """Turn ``y = cast(x); f(y)`` into ``f(x)`` with a
        ``__amp_cast__`` attr on f — the executor applies the astype
        inline while gathering inputs (run_block_ops), so the cast costs
        zero dispatched ops.  Value-exact: same astype, same place in the
        dataflow."""
        src, out = op.inputs["X"][0], op.outputs["Out"][0]
        dt = str(op.attrs.get("out_dtype", "float32"))
        if not self._rewirable(block, ctx, out):
            return False
        consumers = self._consumers(block, op, out)
        if not consumers or any(o.type in _UNFOLDABLE for o in consumers):
            return False
        ci = block.ops.index(op)
        for o in consumers:
            if not self._src_stable(block, ci, block.ops.index(o), src):
                return False
        for o in consumers:
            amp = {k: list(v) for k, v in
                   (o.attrs.get("__amp_cast__") or {}).items()}
            for slot, names in o.inputs.items():
                if out not in names:
                    continue
                dts = amp.get(slot) or [None] * len(names)
                if len(dts) < len(names):
                    dts = list(dts) + [None] * (len(names) - len(dts))
                for k, n in enumerate(names):
                    if n == out:
                        names[k] = src
                        dts[k] = dt
                amp[slot] = dts
            o.set_attr("__amp_cast__", amp)
        block._remove_op(block.ops.index(op))
        return True
