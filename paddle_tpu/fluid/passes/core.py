"""Pass framework core: Pass base class, registry, pipeline.

Reference: paddle/fluid/framework/ir/pass.h (Pass::Apply over ir::Graph,
RegisterPass macros populating a global PassRegistry, 134 registered
passes) and build_strategy.cc AppendPass wiring BuildStrategy knobs to a
pass list.  TPU-native differences: passes rewrite the *Program/Block IR*
directly (there is no separate ir::Graph — the Block op list IS the graph;
SSA-ness comes from trace-time env threading in executor.run_block_ops),
and the payoff is host-side: fewer dispatched ops per trace (the per-op
span loop PR 1 measures), a smaller jaxpr (the compile tax PR 2 measures),
and collective launches XLA will not merge on its own.

Contract notes:

* Every mutation goes through the Block mutators (``append_op`` /
  ``_insert_op`` / ``_insert_op_obj`` / ``_remove_op`` / ``set_attr``) so
  the program's ``_version`` bumps and the executor's cached fingerprint
  (executor._fingerprint) can never serve a stale executable.  The
  pipeline *enforces* this: a pass that changed the op stream without a
  version bump is a hard error, not a silent cache hazard.
* Passes declare read/write sets over IR aspects ({"ops", "attrs",
  "vars"}).  A pass with an empty write set is an analysis/no-op pass and
  the pipeline asserts it did not mutate.
* Every pass run emits a ``pass::<name>`` span (cat="pass") plus
  ``pass.<name>.<stat>`` counters through the PR 1 trace plane.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from .. import trace

__all__ = ["Pass", "PassContext", "PassRegistry", "register_pass",
           "create_pass", "get_pass_names", "PassPipeline"]

IR_ASPECTS = frozenset({"ops", "attrs", "vars"})


class PassContext:
    """Per-application state shared by the passes of one pipeline run.

    ``targets`` are the fetch var names the caller will ask the executor
    for — the DCE seed and the protection set: a pass must never remove or
    re-alias the producer of a target (the fetch would KeyError).
    """

    def __init__(self, program, targets: Sequence[str] = (),
                 build_strategy=None, sharding_plan=None):
        self.program = program
        self.targets = [str(t) for t in (targets or ())]
        self.build_strategy = build_strategy
        # the resolved PR-10 ShardingPlan when the pipeline runs under a
        # sharded CompiledProgram (run() ensures the plan BEFORE the
        # passes) — spec-aware passes (fuse_optimizer) group by it
        self.sharding_plan = sharding_plan
        self.stats: Dict[str, Dict[str, int]] = {}

    def is_protected(self, block, name: str) -> bool:
        """Vars a rewrite must keep producing under their own name:
        fetch targets, persistables (scope state), and data feeds."""
        if name in self.targets:
            return True
        v = block._find_var_recursive(name)
        return v is not None and (v.persistable or v.is_data)


class Pass:
    """Base class: subclass, set ``name``, declare read/write sets, and
    implement ``apply_block`` (or override ``apply`` for whole-program
    passes).  Return a dict of integer stats (``ops_removed``,
    ``ops_fused``, ...) — the pipeline turns them into trace-plane
    counters and span args."""

    name: str = "pass"
    # IR aspects this pass reads / mutates.  writes=∅ => analysis/no-op
    # pass; the pipeline asserts the program version did not move.
    reads: frozenset = frozenset({"ops"})
    writes: frozenset = frozenset({"ops"})

    def __init__(self, **options):
        self.options = options
        bad = (set(self.reads) | set(self.writes)) - IR_ASPECTS
        if bad:
            raise ValueError(
                f"pass '{self.name}' declares unknown IR aspects {bad}; "
                f"valid: {sorted(IR_ASPECTS)}")

    def apply(self, program, ctx: PassContext) -> Dict[str, int]:
        stats: Dict[str, int] = {}
        for block in program.blocks:
            for k, v in (self.apply_block(block, ctx) or {}).items():
                stats[k] = stats.get(k, 0) + int(v)
        return stats

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        return {}

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


class PassRegistry:
    """name -> Pass subclass map (ir/pass.h PassRegistry analog)."""

    def __init__(self):
        self._passes: Dict[str, Type[Pass]] = {}

    def register(self, cls: Type[Pass]) -> Type[Pass]:
        name = cls.name
        if not name or name == "pass":
            raise ValueError(f"{cls.__name__} must set a unique `name`")
        if name in self._passes:
            raise ValueError(f"pass '{name}' already registered "
                             f"({self._passes[name].__name__})")
        self._passes[name] = cls
        return cls

    def get(self, name: str) -> Type[Pass]:
        if name not in self._passes:
            raise KeyError(
                f"no pass named '{name}' registered "
                f"(available: {sorted(self._passes)})")
        return self._passes[name]

    def create(self, name: str, **options) -> Pass:
        return self.get(name)(**options)

    def names(self) -> List[str]:
        return sorted(self._passes)


_registry = PassRegistry()


def register_pass(cls: Type[Pass]) -> Type[Pass]:
    """Class decorator: ``@register_pass`` above a Pass subclass."""
    return _registry.register(cls)


def create_pass(name: str, **options) -> Pass:
    return _registry.create(name, **options)


def get_pass_names() -> List[str]:
    return _registry.names()


def _n_ops(program) -> int:
    return sum(len(b.ops) for b in program.blocks)


class PassPipeline:
    """Ordered pass application with trace-plane instrumentation, version
    enforcement, and optional per-stage Graphviz dumps
    (BuildStrategy.debug_graphviz_path)."""

    def __init__(self, passes: Sequence[Pass] = (),
                 graphviz_path: Optional[str] = None):
        self.passes: List[Pass] = list(passes)
        self.graphviz_path = graphviz_path or None

    def append(self, p: Pass) -> "PassPipeline":
        self.passes.append(p)
        return self

    def _dump(self, program, stage: int, label: str) -> None:
        if not self.graphviz_path:
            return
        from .graphviz import dump_program
        os.makedirs(self.graphviz_path, exist_ok=True)
        dump_program(program, os.path.join(
            self.graphviz_path, f"{stage:02d}_{label}.dot"))

    def apply(self, program, targets: Sequence[str] = (),
              build_strategy=None,
              sharding_plan=None) -> Dict[str, Dict[str, int]]:
        """Run every pass over ``program``; returns {pass: stats}."""
        ctx = PassContext(program, targets=targets,
                          build_strategy=build_strategy,
                          sharding_plan=sharding_plan)
        self._dump(program, 0, "input")
        tr_on = trace.enabled()
        for i, p in enumerate(self.passes):
            v0, n0 = program._version, _n_ops(program)
            t0 = trace.now() if tr_on else 0
            stats = dict(p.apply(program, ctx) or {})
            n1 = _n_ops(program)
            if not p.writes and program._version != v0:
                raise RuntimeError(
                    f"pass '{p.name}' declares an empty write set but "
                    f"bumped the program version ({v0} -> "
                    f"{program._version})")
            if n1 != n0 and program._version == v0:
                # the stale-fingerprint hazard the mutator contract exists
                # to prevent — fail the pipeline, don't poison the cache
                raise RuntimeError(
                    f"pass '{p.name}' changed the op count ({n0} -> {n1}) "
                    f"without bumping the program version; rewrites must "
                    f"go through the Block mutators")
            stats.setdefault("ops_removed", max(n0 - n1, 0))
            ctx.stats[p.name] = stats
            m = trace.metrics()
            for k, v in stats.items():
                if v:
                    m.counter(f"pass.{p.name}.{k}").inc(int(v))
            if tr_on:
                trace.complete(f"pass::{p.name}", t0, cat="pass",
                               args=dict(stats, ops_before=n0,
                                         ops_after=n1))
            self._dump(program, i + 1, p.name)
        return ctx.stats
