"""Declarative op-DAG pattern matcher with var capture.

Reference: paddle/fluid/framework/ir/graph_pattern_detector.h — PDPattern
nodes linked by var edges, GraphPatternDetector walking the graph and
handing matched subgraphs to a rewrite callback.  Here a Pattern is an
ordered list of op templates (op type or alternatives, plus constraints on
named input/output slots and attrs); a slot constraint is a list of PVar
captures and/or literal var names.  Matching walks ``block.ops`` in
program order (fluid blocks are topologically ordered by construction), so
pattern ops must be declared in the order they appear in the block —
forward ops first, their grad ops after, exactly how append_backward lays
them out.

Only the slots named in the template are constrained; unlisted slots match
anything (a generic_grad carries I_<slot> mirrors of every forward slot —
a pattern usually pins just the one that identifies the edge).  Attr
constraints are literal values or predicates.

Rewrites go through the Block mutators (``_insert_op`` / ``_insert_op_obj``
/ ``_remove_op``) so every rewrite bumps the program version and the
executor recompiles (see executor._fingerprint).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .core import Pass, PassContext

__all__ = ["PVar", "POp", "Pattern", "Match", "PatternRewritePass",
           "writer_index"]


class PVar:
    """A capture slot: first binding fixes the var name, later uses must
    agree (the DAG edge)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"?{self.name}"


class POp:
    """One op template in a Pattern."""

    def __init__(self, types, ins=None, outs=None, attrs=None):
        self.types = (types,) if isinstance(types, str) else tuple(types)
        self.ins = dict(ins or {})
        self.outs = dict(outs or {})
        self.attrs = dict(attrs or {})

    def _match_slots(self, spec: Dict[str, list], actual: Dict[str, list],
                     binding: Dict[str, str]) -> Optional[Dict[str, str]]:
        for slot, pats in spec.items():
            names = actual.get(slot)
            if names is None or len(names) != len(pats):
                return None
            for pat, name in zip(pats, names):
                if isinstance(pat, PVar):
                    bound = binding.get(pat.name)
                    if bound is None:
                        binding = dict(binding)
                        binding[pat.name] = name
                    elif bound != name:
                        return None
                elif pat != name:
                    return None
        return binding

    def match(self, op, binding: Dict[str, str]) -> Optional[Dict[str, str]]:
        if op.type not in self.types:
            return None
        for k, want in self.attrs.items():
            have = op.attrs.get(k)
            ok = want(have) if callable(want) else have == want
            if not ok:
                return None
        binding = self._match_slots(self.ins, op.inputs, binding)
        if binding is None:
            return None
        return self._match_slots(self.outs, op.outputs, binding)


class Match:
    """A matched subgraph: pattern-aligned ops + the var bindings."""

    def __init__(self, block, ops, binding: Dict[str, str]):
        self.block = block
        self.ops = list(ops)
        self.binding = dict(binding)

    def var(self, name: str) -> str:
        return self.binding[name]

    def index(self, i: int) -> int:
        """Current position of matched op i in the block (positions move
        as rewrites splice ops)."""
        return self.block.ops.index(self.ops[i])


class Pattern:
    """Build with ``var()`` + ``op()``; match with ``match_all(block)``."""

    def __init__(self, name: str):
        self.name = name
        self.pops: List[POp] = []
        self._vars: Dict[str, PVar] = {}

    def var(self, name: str) -> PVar:
        v = self._vars.get(name)
        if v is None:
            v = self._vars[name] = PVar(name)
        return v

    def vars(self, names: str) -> Tuple[PVar, ...]:
        return tuple(self.var(n) for n in names.split())

    def op(self, types, ins=None, outs=None, attrs=None) -> POp:
        p = POp(types, ins, outs, attrs)
        self.pops.append(p)
        return p

    # -- matching -----------------------------------------------------------
    def _extend(self, ops, start: int, depth: int,
                binding: Dict[str, str], picked: list):
        if depth == len(self.pops):
            yield picked, binding
            return
        pop = self.pops[depth]
        for i in range(start, len(ops)):
            b = pop.match(ops[i], binding)
            if b is not None:
                yield from self._extend(ops, i + 1, depth + 1, b,
                                        picked + [ops[i]])

    def first_match(self, block, start: int = 0) -> Optional[Match]:
        for picked, binding in self._extend(block.ops, start, 0, {}, []):
            return Match(block, picked, binding)
        return None

    def match_all(self, block) -> List[Match]:
        """All non-overlapping matches, scanning in program order."""
        out, used = [], set()
        for picked, binding in self._extend(block.ops, 0, 0, {}, []):
            if any(id(op) in used for op in picked):
                continue
            used.update(id(op) for op in picked)
            out.append(Match(block, picked, binding))
        return out


def writer_index(block, name: str) -> List[int]:
    """Indices of ops writing ``name`` — the single-writer precondition
    every rewrite rule checks before re-aliasing an edge."""
    return [i for i, op in enumerate(block.ops)
            if name in op.output_arg_names]


class PatternRewritePass(Pass):
    """A Pass driven by (Pattern, rewrite) rules, tried in order.

    ``rewrite(match, ctx) -> bool`` performs the in-place block rewrite
    through the mutators and returns True on success; returning False
    leaves the block untouched (a structural precondition failed — e.g.
    the intermediate var has an extra consumer) and the scan moves on.
    After every successful rewrite the scan restarts: positions and
    consumer sets have changed.
    """

    #: list of (Pattern, rewrite_fn-name) pairs; subclasses populate in
    #: __init__ via self.rules
    max_rewrites = 10_000

    def __init__(self, **options):
        super().__init__(**options)
        self.rules: List[Tuple[Pattern, Callable]] = []

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        fused = 0
        for pattern, rewrite in self.rules:
            budget = self.max_rewrites
            rejected = set()            # op-id tuples rewrite() declined
            while budget > 0:
                budget -= 1
                done = False
                for picked, binding in pattern._extend(
                        block.ops, 0, 0, {}, []):
                    key = tuple(id(op) for op in picked)
                    if key in rejected:
                        continue
                    m = Match(block, picked, binding)
                    if rewrite(m, ctx):
                        fused += 1
                        done = True
                        break           # restart scan: block changed
                    rejected.add(key)
                if not done:
                    break
        return {"ops_fused": fused} if fused else {}
