"""The Pallas kernel tier as compiler passes.

Reference: the qingshui/PaddleBox fork's identity is its fused ads/CTR
operators (PAPER.md: ``operators/fused/``, ``multihead_matmul_op.cu``,
``bert_encoder_functor.cu``, ``fused_embedding_seq_pool_op.cc``,
``framework/ir/fuse_optimizer_ops_pass/``).  The seed shipped the KERNELS
half of that story — ``ops/pallas_kernels.py`` behind the
``fused_multihead_attention`` / ``fused_embedding_pool`` / ``fused_*``
op boundaries — but nothing in the compiler ever *produced* those ops: a
BERT program built from plain matmul/softmax layers lowered op-by-op.
These three pattern-rewrite passes close the gap the same way PR 3/PR 5
did for fusion and AMP: any existing program gets the kernels without
touching model code.

* ``fuse_attention`` — the naive attention chain matmul(Q,Kᵀ) → scale →
  (+mask) → softmax → (dropout) → matmul(·,V), including the paired
  ``generic_grad`` ops of training programs, rewrites to ONE
  ``fused_multihead_attention`` op (+ one fused generic_grad).  The
  lowering dispatches to the Pallas flash kernel on TPU
  (``FLAGS_pallas_min_seq`` crossover, additive-bias masks ride the
  kernel's ``ab`` argument) and the XLA-fused reference elsewhere; an
  absorbed dropout op's seed is stamped into the fused op so the XLA
  path regenerates the identical mask.
* ``fuse_paged_attention`` — the block-paged decode attend chain
  (serving/decode.py paged programs): page-table gather ×2 → reshape ×2
  → mul+reduce_sum scores → scale → exact-zero mask → softmax →
  mul+reduce_sum context, rewritten to ONE ``paged_attention`` op whose
  TPU lowering is the Pallas paged flash kernel
  (``pallas_kernels.paged_flash_attention_tpu``) and whose XLA fallback
  reproduces the unfused chain bit-for-bit (the decode engine's
  exactness gate depends on that).
* ``fuse_sparse_embedding`` — the CTR hot path
  ``lookup_table[_v2]`` (+ ``sequence_pool``/``reduce_sum(dim=1)``)
  rewrites to ``fused_embedding_pool``: Pallas fused gather+pool forward
  with a fused scatter-add (segment-sum) backward, XLA take/masked-sum
  fallback mirroring the unfused chain.
* ``fuse_optimizer`` — consecutive same-(family, dtype, attrs, lr,
  PartitionSpec-group) ``adam``/``lamb``/``momentum`` update ops bucket
  into one ``fused_adam``/``fused_lamb``/``fused_momentum`` op: one
  launch per bucket over a flattened param buffer, element-for-element
  the same arithmetic (bit-compares against per-param updates), PR-5
  MasterParam slots carried through, and — under a PR-10 sharding plan —
  bucketing only within identical-spec groups so the whole-step pjit
  path never pays a reshard.

Every pass counts ``kernel_tier.<pass>.rewrites``; wiring is the
``BuildStrategy.fuse_attention`` / ``fuse_sparse_embedding`` /
``fuse_optimizer`` knobs plus the ``kernel_tier`` umbrella, appended by
``passes_for_build_strategy`` after the pairwise fusions and before AMP
(docs/passes.md).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import trace
from ..framework import Operator, _op_reads
from .core import Pass, PassContext, register_pass
from .pattern import Pattern, PatternRewritePass, writer_index as _widx

__all__ = ["FuseAttentionPass", "FusePagedAttentionPass",
           "FuseSparseEmbeddingPass", "FuseOptimizerPass"]


def _consumers(block, name: str) -> List[Operator]:
    return [op for op in block.ops if name in _op_reads(block, op)]


def _internal_edge(block, ctx: PassContext, name: str, allowed_ops) -> bool:
    """A var the rewrite deletes must be a purely internal edge: written
    once, not protected, consumed only by the ops being fused."""
    if ctx.is_protected(block, name):
        return False
    if len(_widx(block, name)) != 1:
        return False
    allowed = {id(o) for o in allowed_ops}
    return all(id(c) in allowed for c in _consumers(block, name))


def _ndim(block, name: str) -> Optional[int]:
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return None
    return len(v.shape)


def _splice(block, new_op: Operator, anchor: Operator, dead) -> None:
    """Insert ``new_op`` right after ``anchor``, remove the ``dead`` ops —
    all through the version-bumping mutators."""
    block._insert_op_obj(block.ops.index(anchor) + 1, new_op)
    for op in dead:
        block._remove_op(block.ops.index(op))


def _count_rewrite(pass_name: str) -> None:
    trace.metrics().counter(f"kernel_tier.{pass_name}.rewrites").inc()


# ---------------------------------------------------------------------------
# fuse_attention
# ---------------------------------------------------------------------------

def _falsy(v) -> bool:
    return not v


def _truthy(v) -> bool:
    return bool(v)


@register_pass
class FuseAttentionPass(PatternRewritePass):
    """matmul(Q,Kᵀ) → [scale] → [+mask] → softmax → [dropout] → matmul(·,V)
    ⇒ ``fused_multihead_attention`` (forward AND the paired generic_grad
    chain in training programs).  Patterns are generated for every
    optional-op combination, training variants first and longer chains
    before their own sub-chains, so a complete chain always wins."""

    name = "fuse_attention"

    def __init__(self, **options):
        super().__init__(**options)
        for train in (True, False):
            for with_drop in (True, False):
                for with_mask in (True, False):
                    for with_scale in (True, False):
                        self.rules.append(self._rule(
                            train, with_scale, with_mask, with_drop))

    # -- pattern construction ----------------------------------------------
    def _rule(self, train, with_scale, with_mask, with_drop):
        p = Pattern(f"attention_{'train' if train else 'fwd'}"
                    f"_s{int(with_scale)}m{int(with_mask)}d{int(with_drop)}")
        q, k, v, out = p.vars("q k v out")
        scores = [p.var("s0")]            # score-var chain, program order
        p.op("matmul", ins={"X": [q], "Y": [k]}, outs={"Out": [scores[-1]]},
             attrs={"transpose_X": _falsy, "transpose_Y": _truthy})
        if with_scale:
            scores.append(p.var("s1"))
            p.op("scale", ins={"X": [scores[-2]]},
                 outs={"Out": [scores[-1]]},
                 attrs={"bias": _falsy})
        if with_mask:
            scores.append(p.var("s2"))
            # only the trailing-broadcast, unscaled spelling: a Paddle
            # leading-dim axis or a post-add scale multiplier is not what
            # the fused lowering's `s + mask` computes
            p.op("elementwise_add",
                 ins={"X": [scores[-2]], "Y": [p.var("mask")]},
                 outs={"Out": [scores[-1]]},
                 attrs={"axis": lambda a: a in (None, -1),
                        "scale": lambda sc: sc is None
                        or float(sc) == 1.0})
        probs = [p.var("p0")]
        p.op("softmax", ins={"X": [scores[-1]]}, outs={"Out": [probs[-1]]},
             attrs={"axis": lambda a: a in (None, -1, 3)})
        if with_drop:
            probs.append(p.var("p1"))
            p.op("dropout", ins={"X": [probs[-2]]},
                 outs={"Out": [probs[-1]]})
        p.op("matmul", ins={"X": [probs[-1]], "Y": [v]},
             outs={"Out": [out]},
             attrs={"transpose_X": _falsy, "transpose_Y": _falsy,
                    "alpha": lambda a: a is None or float(a) == 1.0})
        if train:
            # grads in reverse forward order (append_backward layout)
            p.op("generic_grad",
                 ins={"I_X": [probs[-1]], "I_Y": [v], "G_Out": [p.var("go")]},
                 outs={"GI_X": [p.var("gp")], "GI_Y": [p.var("gv")]},
                 attrs={"fwd_type": "matmul"})
            g_cur = p.var("gp")
            if with_drop:
                p.op("generic_grad",
                     ins={"I_X": [probs[-2]], "G_Out": [g_cur]},
                     outs={"GI_X": [p.var("gp0")]},
                     attrs={"fwd_type": "dropout"})
                g_cur = p.var("gp0")
            p.op("generic_grad", ins={"I_X": [scores[-1]], "G_Out": [g_cur]},
                 outs={"GI_X": [p.var("gsm")]},
                 attrs={"fwd_type": "softmax"})
            g_cur = p.var("gsm")
            if with_mask:
                p.op("generic_grad",
                     ins={"I_X": [scores[-2]], "G_Out": [g_cur]},
                     outs={"GI_X": [p.var("gadd")]},
                     attrs={"fwd_type": "elementwise_add"})
                g_cur = p.var("gadd")
            if with_scale:
                p.op("generic_grad",
                     ins={"I_X": [scores[0]], "G_Out": [g_cur]},
                     outs={"GI_X": [p.var("gsc")]},
                     attrs={"fwd_type": "scale"})
                g_cur = p.var("gsc")
            p.op("generic_grad",
                 ins={"I_X": [q], "I_Y": [k], "G_Out": [g_cur]},
                 outs={"GI_X": [p.var("gq")], "GI_Y": [p.var("gk")]},
                 attrs={"fwd_type": "matmul"})

        def rewrite(m, ctx, _flags=(train, with_scale, with_mask,
                                    with_drop)):
            return self._rewrite(m, ctx, *_flags)

        return (p, rewrite)

    # -- rewrite ------------------------------------------------------------
    def _rewrite(self, m, ctx, train, with_scale, with_mask,
                 with_drop) -> bool:
        block = m.block
        n_fwd = 3 + int(with_scale) + int(with_mask) + int(with_drop)
        fwd_ops, grad_ops = m.ops[:n_fwd], m.ops[n_fwd:]
        mm2 = fwd_ops[-1]
        drop_op = fwd_ops[-2] if with_drop else None
        # the naive chain operates on [B, H, T, T] scores — require the
        # 4-d shape the fused op's lowering assumes.  Unknown shapes stay
        # on the op-by-op path (conservative: never mis-fuse an mlp's
        # matmul→softmax→matmul into an attention kernel).
        for name in (m.var("q"), m.var("k"), m.var("v"), m.var("out")):
            if _ndim(block, name) != 4:
                return False
        # internal edges: every intermediate score/prob var dies with the
        # rewrite, so it must have no consumer outside the matched ops
        inter = [m.binding[n] for n in
                 ("s0", "s1", "s2", "p0", "p1") if n in m.binding]
        allowed = fwd_ops + grad_ops
        for t in inter:
            if not _internal_edge(block, ctx, t, allowed):
                return False
        if len(_widx(block, m.var("out"))) != 1:
            return False
        for name in (m.var("q"), m.var("k"), m.var("v")):
            if len(_widx(block, name)) > 1:
                return False
        if drop_op is not None:
            mask_out = (drop_op.outputs.get("Mask") or [None])[0]
            if mask_out and _consumers(block, mask_out):
                return False
        if train:
            # grad chain intermediates are internal too, and the mask must
            # not itself require a gradient (the fused op cannot emit one)
            ginter = [m.binding[n] for n in
                      ("gp", "gp0", "gsm", "gadd", "gsc") if n in m.binding]
            for t in ginter:
                if not _internal_edge(block, ctx, t, allowed):
                    return False
            for n in ("gq", "gk", "gv"):
                if len(_widx(block, m.var(n))) != 1:
                    return False
            if with_mask:
                add_g = next(o for o in grad_ops
                             if o.attrs.get("fwd_type") == "elementwise_add")
                if add_g.outputs.get("GI_Y"):
                    return False

        scale = float(fwd_ops[0].attrs.get("alpha", 1.0) or 1.0)
        if with_scale:
            scale *= float(fwd_ops[1].attrs.get("scale", 1.0))
        attrs = {"scale": scale, "causal": False,
                 "op_role": fwd_ops[0].attrs.get("op_role", 0)}
        if drop_op is not None:
            attrs.update(
                dropout_rate=float(drop_op.attrs.get("dropout_prob", 0.5)),
                dropout_seed=int(drop_op.attrs.get(
                    "op_seed", drop_op.attrs.get("seed", 0) or 0)),
                dropout_implementation=drop_op.attrs.get(
                    "dropout_implementation", "downgrade_in_infer"),
                dropout_is_test=bool(drop_op.attrs.get("is_test", False)))
        ins = {"Q": [m.var("q")], "K": [m.var("k")], "V": [m.var("v")]}
        in_slots = ["Q", "K", "V"]
        if with_mask:
            ins["Mask"] = [m.var("mask")]
            in_slots.append("Mask")
        fused = Operator(block, "fused_multihead_attention", ins,
                         {"Out": [m.var("out")]}, attrs)
        if train:
            g_ins = {"I_" + s: list(ins[s]) for s in in_slots}
            g_ins["G_Out"] = [m.var("go")]
            fused_g = Operator(
                block, "generic_grad", g_ins,
                {"GI_Q": [m.var("gq")], "GI_K": [m.var("gk")],
                 "GI_V": [m.var("gv")]},
                {"fwd_type": "fused_multihead_attention",
                 "fwd_attrs": dict(attrs), "in_slots": list(in_slots),
                 "grad_slots": ["Q", "K", "V"], "op_role": 1})
            _splice(block, fused_g, grad_ops[0], grad_ops)
        _splice(block, fused, mm2, fwd_ops)
        _count_rewrite(self.name)
        return True


# ---------------------------------------------------------------------------
# fuse_paged_attention
# ---------------------------------------------------------------------------

@register_pass
class FusePagedAttentionPass(PatternRewritePass):
    """gather(KPool, pt) → reshape → gather(VPool, pt) → reshape →
    mul+reduce_sum(dim=[2]) scores → scale → s·valid + scale(valid, N,
    -N) → softmax → mul+reduce_sum(dim=[1]) context ⇒ one
    ``paged_attention`` op (serving/decode.py paged decode/verify
    programs emit exactly this chain, once per unrolled step).

    The matched spelling is load-bearing: the op's XLA fallback
    (ops/attention.py ``_paged_reference``) reproduces each unfused
    lowering bit-for-bit, so the rewrite is bit-transparent on CPU and
    only changes the schedule on TPU (Pallas paged flash kernel).  The
    mask arithmetic is only recognised in the exact-zero form
    (``bias == -scale`` on the valid-scale op) — anything else is not
    the decode contract and stays unfused."""

    name = "fuse_paged_attention"

    def __init__(self, **options):
        super().__init__(**options)
        self.rules.append(self._rule())

    def _rule(self):
        p = Pattern("paged_attention_decode")
        kp, vp, idx, q, valid, out = p.vars("kp vp idx q valid out")
        p.op("gather", ins={"X": [kp], "Index": [idx]},
             outs={"Out": [p.var("kgf")]})
        p.op("reshape2", ins={"X": [p.var("kgf")]},
             outs={"Out": [p.var("kg")]})
        p.op("gather", ins={"X": [vp], "Index": [idx]},
             outs={"Out": [p.var("vgf")]})
        p.op("reshape2", ins={"X": [p.var("vgf")]},
             outs={"Out": [p.var("vg")]})
        p.op("unsqueeze2", ins={"X": [q]}, outs={"Out": [p.var("qe")]},
             attrs={"axes": lambda a: list(a or ()) == [1]})
        p.op("elementwise_mul",
             ins={"X": [p.var("kg")], "Y": [p.var("qe")]},
             outs={"Out": [p.var("m1")]},
             attrs={"axis": lambda a: a in (None, -1)})
        p.op("reduce_sum", ins={"X": [p.var("m1")]},
             outs={"Out": [p.var("s0")]},
             attrs={"dim": lambda d: list(d or ()) == [2],
                    "keep_dim": _falsy, "reduce_all": _falsy})
        p.op("scale", ins={"X": [p.var("s0")]}, outs={"Out": [p.var("s1")]},
             attrs={"bias": _falsy,
                    "bias_after_scale": lambda b: b in (None, True)})
        p.op("elementwise_mul", ins={"X": [p.var("s1")], "Y": [valid]},
             outs={"Out": [p.var("sm")]},
             attrs={"axis": lambda a: a in (None, -1)})
        p.op("scale", ins={"X": [valid]}, outs={"Out": [p.var("vb")]},
             attrs={"bias_after_scale": lambda b: b in (None, True)})
        p.op("elementwise_add",
             ins={"X": [p.var("sm")], "Y": [p.var("vb")]},
             outs={"Out": [p.var("s2")]},
             attrs={"axis": lambda a: a in (None, -1)})
        p.op("softmax", ins={"X": [p.var("s2")]},
             outs={"Out": [p.var("p0")]},
             attrs={"axis": lambda a: a in (None, -1, 1)})
        p.op("unsqueeze2", ins={"X": [p.var("p0")]},
             outs={"Out": [p.var("pe")]},
             attrs={"axes": lambda a: list(a or ()) == [2]})
        p.op("elementwise_mul",
             ins={"X": [p.var("vg")], "Y": [p.var("pe")]},
             outs={"Out": [p.var("m2")]},
             attrs={"axis": lambda a: a in (None, -1)})
        p.op("reduce_sum", ins={"X": [p.var("m2")]},
             outs={"Out": [out]},
             attrs={"dim": lambda d: list(d or ()) == [1],
                    "keep_dim": _falsy, "reduce_all": _falsy})
        return (p, self._rewrite)

    def _rewrite(self, m, ctx) -> bool:
        block = m.block
        ops = m.ops
        # shape guards: flat [R, d] pools, [B, S, d] gathered caches,
        # [B, d] query, [B, S] mask — a coincidental gather→softmax
        # chain with other ranks is not the decode contract
        for name, nd in ((m.var("kp"), 2), (m.var("vp"), 2),
                         (m.var("kg"), 3), (m.var("vg"), 3),
                         (m.var("q"), 2), (m.var("valid"), 2),
                         (m.var("out"), 2)):
            if _ndim(block, name) != nd:
                return False
        # the mask must be the exact-zero spelling: valid*N + (-N)
        vb_op = ops[9]
        neg = float(vb_op.attrs.get("scale", 1.0))
        if float(vb_op.attrs.get("bias", 0.0) or 0.0) != -neg:
            return False
        # every intermediate dies with the rewrite
        inter = [m.binding[n] for n in
                 ("kgf", "kg", "vgf", "vg", "qe", "m1", "s0", "s1",
                  "sm", "vb", "s2", "p0", "pe", "m2")]
        for t in inter:
            if not _internal_edge(block, ctx, t, ops):
                return False
        if len(_widx(block, m.var("out"))) != 1:
            return False
        # reshape2/unsqueeze2 XShape side outputs must be unconsumed
        for op in ops:
            for slot, names in op.outputs.items():
                if slot == "Out":
                    continue
                for n in names:
                    if _consumers(block, n):
                        return False
        scale = float(ops[7].attrs.get("scale", 1.0))
        ps = int(block.program._hints.get("kv_page_size", 1) or 1)
        fused = Operator(
            block, "paged_attention",
            {"Q": [m.var("q")], "KPool": [m.var("kp")],
             "VPool": [m.var("vp")], "Index": [m.var("idx")],
             "Valid": [m.var("valid")]},
            {"Out": [m.var("out")]},
            {"scale": scale, "neg": neg, "page_size": ps,
             "op_role": ops[0].attrs.get("op_role", 0)})
        _splice(block, fused, ops[-1], ops)
        _count_rewrite(self.name)
        return True


# ---------------------------------------------------------------------------
# fuse_sparse_embedding
# ---------------------------------------------------------------------------

_LOOKUPS = ("lookup_table_v2", "lookup_table")


@register_pass
class FuseSparseEmbeddingPass(PatternRewritePass):
    """``lookup_table[_v2]`` + (``sequence_pool``(SUM/AVERAGE) |
    ``reduce_sum(dim=[1])``) ⇒ ``fused_embedding_pool`` — the PaddleBox
    fused_embedding_seq_pool path.  Training programs collapse the two
    generic_grad ops into one whose backward is the fused scatter-add."""

    name = "fuse_sparse_embedding"

    def __init__(self, **options):
        super().__init__(**options)
        for train in (True, False):
            for pool_kind in ("sequence_pool", "reduce_sum"):
                self.rules.append(self._rule(train, pool_kind))

    def _rule(self, train, pool_kind):
        p = Pattern(f"emb_pool_{pool_kind}_{'train' if train else 'fwd'}")
        w, ids, e, out = p.vars("w ids e out")
        p.op(_LOOKUPS, ins={"W": [w], "Ids": [ids]}, outs={"Out": [e]})
        if pool_kind == "sequence_pool":
            p.op("sequence_pool", ins={"X": [e]}, outs={"Out": [out]},
                 attrs={"pooltype": lambda t: str(t).upper()
                        in ("SUM", "AVERAGE")})
        else:
            p.op("reduce_sum", ins={"X": [e]}, outs={"Out": [out]},
                 attrs={"dim": lambda d: list(d or ()) == [1],
                        "keep_dim": _falsy, "reduce_all": _falsy})
        if train:
            p.op("generic_grad", ins={"I_X": [e], "G_Out": [p.var("g")]},
                 outs={"GI_X": [p.var("ge")]},
                 attrs={"fwd_type": pool_kind})
            p.op("generic_grad", ins={"I_W": [w], "G_Out": [p.var("ge")]},
                 outs={"GI_W": [p.var("gw")]},
                 attrs={"fwd_type": lambda t: t in _LOOKUPS})

        def rewrite(m, ctx, _flags=(train, pool_kind)):
            return self._rewrite(m, ctx, *_flags)

        return (p, rewrite)

    def _rewrite(self, m, ctx, train, pool_kind) -> bool:
        block = m.block
        lookup, pool = m.ops[0], m.ops[1]
        grad_ops = m.ops[2:]
        # the gathered [B, S, D] intermediate dies with the rewrite
        if not _internal_edge(block, ctx, m.var("e"), m.ops):
            return False
        nd = _ndim(block, m.var("e"))
        if nd is not None and nd != 3:
            return False
        if nd is None and pool_kind == "reduce_sum":
            return False          # reduce_sum(dim=1) is only a pool on 3-d
        if len(_widx(block, m.var("out"))) != 1:
            return False
        # side outputs of the pooled op (MaxIndex) must be unconsumed
        for slot, names in pool.outputs.items():
            if slot == "Out":
                continue
            for n in names:
                if _consumers(block, n):
                    return False
        if train:
            if not _internal_edge(block, ctx, m.var("ge"), m.ops):
                return False
            if len(_widx(block, m.var("gw"))) != 1:
                return False

        attrs = {"pooltype": str(pool.attrs.get("pooltype", "SUM")).upper()
                 if pool_kind == "sequence_pool" else "SUM",
                 "padding_idx": lookup.attrs.get("padding_idx", -1),
                 "squeeze_ids": lookup.type == "lookup_table",
                 "op_role": lookup.attrs.get("op_role", 0)}
        ins = {"W": [m.var("w")], "Ids": [m.var("ids")]}
        in_slots = ["W", "Ids"]
        length = (pool.inputs.get("Length") or [None])[0] \
            if pool_kind == "sequence_pool" else None
        if length is not None:
            ins["Length"] = [length]
            in_slots.append("Length")
        fused = Operator(block, "fused_embedding_pool", ins,
                         {"Out": [m.var("out")]}, attrs)
        if train:
            g_ins = {"I_" + s: list(ins[s]) for s in in_slots}
            g_ins["G_Out"] = [m.var("g")]
            fused_g = Operator(
                block, "generic_grad", g_ins, {"GI_W": [m.var("gw")]},
                {"fwd_type": "fused_embedding_pool",
                 "fwd_attrs": dict(attrs), "in_slots": list(in_slots),
                 "grad_slots": ["W"], "op_role": 1})
            _splice(block, fused_g, grad_ops[0], grad_ops)
        _splice(block, fused, pool, [lookup, pool])
        _count_rewrite(self.name)
        return True


# ---------------------------------------------------------------------------
# fuse_optimizer
# ---------------------------------------------------------------------------

_FUSABLE_UPDATES: Dict[str, Dict] = {
    "adam": {"fused": "fused_adam",
             "ins": frozenset({"Param", "Grad", "Moment1", "Moment2",
                               "Beta1Pow", "Beta2Pow", "LearningRate"}),
             "outs": ("ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut")},
    "lamb": {"fused": "fused_lamb",
             "ins": frozenset({"Param", "Grad", "Moment1", "Moment2",
                               "Beta1Pow", "Beta2Pow", "LearningRate"}),
             "outs": ("ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut")},
    "momentum": {"fused": "fused_momentum",
                 "ins": frozenset({"Param", "Grad", "Velocity",
                                   "LearningRate"}),
                 "outs": ("ParamOut", "VelocityOut")},
}

_SHARED_SLOTS = ("LearningRate",)


@register_pass
class FuseOptimizerPass(Pass):
    """Bucket consecutive same-family per-param update ops into one fused
    update op (fuse_adam_op_pass / fuse_momentum_op_pass semantics).  The
    bucket key is (op type, param dtype, multi-precision, the lr var, the
    full attr set, and — when a PR-10 sharding plan is live — the param's
    resolved PartitionSpec), so a bucket is always homogeneous: one
    flattened buffer, one launch, zero implied reshards under pjit."""

    name = "fuse_optimizer"

    def __init__(self, bucket_size: int = 1024, **options):
        super().__init__(**options)
        self.bucket_size = max(int(bucket_size), 2)

    # -- bucket keying ------------------------------------------------------
    def _spec_group(self, block, ctx: PassContext, param: str) -> str:
        plan = getattr(ctx, "sharding_plan", None)
        if plan is None:
            return ""
        v = block._find_var_recursive(param)
        if v is None or v.shape is None:
            return f"?{param}"     # unknown shape: never buckets
        try:
            return repr(plan.spec_for(param, tuple(v.shape)))
        except Exception:          # noqa: BLE001 — never block the rewrite
            return f"?{param}"

    def _key(self, block, ctx: PassContext, op) -> Optional[tuple]:
        spec = _FUSABLE_UPDATES.get(op.type)
        if spec is None:
            return None
        slots = set(op.inputs)
        has_master = "MasterParam" in slots
        want = spec["ins"] | ({"MasterParam"} if has_master else set())
        if slots != want:
            return None            # SkipUpdate or exotic wiring: leave it
        if any(len(names) != 1 for names in op.inputs.values()):
            return None
        param = op.inputs["Param"][0]
        v = block._find_var_recursive(param)
        dtype = v.dtype if v is not None else None
        attr_sig = tuple(sorted((k, repr(val)) for k, val in op.attrs.items()
                                if k not in ("op_role", "op_seed")))
        return (op.type, str(dtype), has_master,
                op.inputs["LearningRate"][0], attr_sig,
                self._spec_group(block, ctx, param))

    # -- rewriting ----------------------------------------------------------
    def _fuse_run(self, block, seg, out_ops) -> int:
        """Fuse one same-key run; returns the number of ops removed."""
        if len(seg) < 2:
            out_ops.extend(seg)
            return 0
        spec = _FUSABLE_UPDATES[seg[0].type]
        # per-param vars must be pairwise disjoint (params shared between
        # two update ops would race inside one fused op)
        per_param = [n for op in seg for slot, names in op.inputs.items()
                     if slot not in _SHARED_SLOTS for n in names]
        if len(set(per_param)) != len(per_param):
            out_ops.extend(seg)
            return 0
        removed = 0
        for lo in range(0, len(seg), self.bucket_size):
            chunk = seg[lo:lo + self.bucket_size]
            if len(chunk) < 2:
                out_ops.extend(chunk)
                continue
            ins = {slot: [op.inputs[slot][0] for op in chunk]
                   for slot in chunk[0].inputs if slot not in _SHARED_SLOTS}
            ins["LearningRate"] = list(chunk[0].inputs["LearningRate"])
            out_slots = list(spec["outs"])
            if "MasterParam" in chunk[0].inputs:
                out_slots.append("MasterParamOut")
            outs = {slot: [op.outputs[slot][0] for op in chunk]
                    for slot in out_slots}
            out_ops.append(Operator(
                block, spec["fused"], ins, outs, dict(chunk[0].attrs)))
            removed += len(chunk) - 1
            _count_rewrite(self.name)
        return removed

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        out_ops: list = []
        seg: list = []
        seg_key = None
        removed = 0

        def flush():
            nonlocal removed
            if seg:
                removed += self._fuse_run(block, seg, out_ops)
                seg.clear()

        for op in block.ops:
            key = self._key(block, ctx, op)
            if key is not None:
                if seg and key != seg_key:
                    flush()
                seg_key = key
                seg.append(op)
            else:
                flush()
                out_ops.append(op)
        flush()
        if removed:
            block.ops = out_ops
            block.program._bump_version()
        return {"ops_removed": removed}
