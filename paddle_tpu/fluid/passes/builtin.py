"""The working pass catalog: DCE, constant folding, add+act / bn+act
fusion, gradient all-reduce coalescing, identity pruning.

Reference pass names (framework/ir/): fuse_elewise_add_act_pass.cc,
fuse_bn_act_pass.cc, fuse_all_reduce_op_pass.cc,
constant_folding_pass.cc, identity_op_clean_pass.cc, plus the
build_strategy.h knobs that gate them.  TPU-native payoff: each fusion
removes a per-op host dispatch from the traced step and shrinks the jaxpr
XLA must compile; allreduce coalescing turns N small ICI launches into
ceil(N/bucket) flattened ones — a merge XLA does not perform across
independent psums.

Training-aware fusion: append_backward (backward.py) emits one
``generic_grad`` per forward op, so fusing `add+act` in a training program
must also fuse the two grad ops — the intermediate var is consumed by the
act's grad (``I_X``).  The fused grad is simply ``generic_grad`` over the
fused op's own lowering rule (vjp correctness is inherited, exactly like
every other op's gradient on this stack).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..framework import Operator, prune_ops, unique_name, _op_reads
from .core import Pass, PassContext, register_pass, create_pass
from .pattern import Pattern, PatternRewritePass, writer_index as \
    _writer_idxs

ACTS = ("relu", "sigmoid", "tanh")


def _consumers(block, name: str) -> List[Operator]:
    """Ops reading ``name``, including control-flow sub-block captures."""
    return [op for op in block.ops if name in _op_reads(block, op)]


def _no_hazard_between(block, i0: int, i1: int, reads, writes) -> bool:
    """Safe to move an op from position i0 to i1 (i0 < i1): no op strictly
    between may write a var the moved op reads, or touch a var it
    writes."""
    reads, writes = set(reads), set(writes)
    for op in block.ops[i0 + 1:i1]:
        wr = set(op.output_arg_names)
        if (wr & (reads | writes)) or (set(_op_reads(block, op)) & writes):
            return False
    return True


# ---------------------------------------------------------------------------
# dead-code elimination
# ---------------------------------------------------------------------------

@register_pass
class DeadCodeEliminationPass(Pass):
    """Backward-reachability DCE from the fetch targets
    (framework/prune.cc semantics via framework.prune_ops): ops feeding
    neither a target, persistable/optimizer state, nor a side effect are
    removed from the *program* — every later trace and serialization sees
    the smaller block.  Sub-blocks are left intact (their liveness is the
    owning control-flow op's business)."""

    name = "dce"

    def apply(self, program, ctx: PassContext) -> Dict[str, int]:
        block = program.global_block()
        targets = list(ctx.targets) or None
        kept = prune_ops(block, block.ops, targets=targets,
                         keep_state_writes=True)
        removed = len(block.ops) - len(kept)
        if removed:
            block.ops = kept
            program._bump_version()
        return {"ops_removed": removed}


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

@register_pass
class ConstantFoldPass(Pass):
    """Fold fill_constant/scale/cast chains at pass time instead of trace
    time: ``scale(fill_constant)`` and ``cast(fill_constant)`` become a
    single fill_constant; ``scale(scale(x))`` composes into one scale.
    Orphaned producers are left for DCE."""

    name = "constant_fold"
    writes = frozenset({"ops", "attrs"})

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        folded = 0
        for _ in range(4 * len(block.ops) + 16):
            if not self._fold_one(block):
                break
            folded += 1
        return {"ops_folded": folded}

    def _consts(self, block) -> Dict[str, Operator]:
        out = {}
        for op in block.ops:
            if op.type == "fill_constant" and not op.inputs.get(
                    "ShapeTensor") and not op.inputs.get("ValueTensor"):
                name = (op.outputs.get("Out") or [None])[0]
                if name and len(_writer_idxs(block, name)) == 1:
                    out[name] = op
        return out

    def _replace_with_fill(self, block, i, src_fill, out_name, value,
                           dtype, op_role):
        block._remove_op(i)
        block._insert_op(
            i, "fill_constant", outputs={"Out": [out_name]},
            attrs={"shape": list(src_fill.attrs.get("shape", [])),
                   "value": float(value), "dtype": dtype,
                   "op_role": op_role})

    def _fold_one(self, block) -> bool:
        consts = self._consts(block)
        for i, op in enumerate(block.ops):
            out = (op.outputs.get("Out") or [None])[0]
            src = (op.inputs.get("X") or [None])[0]
            if out is None or src is None:
                continue
            if op.type == "scale" and not op.inputs.get("ScaleTensor"):
                s = float(op.attrs.get("scale", 1.0))
                b = float(op.attrs.get("bias", 0.0))
                after = bool(op.attrs.get("bias_after_scale", True))
                if src in consts:
                    v = float(consts[src].attrs.get("value", 0.0))
                    self._replace_with_fill(
                        block, i, consts[src], out,
                        v * s + b if after else (v + b) * s,
                        consts[src].attrs.get("dtype", "float32"),
                        op.attrs.get("op_role", 0))
                    return True
                widx = _writer_idxs(block, src)
                if len(widx) == 1 and widx[0] < i and after:
                    inner = block.ops[widx[0]]
                    # rewiring the outer scale to read inner's input is
                    # only sound if that input still holds the value
                    # inner saw — no op between them may rewrite it
                    if (inner.type == "scale"
                            and not inner.inputs.get("ScaleTensor")
                            and inner.attrs.get("bias_after_scale", True)
                            and inner.inputs.get("X")
                            and _no_hazard_between(
                                block, widx[0], i,
                                reads=inner.inputs["X"], writes=())):
                        s1 = float(inner.attrs.get("scale", 1.0))
                        b1 = float(inner.attrs.get("bias", 0.0))
                        # (x*s1+b1)*s+b == x*(s1*s) + (b1*s+b)
                        op.inputs["X"] = list(inner.inputs["X"])
                        op.set_attr("scale", s1 * s)
                        op.set_attr("bias", b1 * s + b)
                        return True
            elif op.type == "cast" and src in consts:
                self._replace_with_fill(
                    block, i, consts[src], out,
                    consts[src].attrs.get("value", 0.0),
                    op.attrs.get("out_dtype", "float32"),
                    op.attrs.get("op_role", 0))
                return True
        return False


# ---------------------------------------------------------------------------
# elementwise_add + activation fusion
# ---------------------------------------------------------------------------

def _grad_of(op_type: str):
    return lambda v: v == op_type


class _FusionPass(PatternRewritePass):
    """Shared machinery for pairwise producer->activation fusion with
    optional grad-pair fusion (training programs)."""

    def _check_edge(self, m, ctx, t: str, extra_consumers) -> bool:
        """The fused-away intermediate ``t`` must be an internal edge:
        written once, consumed only by the ops being fused, not
        protected."""
        block = m.block
        if ctx.is_protected(block, t):
            return False
        if len(_writer_idxs(block, t)) != 1:
            return False
        allowed = {id(o) for o in extra_consumers}
        return all(id(c) in allowed for c in _consumers(block, t))

    def _splice(self, block, new_op, anchor, dead) -> None:
        """Insert ``new_op`` right after ``anchor`` and remove the
        ``dead`` ops — all through the version-bumping mutators."""
        block._insert_op_obj(block.ops.index(anchor) + 1, new_op)
        for op in dead:
            block._remove_op(block.ops.index(op))


@register_pass
class FuseElewiseAddActPass(_FusionPass):
    """elementwise_add + {relu,sigmoid,tanh} -> fused_elemwise_activation
    (fuse_elewise_add_act_pass.cc).  In training programs the pair of
    generic_grad ops collapses into one generic_grad over the fused op."""

    name = "fuse_elewise_add_act"

    def __init__(self, **options):
        super().__init__(**options)
        pt = Pattern("elewise_add_act_train")
        x, y, t, out, g, tg = pt.vars("x y t out g tg")
        pt.op("elementwise_add", ins={"X": [x], "Y": [y]},
              outs={"Out": [t]})
        pt.op(ACTS, ins={"X": [t]}, outs={"Out": [out]})
        pt.op("generic_grad", ins={"I_X": [t], "G_Out": [g]},
              outs={"GI_X": [tg]})
        pt.op("generic_grad", ins={"G_Out": [tg]})
        pf = Pattern("elewise_add_act_fwd")
        x2, y2, t2, out2 = pf.vars("x y t out")
        pf.op("elementwise_add", ins={"X": [x2], "Y": [y2]},
              outs={"Out": [t2]})
        pf.op(ACTS, ins={"X": [t2]}, outs={"Out": [out2]})
        self.rules = [(pt, self._rewrite_train), (pf, self._rewrite_fwd)]

    def _fused_ops(self, m, with_grads: bool):
        block = m.block
        add, act = m.ops[0], m.ops[1]
        t, out = m.var("t"), m.var("out")
        attrs = {"functor_list": ["elementwise_add", act.type],
                 "axis": add.attrs.get("axis", -1),
                 "op_role": add.attrs.get("op_role", 0)}
        inter = unique_name(t + "@fuse_inter")
        fused = Operator(block, "fused_elemwise_activation",
                         {"X": list(add.inputs["X"]),
                          "Y": list(add.inputs["Y"])},
                         {"Out": [out], "IntermediateOut": [inter]},
                         attrs)
        if not with_grads:
            return fused, None
        act_g, add_g = m.ops[2], m.ops[3]
        g_ins = {"I_X": list(add.inputs["X"]),
                 "I_Y": list(add.inputs["Y"]),
                 "G_Out": list(act_g.inputs["G_Out"])}
        g_outs = {k: list(v) for k, v in add_g.outputs.items()}
        fused_g = Operator(block, "generic_grad", g_ins, g_outs,
                           {"fwd_type": "fused_elemwise_activation",
                            "fwd_attrs": dict(attrs),
                            "in_slots": ["X", "Y"],
                            "grad_slots": list(
                                add_g.attrs.get("grad_slots", [])),
                            "op_role": 1})
        return fused, fused_g

    def _common_ok(self, m, ctx, consumers_of_t) -> bool:
        block = m.block
        add, act = m.ops[0], m.ops[1]
        if not self._check_edge(m, ctx, m.var("t"), consumers_of_t):
            return False
        if len(_writer_idxs(block, m.var("out"))) != 1:
            return False
        return _no_hazard_between(
            block, m.index(0), m.index(1),
            reads=add.input_arg_names, writes=[m.var("t")])

    def _rewrite_fwd(self, m, ctx) -> bool:
        if not self._common_ok(m, ctx, m.ops[1:2]):
            return False
        fused, _ = self._fused_ops(m, with_grads=False)
        self._splice(m.block, fused, m.ops[1], m.ops[:2])
        return True

    def _rewrite_train(self, m, ctx) -> bool:
        block = m.block
        add, act, act_g, add_g = m.ops
        if act_g.attrs.get("fwd_type") != act.type:
            return False
        if add_g.attrs.get("fwd_type") != "elementwise_add":
            return False
        if (add_g.inputs.get("I_X") != add.inputs.get("X")
                or add_g.inputs.get("I_Y") != add.inputs.get("Y")):
            return False
        if not self._common_ok(m, ctx, [act, act_g]):
            return False
        tg = m.var("tg")
        if (len(_writer_idxs(block, tg)) != 1
                or not self._check_edge(m, ctx, tg, [add_g])):
            return False
        if not _no_hazard_between(
                block, m.index(2), m.index(3),
                reads=list(add.inputs["X"]) + list(add.inputs["Y"])
                + list(act_g.inputs["G_Out"]),
                writes=add_g.output_arg_names):
            return False
        fused, fused_g = self._fused_ops(m, with_grads=True)
        self._splice(block, fused_g, act_g, [act_g, add_g])
        self._splice(block, fused, act, [add, act])
        return True


@register_pass
class FuseBnActPass(_FusionPass):
    """batch_norm + activation -> fused_bn_activation
    (fuse_bn_act_pass.cc), with the same training-aware grad-pair fusion
    as fuse_elewise_add_act."""

    name = "fuse_bn_act"

    def __init__(self, **options):
        super().__init__(**options)
        pt = Pattern("bn_act_train")
        x, t, out, g, tg = pt.vars("x t out g tg")
        pt.op("batch_norm", ins={"X": [x]}, outs={"Y": [t]})
        pt.op(ACTS, ins={"X": [t]}, outs={"Out": [out]})
        pt.op("generic_grad", ins={"I_X": [t], "G_Out": [g]},
              outs={"GI_X": [tg]})
        pt.op("generic_grad", ins={"G_Y": [tg]})
        pf = Pattern("bn_act_fwd")
        x2, t2, out2 = pf.vars("x t out")
        pf.op("batch_norm", ins={"X": [x2]}, outs={"Y": [t2]})
        pf.op(ACTS, ins={"X": [t2]}, outs={"Out": [out2]})
        self.rules = [(pt, self._rewrite_train), (pf, self._rewrite_fwd)]

    def _fused_op(self, m) -> Operator:
        block = m.block
        bn, act = m.ops[0], m.ops[1]
        outs = {k: list(v) for k, v in bn.outputs.items()}
        outs["Y"] = [m.var("out")]
        return Operator(block, "fused_bn_activation",
                        {k: list(v) for k, v in bn.inputs.items()}, outs,
                        dict(bn.attrs, act_type=act.type))

    def _common_ok(self, m, ctx, consumers_of_t) -> bool:
        block = m.block
        bn = m.ops[0]
        if bn.attrs.get("use_global_stats"):
            return False
        if not self._check_edge(m, ctx, m.var("t"), consumers_of_t):
            return False
        if len(_writer_idxs(block, m.var("out"))) != 1:
            return False
        # moving bn down to the act position carries its state writes
        # (MeanOut/VarianceOut write the Mean/Variance vars in place)
        other_outs = [n for n in bn.output_arg_names if n != m.var("t")]
        return _no_hazard_between(
            block, m.index(0), m.index(1),
            reads=bn.input_arg_names,
            writes=[m.var("t")] + other_outs)

    def _rewrite_fwd(self, m, ctx) -> bool:
        if not self._common_ok(m, ctx, m.ops[1:2]):
            return False
        self._splice(m.block, self._fused_op(m), m.ops[1], m.ops[:2])
        return True

    def _rewrite_train(self, m, ctx) -> bool:
        block = m.block
        bn, act, act_g, bn_g = m.ops
        if act_g.attrs.get("fwd_type") != act.type:
            return False
        if bn_g.attrs.get("fwd_type") != "batch_norm":
            return False
        if bn_g.inputs.get("I_X") != bn.inputs.get("X"):
            return False
        if not self._common_ok(m, ctx, [act, act_g]):
            return False
        tg = m.var("tg")
        if not self._check_edge(m, ctx, tg, [bn_g]):
            return False
        grad_reads = [n for slot, ns in bn_g.inputs.items()
                      if slot != "G_Y" for n in ns]
        if not _no_hazard_between(
                block, m.index(2), m.index(3),
                reads=grad_reads + list(act_g.inputs["G_Out"]),
                writes=bn_g.output_arg_names):
            return False
        fused = self._fused_op(m)
        g_ins = {k: list(v) for k, v in bn_g.inputs.items()
                 if k != "G_Y"}
        g_ins["G_Y"] = list(act_g.inputs["G_Out"])
        fused_g = Operator(
            block, "generic_grad", g_ins,
            {k: list(v) for k, v in bn_g.outputs.items()},
            {"fwd_type": "fused_bn_activation",
             "fwd_attrs": dict(fused.attrs),
             "in_slots": list(bn_g.attrs.get("in_slots", [])),
             "grad_slots": list(bn_g.attrs.get("grad_slots", [])),
             "op_role": 1})
        self._splice(block, fused_g, act_g, [act_g, bn_g])
        self._splice(block, fused, act, [bn, act])
        return True


# ---------------------------------------------------------------------------
# gradient all-reduce coalescing
# ---------------------------------------------------------------------------

@register_pass
class CoalesceAllReducePass(Pass):
    """Bucket consecutive single-tensor c_allreduce_{sum,avg} launches
    into flattened c_allreduce_coalesced ops (fuse_all_reduce_op_pass.cc
    + coalesce_tensor semantics): per step, n collective launches become
    ceil(n/bucket_size).  Only strictly consecutive runs are touched — an
    op between two allreduces may consume a reduced value, and order
    within a run cannot matter (disjoint vars, checked)."""

    name = "coalesce_allreduce"
    COALESCABLE = {"c_allreduce_sum": "sum", "c_allreduce_avg": "avg"}

    def __init__(self, bucket_size: int = 32, **options):
        super().__init__(**options)
        self.bucket_size = max(int(bucket_size), 2)

    def _coalescable(self, op) -> bool:
        return (op.type in self.COALESCABLE
                and len(op.inputs.get("X", ())) == 1
                and len(op.outputs.get("Out", ())) == 1
                and set(op.inputs) == {"X"})

    def _key(self, op):
        return (op.type, int(op.attrs.get("ring_id", 0)))

    def _flush(self, block, seg, out_ops):
        """Coalesce one contiguous same-(type, ring) segment in place —
        emission order is preserved relative to every other op, so an
        interleaved run of mixed types/rings is never reordered (a later
        collective may read an earlier one's output)."""
        op_type, ring = self._key(seg[0])
        xs = [o.inputs["X"][0] for o in seg]
        outs = [o.outputs["Out"][0] for o in seg]
        # in-segment ordering must be irrelevant: no chaining, no dups
        if (len(seg) < 2 or len(set(xs)) != len(xs)
                or len(set(outs)) != len(outs)
                or any(x in outs and x != o.outputs["Out"][0]
                       for x, o in zip(xs, seg))):
            out_ops.extend(seg)
            return 0, 0
        removed = fused = 0
        for k in range(0, len(seg), self.bucket_size):
            chunk = seg[k:k + self.bucket_size]
            if len(chunk) < 2:
                out_ops.extend(chunk)
                continue
            attrs = {"ring_id": ring,
                     "reduce": self.COALESCABLE[op_type],
                     "use_calc_stream": True,
                     "op_role": chunk[0].attrs.get("op_role", 1)}
            # the mesh-axis stamp (insert_allreduce_ops) survives
            # coalescing so shard_collectives maps ring -> axis
            # deterministically from the op itself
            if chunk[0].attrs.get("mesh_axis"):
                attrs["mesh_axis"] = chunk[0].attrs["mesh_axis"]
            out_ops.append(Operator(
                block, "c_allreduce_coalesced",
                {"X": [o.inputs["X"][0] for o in chunk]},
                {"Out": [o.outputs["Out"][0] for o in chunk]},
                attrs))
            removed += len(chunk) - 1
            fused += len(chunk)
        return removed, fused

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        out_ops: list = []
        seg: list = []
        removed = launches_fused = 0

        def flush():
            nonlocal removed, launches_fused
            if seg:
                r, f = self._flush(block, seg, out_ops)
                removed += r
                launches_fused += f
                seg.clear()

        for op in block.ops:
            if self._coalescable(op):
                if seg and self._key(op) != self._key(seg[0]):
                    flush()
                seg.append(op)
            else:
                flush()
                out_ops.append(op)
        flush()
        if removed:
            block.ops = out_ops
            block.program._bump_version()
        return {"ops_removed": removed, "launches_fused": launches_fused}


# ---------------------------------------------------------------------------
# identity cleanup
# ---------------------------------------------------------------------------

@register_pass
class PruneIdentityPass(Pass):
    """Remove no-op plumbing (identity_op_clean_pass.cc): scale(1.0, 0.0),
    cast to the var's own device dtype, and assign of a write-once
    non-persistable var — consumers are rewired to the source var."""

    name = "prune_identity"

    def _is_identity(self, block, op) -> bool:
        if op.type == "scale":
            return (not op.inputs.get("ScaleTensor")
                    and float(op.attrs.get("scale", 1.0)) == 1.0
                    and float(op.attrs.get("bias", 0.0)) == 0.0)
        if op.type == "cast":
            src = (op.inputs.get("X") or [None])[0]
            v = block._find_var_recursive(src) if src else None
            if v is None or v.dtype is None:
                return False
            from ..framework import device_dtype
            try:
                return device_dtype(op.attrs.get("out_dtype", "float32")) \
                    == device_dtype(v.dtype)
            except (ValueError, TypeError):
                return False
        if op.type == "assign":
            src = (op.inputs.get("X") or [None])[0]
            v = block._find_var_recursive(src) if src else None
            # persistable sources are the snapshot idiom (read-old-value
            # before an in-place state update) — never prune those
            return v is not None and not v.persistable
        return False

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        removed = 0
        for _ in range(len(block.ops) + 16):
            if not self._prune_one(block, ctx):
                break
            removed += 1
        return {"ops_removed": removed}

    def _prune_one(self, block, ctx: PassContext) -> bool:
        prog = block.program
        for i, op in enumerate(block.ops):
            if not self._is_identity(block, op):
                continue
            src = (op.inputs.get("X") or [None])[0]
            out = (op.outputs.get("Out") or [None])[0]
            if src is None or out is None or src == out:
                continue
            if ctx.is_protected(block, out):
                continue
            if len(_writer_idxs(block, out)) != 1:
                continue
            if len(_writer_idxs(block, src)) > 1:
                continue
            # every consumer must live in THIS block (sub-block captures
            # and attr-carried names can't be rewired safely)
            other = [o for b in prog.blocks for o in b.ops
                     if b is not block and out in _op_reads(b, o)]
            if other or any(out in repr(o.attrs) for b in prog.blocks
                            for o in b.ops):
                continue
            for o in block.ops:
                if o is op:
                    continue
                for slot, names in o.inputs.items():
                    if out in names:
                        o.inputs[slot] = [src if n == out else n
                                          for n in names]
            block._remove_op(i)
            return True
        return False


# ---------------------------------------------------------------------------
# legacy shim target
# ---------------------------------------------------------------------------

@register_pass
class MemoryOptimizeLegacyPass(Pass):
    """The 1.x memory_optimize transpiler routed through the pass manager:
    a declared-read-only no-op (XLA owns buffer liveness on this stack),
    but one that *runs* — callers see a pass::memory_optimize_legacy span
    and counter instead of silence."""

    name = "memory_optimize_legacy"
    writes = frozenset()

    def apply(self, program, ctx: PassContext) -> Dict[str, int]:
        return {"programs_seen": 1}


# ---------------------------------------------------------------------------
# BuildStrategy -> pipeline wiring (build_strategy.cc AppendPass analog)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# dispatched collectives -> sharding constraints (the SPMD sharding plane)
# ---------------------------------------------------------------------------

@register_pass
class ShardCollectivesPass(Pass):
    """Rewrite Fleet's ring-id collectives into ``shard_constraint`` ops —
    the pjit-first half of the sharding plane (parallel/sharding.py,
    docs/sharding.md).  A dispatched ``c_allreduce_*`` is an opaque
    launch XLA cannot fuse or overlap; under a whole-step sharded compile
    the same synchronisation is a *replicated sharding constraint* on the
    gradient: GSPMD inserts (and schedules, and fuses) the reduce the
    constraint implies.  The op keeps its dataflow position, records its
    origin + mesh axis (``mesh_axis`` attr stamped by
    ``insert_allreduce_ops``, else the ring registry's mapping), and
    lowers to ``lax.with_sharding_constraint`` when a plan's mesh is live
    — identity otherwise, so the rewritten program still runs unsharded.

    The per-op dispatch path is untouched for programs that never opt in
    (``BuildStrategy.sharding`` unset): those keep lowering collectives
    through ``LoweringContext.mesh_axes`` as before.
    """

    name = "shard_collectives"
    REWRITABLE = frozenset({
        "c_allreduce_sum", "c_allreduce_avg", "c_allreduce_coalesced",
        "c_broadcast",
    })

    def _axis_of(self, op) -> Optional[str]:
        ax = op.attrs.get("mesh_axis")
        if ax:
            return str(ax)
        from ...parallel import mesh as mesh_registry
        return mesh_registry.axis_for_ring(
            int(op.attrs.get("ring_id", 0)))

    def apply_block(self, block, ctx: PassContext) -> Dict[str, int]:
        from .. import trace
        implied = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            xs = list(op.inputs.get("X", ()))
            outs = list(op.outputs.get("Out", ()))
            if op.type not in self.REWRITABLE or not xs \
                    or len(xs) != len(outs):
                i += 1
                continue
            new = Operator(
                block, "shard_constraint",
                {"X": xs}, {"Out": outs},
                {"spec": [],                        # replicated = synced
                 "origin": op.type,
                 "ring_id": int(op.attrs.get("ring_id", 0)),
                 "mesh_axis": self._axis_of(op) or "",
                 "op_role": op.attrs.get("op_role", 1)})
            block._remove_op(i)
            block._insert_op_obj(i, new)
            implied += len(xs)
            i += 1
        if implied:
            trace.metrics().counter("sharding.collectives_implied").inc(
                implied)
        return {"collectives_implied": implied}


def passes_for_build_strategy(build_strategy) -> List[Pass]:
    """Instantiate the pass list a BuildStrategy's knobs select, in the
    canonical order: fold -> fuse -> kernel_tier -> clean -> amp -> dce
    -> coalesce.  The kernel tier runs after the pairwise fusions (they
    never overlap its chains) and before AMP (the fused attention op is
    white-listed MXU compute, so the bf16 rewrite sees ONE op instead of
    the six-op chain); AMP runs before DCE (which sweeps the cast
    orphans the redundancy pruner leaves)."""
    from . import amp as _amp  # noqa: F401 — registers the AMP passes
    from . import kernel_tier as _kt  # noqa: F401 — registers the tier
    bs = build_strategy
    mem = bool(getattr(bs, "memory_optimize", None))
    tier = bool(getattr(bs, "kernel_tier", False))
    specs = []
    if getattr(bs, "constant_folding", False) or mem:
        specs.append(("constant_fold", {}))
    if getattr(bs, "fuse_elewise_add_act_ops", False):
        specs.append(("fuse_elewise_add_act", {}))
    if getattr(bs, "fuse_bn_act_ops", False):
        specs.append(("fuse_bn_act", {}))
    if tier or getattr(bs, "fuse_attention", False):
        specs.append(("fuse_attention", {}))
    if tier or getattr(bs, "fuse_paged_attention", False):
        specs.append(("fuse_paged_attention", {}))
    if tier or getattr(bs, "fuse_sparse_embedding", False):
        specs.append(("fuse_sparse_embedding", {}))
    if tier or getattr(bs, "fuse_optimizer", False) \
            or getattr(bs, "fuse_all_optimizer_ops", False):
        specs.append(("fuse_optimizer", {}))
    if mem:
        specs.append(("prune_identity", {}))
    if getattr(bs, "amp", False):
        specs.append(("amp_bf16", {
            "dtype": getattr(bs, "amp_dtype", "bfloat16") or "bfloat16",
            "custom_white_list": getattr(bs, "amp_custom_white_list",
                                         None),
            "custom_black_list": getattr(bs, "amp_custom_black_list",
                                         None)}))
        if getattr(bs, "prune_redundant_casts", True):
            specs.append(("prune_redundant_casts", {}))
    if getattr(bs, "enable_dce", False) or mem:
        specs.append(("dce", {}))
    if getattr(bs, "fuse_all_reduce_ops", False):
        specs.append(("coalesce_allreduce", {
            "bucket_size": int(
                getattr(bs, "fuse_grad_size_in_num", 32) or 32)}))
    if getattr(bs, "sharding", None):
        # last: whatever allreduce shape survives (coalesced or per-grad)
        # is rewritten into sharding constraints for the pjit step
        specs.append(("shard_collectives", {}))
    return [create_pass(name, **kw) for name, kw in specs]
