"""Checkpoint save/load + inference model export.

Reference: python/paddle/fluid/io.py — save_vars:238, save_persistables:620,
load_persistables:994, save/load_inference_model:1198,1411.

Model format: `__model__` is the ProgramDesc protobuf (the reference's own
wire format, re-specified in proto/framework.proto), with feed/fetch ops
spliced in exactly as the reference does (io.py:1151,1179) and an
OpVersionMap pinning op semantics (fluid/op_version_registry.py).  Params
are one .npz per save on the native path (fast, safe), and the loader also
reads the reference's binary formats (per-var LoDTensor files and
save_combine concatenations) so artifacts produced by the reference load
directly.  The pre-round-5 pickled-IR format is refused with a re-export
message — pickle is not a deployment contract.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .core import global_scope
from .framework import Program, Parameter, default_main_program


def _vars_to_save(program: Program, predicate=None):
    out = []
    for v in program.global_block().vars.values():
        if not v.persistable:
            continue
        if predicate and not predicate(v):
            continue
        out.append(v)
    return out


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Atomic archive write: the npz is serialized to memory and committed
    via tmp+fsync+``os.replace`` (checkpoint.atomic_write_bytes — the
    PR-2 PersistentCache idiom), so a crash mid-save leaves the PREVIOUS
    archive intact instead of a torn .npz that refuses to load."""
    import io as _io
    from .checkpoint import atomic_write_bytes
    main_program = main_program or default_main_program()
    scope = global_scope()
    if vars is None:
        vars = _vars_to_save(main_program, predicate)
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if not isinstance(v, str) else v
        val = scope.find_var(name)
        if val is not None:
            arrays[name] = np.asarray(val)
    path = os.path.join(dirname, filename or "params.npz")
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(path, buf.getvalue())
    return path


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, strict=False):
    """With ``strict=True`` (the checkpoint-restore contract) a requested
    var missing from the archive, or present with a different
    shape/dtype than the program declares, raises naming every offender —
    the legacy default silently skips, which turns a truncated save into
    randomly re-initialised weights."""
    import jax.numpy as jnp
    scope = global_scope()
    path = os.path.join(dirname, filename or "params.npz")
    data = np.load(path, allow_pickle=False)
    main_program = main_program or default_main_program()
    if vars is None and predicate is not None:
        vars = _vars_to_save(main_program, predicate)
    wanted = None
    if vars is not None:
        wanted = {v.name if not isinstance(v, str) else v for v in vars}
    if strict:
        requested = wanted if wanted is not None else {
            v.name for v in _vars_to_save(main_program)}
        missing = sorted(requested - set(data.files))
        mismatched = []
        block = main_program.global_block()
        for name in sorted(requested & set(data.files)):
            v = block.vars.get(name)
            if v is None:
                continue
            arr = data[name]
            shp = list(v.shape or [])
            if shp and all(int(x) >= 0 for x in shp) \
                    and list(arr.shape) != shp:
                mismatched.append(f"{name}: archive shape "
                                  f"{list(arr.shape)} != var shape {shp}")
            try:
                if v.dtype is not None \
                        and np.dtype(str(v.dtype)) != arr.dtype:
                    mismatched.append(f"{name}: archive dtype {arr.dtype} "
                                      f"!= var dtype {v.dtype}")
            except TypeError:
                pass        # non-numpy dtype (bf16 etc): archive wins
        if missing or mismatched:
            raise ValueError(
                f"load_vars(strict): archive {path} does not satisfy the "
                f"request.  Missing vars: {', '.join(missing) or 'none'}.  "
                f"Mismatches: {'; '.join(mismatched) or 'none'}")
    for name in data.files:
        if wanted is not None and name not in wanted:
            continue
        scope.set_var(name, jnp.asarray(data[name]))


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: isinstance(v, Parameter),
                     filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def _splice_feed_fetch(program: Program, feed_names, fetch_names) -> None:
    """Add reference-style feed/fetch holder vars + ops (io.py:1151,1179):
    feed ops write each input var from the FEED_MINIBATCH holder, fetch
    ops read each target into the FETCH_LIST holder, `col` = position."""
    block = program.global_block()
    feed_var = block.create_var(name="feed", dtype=None)
    feed_var.proto_var_type = "feed"
    feed_var.persistable = True
    fetch_var = block.create_var(name="fetch", dtype=None)
    fetch_var.proto_var_type = "fetch"
    fetch_var.persistable = True
    from .framework import Operator
    feed_ops = [Operator(block, "feed", {"X": ["feed"]}, {"Out": [name]},
                         {"col": i})
                for i, name in enumerate(feed_names)]
    fetch_ops = [Operator(block, "fetch", {"X": [name]},
                          {"Out": ["fetch"]}, {"col": i})
                 for i, name in enumerate(fetch_names)]
    block.ops[:0] = feed_ops
    block.ops.extend(fetch_ops)
    program._bump_version()


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Export `__model__` (ProgramDesc protobuf) + params — io.py:1198
    analog.  With params_filename the params are ALSO written in the
    reference save_combine binary format next to the native npz, so the
    artifact is consumable by reference tooling."""
    from . import proto_serde
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    # clone(for_test) strips the backward tail; _prune then cuts to the
    # target-reachable subgraph (reference io.py:1198 prunes + optimizes —
    # an exported model must not carry loss/metric ops)
    infer_prog = main_program.clone(for_test=True)._prune(
        [v.name for v in target_vars])
    manifest = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
    }
    _splice_feed_fetch(infer_prog, manifest["feed_names"],
                       manifest["fetch_names"])
    # deliberate human-readable sidecar (feed/fetch are authoritative in
    # the protobuf's feed/fetch ops; this is for quick shell inspection)
    with open(os.path.join(dirname, "__model__.json"), "w") as f:
        json.dump(manifest, f)
    with open(os.path.join(dirname, model_filename or "__model__"),
              "wb") as f:
        f.write(proto_serde.program_to_proto_bytes(infer_prog))
    if not program_only:
        save_persistables(executor, dirname, main_program)
        if params_filename:
            scope = global_scope()
            arrays = {}
            for v in _vars_to_save(infer_prog):
                if getattr(v, "proto_var_type", None) in ("feed", "fetch"):
                    continue
                val = scope.find_var(v.name)
                if val is None:
                    # the combined format is positional (sorted names); a
                    # gap would shift every later tensor onto the wrong var
                    raise ValueError(
                        f"persistable var '{v.name}' has no value in the "
                        f"scope — run the startup program before exporting")
                arrays[v.name] = np.asarray(val)
            proto_serde.save_combined_params(
                os.path.join(dirname, params_filename), arrays)
    return manifest["fetch_names"]


def _load_reference_params(dirname, program, params_filename=None):
    """Read params saved in the reference's binary formats: one combined
    save_combine file, or one LoDTensor file per persistable var."""
    from . import proto_serde
    import jax.numpy as jnp
    scope = global_scope()
    names = [v.name for v in program.global_block().vars.values()
             if v.persistable
             and getattr(v, "proto_var_type", None) not in ("feed", "fetch")]
    if params_filename:
        arrays = proto_serde.load_combined_params(
            os.path.join(dirname, params_filename), names)
        for name, arr in arrays.items():
            scope.set_var(name, jnp.asarray(arr))
        return
    for name in names:
        path = os.path.join(dirname, name)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no param file for persistable var '{name}' in {dirname}")
        with open(path, "rb") as f:
            arr, _lod, _ = proto_serde.deserialize_lod_tensor(f.read())
        scope.set_var(name, jnp.asarray(arr))


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Load a `__model__` ProgramDesc (this framework's OR the
    reference's) + params (native npz, reference combined file, or
    reference per-var files) — io.py:1411 analog."""
    from . import proto_serde
    path = os.path.join(dirname, model_filename or "__model__")
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] == b"\x80\x04" or data[:2] == b"\x80\x03":
        raise RuntimeError(
            f"{path} is a legacy pickled-IR artifact; re-export it with "
            f"save_inference_model — the model format is now the "
            f"ProgramDesc protobuf")
    program = proto_serde.program_from_proto_bytes(data)
    feed_names, fetch_names = proto_serde.strip_feed_fetch_ops(program)
    manifest_path = os.path.join(dirname, "__model__.json")
    if not fetch_names and os.path.exists(manifest_path):
        # program had no feed/fetch ops (program_only legacy export)
        with open(manifest_path) as f:
            manifest = json.load(f)
        feed_names = manifest["feed_names"]
        fetch_names = manifest["fetch_names"]
    if params_filename:
        # an explicit params file always wins over a sibling params.npz
        _load_reference_params(dirname, program, params_filename)
    elif os.path.exists(os.path.join(dirname, "params.npz")):
        load_persistables(executor, dirname, program)
    else:
        _load_reference_params(dirname, program, None)
    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def get_program_persistable_vars(program):
    return [v for v in program.global_block().vars.values() if v.persistable]
