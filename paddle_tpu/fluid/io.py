"""Checkpoint save/load + inference model export.

Reference: python/paddle/fluid/io.py — save_vars:238, save_persistables:620,
load_persistables:994, save/load_inference_model:1198,1411.  TPU-native
format: one .npz per save (vars as named numpy arrays) plus a JSON program
manifest for inference models — functionally equivalent to the reference's
`__model__` ProgramDesc + per-var files, without protobuf coupling.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import List, Optional

import numpy as np

from .core import global_scope
from .framework import Program, Parameter, default_main_program


def _vars_to_save(program: Program, predicate=None):
    out = []
    for v in program.global_block().vars.values():
        if not v.persistable:
            continue
        if predicate and not predicate(v):
            continue
        out.append(v)
    return out


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or default_main_program()
    scope = global_scope()
    if vars is None:
        vars = _vars_to_save(main_program, predicate)
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if not isinstance(v, str) else v
        val = scope.find_var(name)
        if val is not None:
            arrays[name] = np.asarray(val)
    path = os.path.join(dirname, filename or "params.npz")
    np.savez(path, **arrays)
    return path


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp
    scope = global_scope()
    path = os.path.join(dirname, filename or "params.npz")
    data = np.load(path, allow_pickle=False)
    main_program = main_program or default_main_program()
    wanted = None
    if vars is not None:
        wanted = {v.name if not isinstance(v, str) else v for v in vars}
    for name in data.files:
        if wanted is not None and name not in wanted:
            continue
        scope.set_var(name, jnp.asarray(data[name]))


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: isinstance(v, Parameter),
                     filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Export program(pickled IR) + params — io.py:1198 analog."""
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    # clone(for_test) strips the backward tail; _prune then cuts to the
    # target-reachable subgraph (reference io.py:1198 prunes + optimizes —
    # an exported model must not carry loss/metric ops)
    infer_prog = main_program.clone(for_test=True)._prune(
        [v.name for v in target_vars])
    manifest = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
    }
    with open(os.path.join(dirname, "__model__.json"), "w") as f:
        json.dump(manifest, f)
    with open(os.path.join(dirname, model_filename or "__model__"), "wb") as f:
        pickle.dump(infer_prog, f)
    if not program_only:
        save_persistables(executor, dirname, main_program,
                          filename=params_filename)
    return manifest["fetch_names"]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or "__model__"), "rb") as f:
        program = pickle.load(f)
    with open(os.path.join(dirname, "__model__.json")) as f:
        manifest = json.load(f)
    load_persistables(executor, dirname, program, filename=params_filename)
    fetch_vars = [program.global_block().var(n)
                  for n in manifest["fetch_names"]]
    return program, manifest["feed_names"], fetch_vars


def get_program_persistable_vars(program):
    return [v for v in program.global_block().vars.values() if v.persistable]
