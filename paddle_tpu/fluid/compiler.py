"""CompiledProgram / BuildStrategy / ExecutionStrategy facades.

Reference: python/paddle/fluid/compiler.py:87 CompiledProgram,
with_data_parallel:163 -> C++ ParallelExecutor + BuildStrategy's 30+ knobs
(framework/details/build_strategy.h:71-195).  TPU-native: data parallelism is
a sharding decision, not a graph rewrite — with_data_parallel() attaches a
jax.sharding.Mesh over the local chips and the Executor jits the SAME step
function with batch-sharded inputs; XLA inserts the gradient all-reduce that
AllReduceOpHandle (details/all_reduce_op_handle.cc:60) performed explicitly.
Most BuildStrategy knobs are therefore accepted-and-ignored: fusion/memory
passes are XLA's job (SURVEY §7 step 5).
"""
from __future__ import annotations

from typing import Optional

from . import trace


class ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class BuildStrategy:
    """Knob container (details/build_strategy.h).  Since the pass
    framework landed (fluid/passes/, docs/passes.md) the rewrite knobs
    are REAL: each one selects a registered Program-IR pass that
    CompiledProgram applies before the Executor caches the lowered
    function (passes.passes_for_build_strategy is the
    build_strategy.cc AppendPass analog).  Knobs that map to XLA concepts
    (enable_inplace -> buffer donation, sync_batch_norm) keep their
    executor-side meaning; the remainder stay settable for API parity."""

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = 0
        # directory: the pipeline dumps one Graphviz .dot per pass stage
        self.debug_graphviz_path = ""
        self.enable_inplace = True          # -> buffer donation (default on)
        # True -> constant_fold + prune_identity + dce passes (the 1.x
        # memory_optimize contract: shrink the live set / op stream)
        self.memory_optimize = None
        # REAL since the kernel tier landed: legacy alias for
        # fuse_optimizer (framework/ir/fuse_optimizer_ops_pass analog)
        self.fuse_all_optimizer_ops = False
        self.fuse_all_reduce_ops = False     # -> coalesce_allreduce pass
        self.fuse_grad_size_in_num = 32      # allreduce bucket size (ops)
        self.fuse_elewise_add_act_ops = False  # -> fuse_elewise_add_act
        self.fuse_bn_act_ops = False           # -> fuse_bn_act
        # Pallas kernel tier (fluid/passes/kernel_tier.py,
        # docs/performance.md "Custom kernel tier"): pattern-rewrite the
        # naive attention chain onto fused_multihead_attention (flash
        # kernel on TPU), lookup_table+pool chains onto
        # fused_embedding_pool (fused gather/scatter-add), and runs of
        # per-param adam/lamb/momentum updates onto one fused bucket
        # update.  kernel_tier=True is the umbrella for all three.
        self.kernel_tier = False
        self.fuse_attention = False            # -> fuse_attention
        self.fuse_paged_attention = False      # -> fuse_paged_attention
        self.fuse_sparse_embedding = False     # -> fuse_sparse_embedding
        self.fuse_optimizer = False            # -> fuse_optimizer
        self.enable_dce = False                # -> dce pass (fetch-seeded)
        self.constant_folding = False          # -> constant_fold pass
        # bf16 mixed precision as a compiler plane (passes/amp.py):
        # amp -> amp_bf16 pass (white/black-list cast insertion with the
        # grad halves kept dtype-consistent), followed by the
        # prune_redundant_casts cleanup unless disabled
        self.amp = False
        self.amp_dtype = "bfloat16"
        self.amp_custom_white_list = None
        self.amp_custom_black_list = None
        self.prune_redundant_casts = True
        # the unified SPMD sharding plane (parallel/sharding.py,
        # docs/sharding.md): "dp" | "tp" | "fsdp" lower a regex
        # PartitionSpec rule set over every param/grad/optimizer
        # accumulator, the executor compiles the WHOLE step as one
        # sharded (pjit) executable with buffer donation, and the
        # shard_collectives pass rewrites Fleet's ring-id allreduce ops
        # into sharding constraints (0 dispatched collectives).  A custom
        # [(regex, PartitionSpec), ...] list is accepted too.
        self.sharding = None
        # optional {"axis": size, ...} mesh override; default is a
        # 1-axis mesh over all local devices (dp/fsdp -> "dp", tp -> "tp")
        self.sharding_mesh = None
        # profile-guided self-tuning (fluid/autotune.py,
        # docs/performance.md "Auto-tuning"): True opts this program
        # into the executor-side search — bucket edges, dispatch
        # fusion/inflight depth, and the kernel-tier crossover tune once
        # per fingerprint on the first run, and persisted winners apply
        # with zero probe cost on restart
        self.auto_tune = False
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.sync_batch_norm = False        # -> sync_batch_norm op psum
        self.num_trainers = 1
        self.trainer_id = 0
        self.trainers_endpoints = []
        self.collective_mode = None
        self.nccl_comm_num = 1


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0                # XLA schedules; inert
        self.num_iteration_per_drop_scope = 1
        # num_iteration_per_run is REAL since the async pipeline landed
        # (fluid/async_pipeline.py): K > 1 stamps the program's
        # steps_per_dispatch hint, and the AsyncStepRunner drives K steps
        # through one lax.scan executable per Python dispatch — the
        # reference's "run K iterations per PE invocation" contract
        self.num_iteration_per_run = 1
        self.allow_op_delay = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy: Optional[BuildStrategy] = None,
                 exec_strategy: Optional[ExecutionStrategy] = None):
        self._program = getattr(program_or_graph, "_program", program_or_graph)
        self._build_strategy = build_strategy or BuildStrategy()
        self._mesh = None
        self._sharding_plan = None
        self._is_data_parallel = False
        self._ir_passes_applied = False
        # forwarded so Executor.run can treat us like a Program
        self._hints = self._program._hints
        if getattr(self._build_strategy, "auto_tune", False):
            # the hint rides the Program (shared dict) so the executor
            # sees it after the CompiledProgram facade unwraps
            self._program._hints["auto_tune"] = True
        if exec_strategy is not None:
            self._apply_exec_strategy(exec_strategy)
        trace.metrics().counter("compiler.compiled_programs").inc()

    def _apply_exec_strategy(self, exec_strategy):
        k = int(getattr(exec_strategy, "num_iteration_per_run", 1) or 1)
        if k > 1:
            self._program._hints["steps_per_dispatch"] = k
        else:
            # explicit k=1 must undo an earlier strategy's hint — the
            # hints dict is shared with the underlying Program
            self._program._hints.pop("steps_per_dispatch", None)

    def _ensure_sharding_plan(self):
        """Lower ``BuildStrategy.sharding`` into a ShardingPlan once, at
        first run (the program's params and shapes exist by then).  The
        mesh defaults to the shared process mesh or a fresh 1-axis mesh
        over all local devices (``sharding_mesh`` overrides); the plan is
        what the executor's sharded-compile path consumes."""
        mode = getattr(self._build_strategy, "sharding", None)
        if not mode or self._sharding_plan is not None:
            return self._sharding_plan
        from ..parallel import sharding as shard_plane
        from ..parallel import mesh as mesh_registry
        mesh = self._mesh
        axes = getattr(self._build_strategy, "sharding_mesh", None)
        if mesh is None and axes:
            mesh = mesh_registry.build_mesh(dict(axes))
        self._sharding_plan = shard_plane.build_plan(
            program=self._program, mode=mode, mesh=mesh)
        self._program._hints["sharding"] = self._sharding_plan.describe()
        if trace.enabled():
            trace.instant("sharding_plan", cat="compile",
                          args=self._sharding_plan.describe())
        return self._sharding_plan

    def _apply_ir_passes(self, fetch_names=()):
        """Run the BuildStrategy-selected pass pipeline over the program,
        once, before the executor fingerprints it (the reference applies
        build-strategy passes when ParallelExecutor materialises the
        graph).  Called by Executor.run with the first run's fetch list —
        the DCE seed and the rewrite protection set.  The rewrite is
        in-place and version-bumped, so every executor cache keyed on the
        old fingerprint is dead the moment a pass mutates."""
        if self._ir_passes_applied:
            return
        self._ir_passes_applied = True
        from . import passes
        hint_fg = self._program._hints.get("fuse_grad_size_in_num")
        if hint_fg is not None:
            # auto-tuner override: the hint travels with the program so a
            # persisted winning config re-applies without a BuildStrategy
            self._build_strategy.fuse_grad_size_in_num = int(hint_fg)
        plist = passes.passes_for_build_strategy(self._build_strategy)
        gv = self._build_strategy.debug_graphviz_path or None
        if not plist and not gv:
            return
        pipe = passes.PassPipeline(plist, graphviz_path=gv)
        if any(p.name == "dce" for p in plist):
            # DCE permanently removes ops unreachable from THIS fetch set;
            # the executor uses the recorded seed to turn a later fetch of
            # a pruned var into an actionable error instead of a bare
            # KeyError deep in the trace
            self._program._hints["ir_pass_dce_targets"] = \
                [str(n) for n in fetch_names]
        _t0 = trace.now() if trace.enabled() else 0
        stats = pipe.apply(self._program, targets=fetch_names,
                           build_strategy=self._build_strategy,
                           sharding_plan=self._sharding_plan)
        if _t0:
            trace.complete("compiler::apply_ir_passes", _t0, cat="compile",
                           args={p: dict(s) for p, s in stats.items()})
        return stats

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """Local multi-chip DP: build a 1-axis device mesh over the chips."""
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._apply_exec_strategy(exec_strategy)
        from ..parallel.mesh import build_data_parallel_mesh
        _t0 = trace.now() if trace.enabled() else 0
        self._mesh = build_data_parallel_mesh(places)
        if _t0:
            trace.complete("compiler::with_data_parallel", _t0,
                           cat="compile",
                           args={"devices": int(self._mesh.size)})
        self._is_data_parallel = True
        if self._build_strategy.sync_batch_norm:
            self._program._hints["sync_batch_norm"] = True
        return self

    def _with_inference_optimize(self, config):
        return self

    @property
    def program(self):
        return self._program
