"""fluid.wrapped_decorator analog: signature-preserving decorator
helpers (the reference wraps `decorator.decorator`; functools does the
same job without the dependency)."""
from __future__ import annotations

import contextlib
import functools

__all__ = ["wrap_decorator", "signature_safe_contextmanager"]


def wrap_decorator(decorator_func):
    @functools.wraps(decorator_func)
    def __impl__(func):
        return functools.wraps(func)(decorator_func(func))
    return __impl__


signature_safe_contextmanager = contextlib.contextmanager
