"""fluid.lod_tensor analog (reference python/paddle/fluid/lod_tensor.py).

LoD design note (SURVEY §7 hard part #1): ragged batches travel as padded
arrays + per-row lengths on this stack; a "LoDTensor" here is a numpy
array carrying `recursive_sequence_lengths` metadata so the reference's
creation helpers keep their contract."""
from __future__ import annotations

import numpy as np

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


class _LoDArray(np.ndarray):
    def recursive_sequence_lengths(self):
        return self._rec_lens

    def lod(self):
        offs = [0]
        for ln in self._rec_lens[0]:
            offs.append(offs[-1] + ln)
        return [offs]


def create_lod_tensor(data, recursive_seq_lens, place=None):
    if isinstance(data, list):
        flat = np.concatenate([np.asarray(d).reshape(-1, 1) for d in data])
        recursive_seq_lens = [[len(np.asarray(d)) for d in data]]
        data = flat
    arr = np.asarray(data).view(_LoDArray)
    total = sum(recursive_seq_lens[-1])
    if total != arr.shape[0]:
        raise ValueError(
            f"sum of sequence lengths {total} != rows {arr.shape[0]}")
    arr._rec_lens = [list(l) for l in recursive_seq_lens]
    return arr


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    rows = sum(recursive_seq_lens[-1])
    data = np.random.randint(low, high + 1,
                             size=[rows] + list(base_shape)).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
