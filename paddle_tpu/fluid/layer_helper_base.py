"""fluid.layer_helper_base analog: the LayerHelper base surface
(reference layer_helper_base.py) — one class serves both tiers here."""
from .layer_helper import LayerHelper as LayerHelperBase

__all__ = ["LayerHelperBase"]
