"""Always-on flight recorder: a bounded ring of structured wide events.

Reference: the reference stack keeps failure forensics next to the
profiler (``PADDLE_ENFORCE`` error stacks annotate what the process was
doing when it died); aviation flight recorders are the cleaner model —
a small, always-on ring of high-signal records that survives to the
post-mortem.  The trace plane (fluid/trace.py) is the opposite design
point: rich but opt-in and unbounded-ish.  This module is the third
leg: **one wide event per executor step and per served request**,
recorded even with ``FLAGS_enable_trace`` off, cheap enough that the
ci_smoke gate holds a recorder-on demo loop within 5% of recorder-off.

A wide event is one flat dict carrying everything an incident
responder asks first:

* step records — ``{"kind": "step", "seq", "ts_us", "step", "dur_us",
  "bucket", "batch_valid", "compile_miss", "fp", "n_fetch", "scan",
  "inflight", "goodput_ratio", "rss_bytes", "hbm_peak_bytes",
  "trace_id"}`` (trace_id present when the step ran under a serving
  batch's context);
* request records — ``{"kind": "request", "seq", "ts_us", "trace_id",
  "batch_id", "rows", "batch_rows", "bucket", "queue_us", "device_us",
  "latency_us", "outcome"}`` (outcome ``ok`` / ``timeout`` /
  ``rejected`` / ``error``);
* marker records — ``kind`` ``"preempt"`` / ``"incident"`` / ... from
  the elastic plane and the SLO watchdog.

Design for the hot path: ``record()`` costs one enabled-boolean, one
dict build, and one lock-guarded ring-slot store — no serialization, no
allocation proportional to history.  Gauge sampling (goodput ratio,
HBM, rss) happens in :func:`record_step` through cached instrument
references; rss is re-read from ``/proc`` at most once per second.

Gating: ``FLAGS_flight_recorder`` (default ON — the whole point is
being there when nobody armed anything) and
``FLAGS_flight_recorder_events`` (ring capacity, default 4096).  The
SLO watchdog (fluid/watchdog.py) reads ``completions`` (steps + ok
requests only — a rejection storm is not liveness) as its progress
signal and embeds ``snapshot()`` into diagnostic bundles.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import trace

__all__ = [
    "FlightRecorder", "recorder", "enabled", "record", "record_step",
    "record_request", "configure", "reset", "rss_bytes",
]

class FlightRecorder:
    """Fixed-capacity ring of wide-event dicts.  ``total`` counts every
    record ever written; ``completions`` counts only records that mean
    WORK COMPLETED (steps, ok requests) — the watchdog's progress
    signal, so a storm of rejections/timeouts from a wedged device
    never masquerades as liveness.  The ring keeps the last
    ``capacity`` records in arrival order."""

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        self.capacity = max(16, int(capacity))
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._buf: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self._n = 0                     # total records ever written
        self._done = 0                  # completion records only

    @property
    def total(self) -> int:
        """Records written since construction (ring bookkeeping)."""
        with self._lock:
            return self._n

    @property
    def completions(self) -> int:
        """Monotonic count of completed-work records (steps + ok
        requests) — what the SLO watchdog reads as progress."""
        with self._lock:
            return self._done

    def record(self, rec: Dict[str, Any],
               progress: Optional[bool] = None) -> None:
        """Store one wide event (adds ``seq``/``ts_us``).  No-op when
        disabled; never raises into the caller's step path.
        ``progress`` marks the record as completed work (default:
        steps and ok-outcome requests)."""
        if not self.enabled:
            return
        if progress is None:
            progress = rec.get("kind") == "step" or (
                rec.get("kind") == "request"
                and rec.get("outcome") == "ok")
        rec["ts_us"] = trace.elapsed_us()
        with self._lock:
            rec["seq"] = self._n
            self._buf[self._n % self.capacity] = rec
            self._n += 1
            if progress:
                self._done += 1

    def snapshot(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """The retained records oldest→newest (``last`` caps the count).
        Each record is copied, so a bundle serializer can't race a
        writer mutating a live dict."""
        with self._lock:
            n, cap = self._n, self.capacity
            start = max(0, n - cap)
            if last is not None:
                start = max(start, n - int(last))
            out = [dict(r) for r in
                   (self._buf[i % cap] for i in range(start, n))
                   if r is not None]
        return out

    def resize(self, capacity: int) -> None:
        keep = self.snapshot()
        with self._lock:
            self.capacity = max(16, int(capacity))
            self._buf = [None] * self.capacity
            # re-lay the retained tail so the ring stays consistent with
            # the (unchanged, monotonic) total count
            keep = keep[-self.capacity:]
            for i, rec in enumerate(keep):
                self._buf[(self._n - len(keep) + i) % self.capacity] = rec

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0
            self._done = 0


_recorder = FlightRecorder(
    capacity=int(os.environ.get("FLAGS_flight_recorder_events", "4096")
                 or 4096),
    enabled=os.environ.get("FLAGS_flight_recorder", "1").strip().lower()
    in trace._TRUE_STRINGS)


def recorder() -> FlightRecorder:
    return _recorder


def enabled() -> bool:
    """The single-boolean hot-path guard (mirrors trace.enabled())."""
    return _recorder.enabled


def record(kind: str, **fields) -> None:
    """Generic wide event — markers from the elastic plane / watchdog."""
    if _recorder.enabled:
        fields["kind"] = kind
        _recorder.record(fields)


def configure(capacity: Optional[int] = None,
              enabled: Optional[bool] = None) -> None:
    """Apply FLAGS_flight_recorder / FLAGS_flight_recorder_events at
    runtime (called from core.set_flags)."""
    if enabled is not None:
        _recorder.enabled = bool(enabled)
    if capacity is not None and int(capacity) != _recorder.capacity:
        _recorder.resize(int(capacity))


def reset() -> None:
    """Clear the ring (test isolation)."""
    _recorder.clear()


# ---------------------------------------------------------------------------
# cheap gauge sampling for step records
# ---------------------------------------------------------------------------

# cached instrument references: record_step must not pay a registry
# dict lookup per step
_m = trace.metrics()
_g_inflight = _m.gauge("executor.inflight_steps")
_g_goodput = _m.gauge("goodput.ratio")
_g_hbm = _m.gauge("xla.mem.lru_total_peak_bytes")

_rss_cache = [0.0, 0]                   # (monotonic stamp, bytes)
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes(max_age_s: float = 1.0) -> int:
    """Process resident set size, re-read from /proc at most once per
    ``max_age_s`` (a syscall per step would show up on the 5% gate)."""
    t = time.monotonic()
    if t - _rss_cache[0] > max_age_s:
        _rss_cache[0] = t
        try:
            with open("/proc/self/statm", "rb") as f:
                _rss_cache[1] = int(f.read().split()[1]) * _PAGE
        except (OSError, ValueError, IndexError):
            pass                        # non-linux: keep the last value
    return _rss_cache[1]


def record_step(step: int, dur_us: float, bucket=None, batch_valid=None,
                compile_miss: bool = False, fp: Optional[str] = None,
                n_fetch: int = 0, scan: Optional[int] = None) -> None:
    """One wide event per completed executor step.  Callers guard with
    :func:`enabled` so a disabled recorder costs one boolean."""
    rec: Dict[str, Any] = {
        "kind": "step", "step": int(step), "dur_us": round(dur_us, 1),
        "compile_miss": bool(compile_miss), "n_fetch": int(n_fetch),
        "inflight": _g_inflight.value,
        "goodput_ratio": round(_g_goodput.value, 4),
        "rss_bytes": rss_bytes(),
        "hbm_peak_bytes": _g_hbm.value,
    }
    if bucket is not None:
        rec["bucket"] = int(bucket)
    if batch_valid is not None:
        rec["batch_valid"] = int(batch_valid)
    if fp:
        rec["fp"] = fp
    if scan:
        rec["scan"] = int(scan)
    tid = trace.current_trace_id()
    if tid is not None:
        rec["trace_id"] = tid
    _recorder.record(rec)


def record_request(trace_id: str, rows: int, outcome: str = "ok",
                   batch_id: Optional[str] = None,
                   batch_rows: Optional[int] = None,
                   bucket=None, queue_us: Optional[float] = None,
                   device_us: Optional[float] = None,
                   latency_us: Optional[float] = None,
                   replica: Optional[str] = None) -> None:
    """One wide event per served (or rejected/timed-out) request.
    ``replica`` attributes a fleet-routed request to the replica that
    served it (the router records these parent-side)."""
    rec: Dict[str, Any] = {
        "kind": "request", "trace_id": trace_id, "rows": int(rows),
        "outcome": outcome,
    }
    if replica is not None:
        rec["replica"] = replica
    if batch_id is not None:
        rec["batch_id"] = batch_id
    if batch_rows is not None:
        rec["batch_rows"] = int(batch_rows)
    if bucket is not None:
        rec["bucket"] = int(bucket)
    if queue_us is not None:
        rec["queue_us"] = round(queue_us, 1)
    if device_us is not None:
        rec["device_us"] = round(device_us, 1)
    if latency_us is not None:
        rec["latency_us"] = round(latency_us, 1)
    _recorder.record(rec)
