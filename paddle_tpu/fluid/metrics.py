"""Python-side streaming metrics (fluid metrics.py: Accuracy, Auc, ...)."""
from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *a, **k):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        return self.value / max(self.weight, 1e-12)


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num + 1)
        self._stat_neg = np.zeros(self._num + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p1 = preds[:, -1] if preds.ndim > 1 else preds
        idx = np.clip((p1 * self._num).astype(int), 0, self._num)
        np.add.at(self._stat_pos, idx, labels)
        np.add.at(self._stat_neg, idx, 1 - labels)

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_p, tot_n = tp[-1], fp[-1]
        if tot_p * tot_n == 0:
            return 0.0
        tp_prev = np.concatenate([[0], tp[:-1]])
        fp_prev = np.concatenate([[0], fp[:-1]])
        return float(np.sum((fp - fp_prev) * (tp + tp_prev) / 2)
                     / (tot_p * tot_n))


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = self.num_label = self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += int(num_infer_chunks)
        self.num_label += int(num_label_chunks)
        self.num_correct += int(num_correct_chunks)

    def eval(self):
        precision = self.num_correct / max(self.num_infer, 1)
        recall = self.num_correct / max(self.num_label, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return precision, recall, f1


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over accumulated predictions (metrics.py:Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def reset(self):
        self.tp = self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int64").ravel()
        labels = np.asarray(labels).astype("int64").ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def reset(self):
        self.tp = self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int64").ravel()
        labels = np.asarray(labels).astype("int64").ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class EditDistance(MetricBase):
    """Average edit distance + instance error rate (metrics.py:EditDistance);
    pairs with layers.edit_distance outputs."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances, "float32").ravel()
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(d > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance.eval before any update")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP(MetricBase):
    """mean Average Precision accumulator (metrics.py:DetectionMAP); the
    in-graph companion op is layers.detection_map."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self._vals = []

    def update(self, value, weight=1):
        self._vals.append((float(np.asarray(value).ravel()[0]),
                           float(weight)))

    def get_map_var(self):
        return None

    def eval(self):
        if not self._vals:
            raise ValueError("DetectionMAP.eval before any update")
        num = sum(v * w for v, w in self._vals)
        den = sum(w for _, w in self._vals)
        return num / max(den, 1e-12)
