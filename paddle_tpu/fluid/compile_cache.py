"""Shape bucketing + process-surviving compile cache + recompile hygiene.

Reference: the fluid executor amortises preparation per (program, scope)
via _ExecutorCache (python/paddle/fluid/executor.py:1110) but never sees a
recompile problem — per-op kernel dispatch is shape-polymorphic.  Under
whole-block XLA compilation (executor.py here) every distinct feed shape is
a full recompile: multi-second cold compiles versus microsecond dispatch,
paid again for every ragged tail batch (`drop_last=False` loaders, eval
epoch ends, variable-length NLP batches) and again after every process
restart (tpu_watch canary restarts, preemption recovery).  This module owns
the three defenses, all gated by flags in fluid.core:

* **Shape bucketing** (`FLAGS_shape_bucketing`, `FLAGS_shape_bucket_edges`)
  — pad the leading batch dim up to a bucket edge (powers of two by
  default) so a ragged epoch compiles at most ``len(edges)`` executables.
  The executor threads the true batch size into the compiled step as a
  traced ``__batch_valid__`` scalar; mask-aware batch reductions
  (ops/reduction.py, ops/nn_ops.py batch-norm stats) keep padded-step
  numerics equal to the unpadded step within fp tolerance.
* **Persistent compile cache** (`FLAGS_persistent_cache_dir`) — jax's own
  compilation cache persists the compiled XLA executables; the
  :class:`PersistentCache` index here records which (program fingerprint,
  bucketed feed sig, jax/backend version) keys have compiled before, so a
  restarted trainer reports a persistent-warm start (zero *cold* misses)
  and tooling can inspect what lives in the cache.
* **Recompile-storm detection** (`FLAGS_recompile_warn_threshold` /
  `FLAGS_recompile_warn_window`) — a sliding-window miss counter that
  fires a trace-plane event with shape/bucket attribution when the miss
  rate says something upstream is feeding unstable shapes.
"""
from __future__ import annotations

import collections
import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# bucket-edge algebra
# ---------------------------------------------------------------------------

def next_pow2(n: int) -> int:
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def pow2_edges(max_size: int) -> Tuple[int, ...]:
    """Power-of-two edges up to ``max_size``, plus ``max_size`` itself —
    what a loader with a known batch size but unknown tail advertises."""
    max_size = int(max_size)
    edges = {max_size}
    e = 1
    while e < max_size:
        edges.add(e)
        e <<= 1
    return tuple(sorted(edges))


_edges_memo: Dict[Any, Tuple[int, ...]] = {}


def normalize_edges(edges) -> Optional[Tuple[int, ...]]:
    """Canonicalise a user edge spec: ``"8,16,32"`` / list / tuple ->
    sorted tuple of positive ints; None stays None (powers of two).
    Memoised — the executor calls this per run with the same env string /
    hint tuple, which must not cost a re-parse per training step."""
    if edges is None:
        return None
    key = edges if isinstance(edges, (str, tuple)) else tuple(edges)
    hit = _edges_memo.get(key)
    if hit is not None:
        return hit
    parts = [p for p in key.replace(";", ",").split(",") if p.strip()] \
        if isinstance(key, str) else key
    out = tuple(sorted({int(e) for e in parts}))
    if not out or out[0] <= 0:
        raise ValueError(
            f"FLAGS_shape_bucket_edges needs positive ints, got {edges!r}")
    if len(_edges_memo) < 256:      # bound: specs are few in practice
        _edges_memo[key] = out
    return out


def bucket_for(n: int, edges: Optional[Sequence[int]] = None) -> int:
    """Smallest bucket edge >= n (powers of two when ``edges`` is None).
    A batch above the largest explicit edge is its own bucket — no padding,
    one executable per such shape, exactly the pre-bucketing behaviour."""
    n = int(n)
    if edges:
        cands = [int(e) for e in edges if int(e) >= n]
        return min(cands) if cands else n
    return next_pow2(n)


def pad_dim0(v, target: int):
    """Zero-pad the leading dim up to ``target``.  numpy feeds pad on the
    host; device arrays pad with jnp (no D2H sync — the prefetch-pipeline
    rule from the executor's feed-sig path applies here too)."""
    if np.ndim(v) == 0:
        return v
    pad = int(target) - int(np.shape(v)[0])
    if pad <= 0:
        return v
    widths = [(0, pad)] + [(0, 0)] * (np.ndim(v) - 1)
    if isinstance(v, np.ndarray):
        return np.pad(v, widths)
    import jax.numpy as jnp
    return jnp.pad(jnp.asarray(v), widths)


# ---------------------------------------------------------------------------
# persistent program-level cache index
# ---------------------------------------------------------------------------

def persistent_key(fingerprint: str, feed_sig, fetch_names,
                   extras: Sequence = ()) -> str:
    """Content key for one compiled executable, stable across processes:
    program fingerprint + bucketed feed signature + fetch set + the
    compile-relevant hints, salted with the jax version and backend (an
    upgraded jax or a different platform must cold-compile)."""
    import jax
    payload = (fingerprint, tuple(feed_sig), tuple(fetch_names),
               tuple(extras), jax.__version__, jax.default_backend())
    return hashlib.sha256(repr(payload).encode()).hexdigest()


_jax_cache_dir_applied: Optional[str] = None


def _configure_jax_cache(root: str) -> None:
    """Point jax's own compilation cache at ``root``/xla so the XLA
    executables (not just this index) survive the process.  Thresholds are
    zeroed: on this stack even a tiny program's compile dwarfs a dispatch,
    so every entry is worth persisting.  Knob names vary across jax
    versions — each update degrades independently."""
    global _jax_cache_dir_applied
    if _jax_cache_dir_applied == root:
        return
    import jax
    xla_dir = os.path.join(root, "xla")
    os.makedirs(xla_dir, exist_ok=True)
    for knob, val in (("jax_compilation_cache_dir", xla_dir),
                      ("jax_persistent_cache_min_entry_size_bytes", -1),
                      ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(knob, val)
        except Exception:       # noqa: BLE001 — the index works without
            pass
    _jax_cache_dir_applied = root


class PersistentCache:
    """On-disk key -> executable-metadata index under
    ``FLAGS_persistent_cache_dir``.

    One JSON file per key (``index/<sha256>.json``) written via
    tempfile + atomic rename: no locks, safe for concurrent trainers
    sharing the directory (canary restarts, multi-host launches on a
    shared filesystem).  Existence of the file IS the hit predicate."""

    def __init__(self, root: str, configure_jax: bool = True):
        self.root = os.path.abspath(root)
        self.index_dir = os.path.join(self.root, "index")
        os.makedirs(self.index_dir, exist_ok=True)
        if configure_jax:
            # a secondary index (the autotune config store under
            # FLAGS_auto_tune_dir) must NOT re-root jax's compilation
            # cache away from the primary persistent dir
            _configure_jax_cache(self.root)

    def path_for(self, key: str) -> str:
        return os.path.join(self.index_dir, key + ".json")

    def has(self, key: str) -> bool:
        return os.path.exists(self.path_for(key))

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path_for(key)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def record(self, key: str, meta: Dict[str, Any]) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.index_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(meta, f, default=str)
            os.replace(tmp, self.path_for(key))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def keys(self) -> List[str]:
        try:
            return sorted(f[:-5] for f in os.listdir(self.index_dir)
                          if f.endswith(".json"))
        except OSError:
            return []

    def entries(self) -> List[Dict[str, Any]]:
        """Every readable index record (unreadable/corrupt ones are
        skipped — the index degrades, it never throws at inspectors)."""
        out = []
        for k in self.keys():
            meta = self.get(k)
            if meta is not None:
                out.append(dict(meta, key=k))
        return out

    def device_footprints(self) -> List[Dict[str, Any]]:
        """Executables in the index that carry device-truth meta
        (``device.flops`` / ``device.peak_bytes``, recorded by the
        executor when FLAGS_device_cost_analysis captured them), sorted
        by peak HBM bytes descending — what "which executable is
        biggest?" tooling reads after the fact, without a live
        process."""
        rows = []
        for meta in self.entries():
            dev = meta.get("device") or {}
            if dev.get("peak_bytes") or dev.get("flops"):
                rows.append({"key": meta.get("key"),
                             "fingerprint": str(
                                 meta.get("fingerprint", ""))[:12],
                             "bucket": meta.get("bucket"),
                             "n_ops": meta.get("n_ops"),
                             "flops": dev.get("flops"),
                             "peak_bytes": dev.get("peak_bytes"),
                             "argument_bytes": dev.get("argument_bytes")})
        rows.sort(key=lambda r: float(r.get("peak_bytes") or 0),
                  reverse=True)
        return rows


_instance: Optional[PersistentCache] = None


def persistent_cache() -> Optional[PersistentCache]:
    """The process PersistentCache for FLAGS_persistent_cache_dir, or None
    when the flag is unset.  Re-reads the flag each call so tests (and
    set_flags at runtime) can repoint or disable it."""
    global _instance
    from . import core
    root = core.get_flag("persistent_cache_dir")
    if not root:
        return None
    root = os.path.abspath(str(root))
    if _instance is None or _instance.root != root:
        _instance = PersistentCache(root)
    return _instance


_config_instance: Optional[PersistentCache] = None


def config_store() -> Optional[PersistentCache]:
    """The tuned-config store (fluid/autotune.py): the same atomic
    JSON-per-key index, rooted at ``FLAGS_auto_tune_dir`` when set, else
    riding the shared ``FLAGS_persistent_cache_dir`` cache — winning
    configs live beside the executables they were measured for.  None
    when neither flag is set (tuning still works, it just re-probes
    after a restart).  Re-reads the flags each call so ``set_flags``
    can repoint it mid-run."""
    global _config_instance
    from . import core
    root = core.get_flag("auto_tune_dir")
    if not root:
        return persistent_cache()
    root = os.path.abspath(str(root))
    if _config_instance is None or _config_instance.root != root:
        _config_instance = PersistentCache(root, configure_jax=False)
    return _config_instance


# ---------------------------------------------------------------------------
# recompile-storm detection
# ---------------------------------------------------------------------------

class RecompileStormDetector:
    """Sliding-window compile-miss monitor.  ``note_miss`` returns the
    attributed misses (shape/bucket info) exactly once when the in-window
    count crosses the threshold, then disarms until the window drains
    below half the threshold — one warning per storm, not per miss."""

    def __init__(self):
        self._misses: collections.deque = collections.deque()
        self._armed = True

    def note_miss(self, info: Dict[str, Any], threshold: int,
                  window: float, now: Optional[float] = None):
        t = time.monotonic() if now is None else now
        while self._misses and t - self._misses[0][0] > window:
            self._misses.popleft()
        # re-arm check BEFORE appending, so small thresholds (1-3, where
        # half rounds to <= 1) can re-arm once the window drains
        if len(self._misses) < max(int(threshold) // 2, 1):
            self._armed = True
        self._misses.append((t, info))
        if self._armed and len(self._misses) >= int(threshold):
            self._armed = False
            return [i for _, i in self._misses]
        return None
