"""fluid.parallel_executor analog (reference parallel_executor.py over
framework/parallel_executor.cc).

TPU design: ParallelExecutor's SSA-graph replication + AllReduce op
handles are replaced outright by XLA GSPMD — CompiledProgram
.with_data_parallel carries the mesh and the executor jits the whole
block over it (fluid/compiler.py).  This class keeps the reference's
construct-then-run API over that machinery."""
from __future__ import annotations

from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from .executor import Executor
from .framework import default_main_program

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy"]


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        program = main_program or default_main_program()
        self._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy,
            share_vars_from=getattr(share_vars_from, "_compiled", None))
        self._exe = Executor()
        self._scope = scope

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        pass                        # XLA owns buffers; nothing to drop
