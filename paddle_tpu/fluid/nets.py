"""Composite network helpers — python/paddle/fluid/nets.py analog.

Same public surface (simple_img_conv_pool, img_conv_group,
sequence_conv_pool, glu, scaled_dot_product_attention); each builds on the
framework's layer API, so the whole composition lowers into the one XLA
program per block like any other op sequence.
"""
from __future__ import annotations

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "img_conv_group",
    "sequence_conv_pool",
    "glu",
    "scaled_dot_product_attention",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """conv2d + pool2d (nets.py:29)."""
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, stride=conv_stride,
                             padding=conv_padding, dilation=conv_dilation,
                             groups=conv_groups, param_attr=param_attr,
                             bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Serial Conv2D[+BatchNorm+Dropout] stack then one Pool2D
    (nets.py:143, the VGG block builder)."""
    if not hasattr(conv_num_filter, "__len__"):
        raise TypeError("conv_num_filter must be a list or tuple")
    n = len(conv_num_filter)

    def _expand(v):
        return list(v) if hasattr(v, "__len__") else [v] * n

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    tmp = input
    for i in range(n):
        # when a conv is followed by batch_norm, the activation moves onto
        # the batch_norm (and the conv drops its bias)
        local_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(tmp, num_filters=conv_num_filter[i],
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr[i],
                            bias_attr=(False if conv_with_batchnorm[i]
                                       else None),
                            act=local_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp,
                                     dropout_prob=conv_batchnorm_drop_rate[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    """sequence_conv + sequence_pool (nets.py:261; text-CNN block).
    Input follows this framework's padded-batch convention [B, T, D]."""
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr,
                                    bias_attr=bias_attr, act=act)
    return layers.sequence_pool(conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    """Gated Linear Unit: split | sigmoid | multiply (nets.py:335)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (nets.py:382).

    queries [N, Lq, d_model], keys/values [N, Lk, d_model]; d_model must
    divide num_heads.  One fused XLA program handles the whole block; for
    long sequences prefer the flash-attention lowering in ops/attention.py.
    """
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same feature size")
    if keys.shape[-1] != values.shape[-1]:
        raise ValueError("keys and values must have the same feature size")
    d_model = queries.shape[-1]
    if d_model % num_heads != 0:
        raise ValueError(f"feature size {d_model} is not divisible by "
                         f"num_heads {num_heads}")

    q, k, v = queries, keys, values
    if num_heads > 1:
        q = layers.fc(q, size=d_model, num_flatten_dims=2, bias_attr=False)
        k = layers.fc(k, size=d_model, num_flatten_dims=2, bias_attr=False)
        v = layers.fc(v, size=d_model, num_flatten_dims=2, bias_attr=False)

    def _split_heads(x):
        if num_heads == 1:
            return x
        b, t = x.shape[0], x.shape[1]
        x = layers.reshape(x, [b, t, num_heads, d_model // num_heads])
        return layers.transpose(x, perm=[0, 2, 1, 3])   # [N, h, T, d_k]

    def _combine_heads(x):
        if num_heads == 1:
            return x
        x = layers.transpose(x, perm=[0, 2, 1, 3])
        b, t = x.shape[0], x.shape[1]
        return layers.reshape(x, [b, t, d_model])

    q, k, v = _split_heads(q), _split_heads(k), _split_heads(v)
    d_k = d_model // num_heads
    scaled_q = layers.scale(q, scale=d_k ** -0.5)
    scores = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(scores)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    return _combine_heads(ctx)
