"""fluid.dataloader namespace (reference fluid/dataloader/): dataset and
sampler algebra + worker plumbing — one implementation lives in
paddle_tpu.io; these modules re-export it under the fluid paths."""
from . import dataset
from .dataset import (Dataset, IterableDataset, TensorDataset,
                      ComposeDataset, ChainDataset, random_split, Subset)
from . import batch_sampler
from .batch_sampler import BatchSampler, DistributedBatchSampler
from . import sampler
from .sampler import (Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler)
from . import dataloader_iter
from .dataloader_iter import get_worker_info

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "random_split", "Subset", "BatchSampler",
           "DistributedBatchSampler", "Sampler", "SequenceSampler",
           "RandomSampler", "WeightedRandomSampler", "get_worker_info"]
