from ...io import get_worker_info

__all__ = ["get_worker_info"]
