from ...io import (Sampler, SequenceSampler, RandomSampler,
                   WeightedRandomSampler)

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler"]
