from ...io import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                   ChainDataset, random_split, Subset)

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "random_split", "Subset"]
