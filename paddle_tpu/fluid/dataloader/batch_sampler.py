from ...io import BatchSampler, DistributedBatchSampler

__all__ = ["BatchSampler", "DistributedBatchSampler"]
