"""ParamAttr / WeightNormParamAttr — per python/paddle/fluid/param_attr.py."""
from __future__ import annotations

from .initializer import Initializer, _to_initializer


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")


class WeightNormParamAttr(ParamAttr):
    """Weight-normalised parameter attr (reference param_attr.py): the
    reparameterisation is applied by nn.SpectralNorm / weight-norm
    utilities at the layer tier; the attr carries `dim` for them."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
