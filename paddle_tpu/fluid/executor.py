"""Executor: whole-block XLA compilation replacing per-op kernel dispatch.

Reference: paddle/fluid/framework/executor.cc — `Prepare` (executor.cc:376)
instantiates ops, `RunPartialPreparedContext` (executor.cc:474-480) hot-loops
`op->Run(scope, place)` per op per step.  TPU-native: `Executor._prepare`
lowers the whole block to ONE jaxpr via the per-op lowering rules and
jit-compiles it; the per-step cost is a single device-program launch.  The
compile cache keyed on (program fingerprint, feed shapes) is the analog of
`ExecutorPrepareContext` caching (_ExecutorCache, executor.py:1110).  Eager
GC / inplace passes are replaced by XLA buffer donation of the parameter
arguments (SURVEY §2.2 TPU note).

Distributed: when the program carries a mesh annotation (parallel/mesh.py),
the same step callable is wrapped in shard_map over the jax.sharding.Mesh so
collective ops (c_allreduce_*, ...) lower to ICI collectives — the analog of
ParallelExecutor's SSA graph + NCCL op handles, with XLA doing the
scheduling that FastThreadedSSAGraphExecutor did by hand.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from . import compile_cache
from . import core
from . import device_stats
from . import flight_recorder as _flight
from . import trace
from .core import Scope, global_scope
from .framework import Program, Block, Variable, default_main_program
from ..ops.registry import get_op, has_op, LoweringContext


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _fetch_name(f):
    return f.name if isinstance(f, Variable) else str(f)


_I32_MAX, _I32_MIN = 2 ** 31 - 1, -(2 ** 31)

# cached instrument refs for the per-step path (a registry dict lookup
# per step would be measurable on the flight recorder's 5% gate).  The
# SLO watchdog reads these as its liveness/progress signals:
# steps_in_progress > 0 means a (possibly wedged) device call is live,
# compiles_in_progress > 0 marks a legitimately long first-call XLA
# compile (never a stall), steps_completed is monotonic progress.
_g_step_live = trace.metrics().gauge("executor.steps_in_progress")
_g_compiling = trace.metrics().gauge("executor.compiles_in_progress")
_c_steps_done = trace.metrics().counter("executor.steps_completed")


def check_feed_width(name, v):
    """Without x64, jax canonicalizes int64/uint64 feeds to 32 bits — for
    CTR feasigns that is silent data corruption (2^32 collisions on real ad
    ids).  Fail loudly instead; host-side numpy inputs only (device arrays
    were staged by a path that already checked)."""
    import jax
    if jax.config.jax_enable_x64 or not isinstance(v, np.ndarray):
        return
    if v.dtype not in (np.int64, np.uint64) or v.size == 0:
        return
    if v.max(initial=0) > _I32_MAX or v.min(initial=0) < _I32_MIN:
        raise OverflowError(
            f"feed '{name}' holds 64-bit integers outside the int32 range; "
            f"they would be silently truncated on device (x64 is off).  "
            f"Route wide feasign ids through the PS/Box embedding tiers — "
            f"ids are translated host-side at full width — or opt in with "
            f"fluid.core.set_flags({{'FLAGS_enable_x64': True}})")


def _fingerprint(program: Program) -> str:
    """Structural SHA-1 of the program, cached on the Program and
    invalidated by mutation (the _ExecutorCache amortisation: reference
    executor.py:1110 prepares once, not per step).  The cache key is the
    program's mutation version (bumped by append_op and the graph passes)
    plus per-block op counts as a safety net against a pass that swaps
    `block.ops` wholesale without bumping."""
    shape = (getattr(program, "_version", None),
             tuple(len(b.ops) for b in program.blocks))
    cached = getattr(program, "_fp_cache", None)
    if cached is not None and cached[0] == shape:
        return cached[1]
    h = hashlib.sha1()
    # dtype-aware: the AMP plane rewrites VAR dtypes (a bf16 program and
    # its fp32 twin can share an op stream modulo attrs), and the compiled
    # executable is specialised on them — they must key the cache exactly
    # like the op stream does
    h.update(f"amp:{int(bool(getattr(program, '_amp_enabled', False)))}:"
             f"{getattr(program, '_amp_dtype', '')}".encode())
    for b in program.blocks:
        h.update(repr(sorted((n, v.dtype) for n, v in b.vars.items()))
                 .encode())
        for op in b.ops:
            h.update(op.type.encode())
            h.update(repr(sorted(op.inputs.items())).encode())
            h.update(repr(sorted(op.outputs.items())).encode())
            h.update(repr(sorted((k, str(v)) for k, v in op.attrs.items()))
                     .encode())
    digest = h.hexdigest()
    program._fp_cache = (shape, digest)
    return digest


class _CompiledBlock:
    """The ExecutorPrepareContext analog: one jitted callable per
    (program, feed signature)."""

    def __init__(self, fn, param_names, written_names, fetch_names,
                 n_ops=None, raw_fn=None, donates=False, err_cell=None,
                 alias_cell=None, jitted=None):
        self.fn = fn
        self.param_names = param_names
        self.written_names = written_names
        self.fetch_names = fetch_names
        self.n_ops = n_ops          # post-prune op count (introspection)
        self.raw_fn = raw_fn        # un-jitted step (run_scan fuses over it)
        self.donates = donates      # jit donates the mutable-state args
        self.err_cell = err_cell    # deferred checkify error (lazy fetches)
        # the lowerable jit wrapper (device_stats.capture AOT-analyses it
        # for measured FLOPs / HBM footprint); None for step builders
        # with no .lower (checkify wrapper, pipeline/PS custom loops)
        self.jitted = jitted if hasattr(jitted, "lower") else None
        # per-fetch does-it-alias-scope-state mask, recorded by TRACER
        # identity at trace time (id() of the returned arrays is useless:
        # XLA may back a fetch and a state output with ONE buffer).  None
        # = unknown (non-plain step builders): treat every fetch as
        # aliasing when the program donates — conservative, never unsafe.
        self.alias_cell = alias_cell

    def fetch_alias_mask(self, n_fetch):
        if self.alias_cell is None:
            return ((self.donates,) * n_fetch)
        if self.alias_cell:
            return self.alias_cell[0]
        return (False,) * n_fetch


def _unpublish_footprints(footprints):
    """Retire every footprint in the dict from the gauges and the
    process-wide aggregates — shared by Executor.close() and the
    GC-time weakref finalizer (which holds this dict, not the
    executor)."""
    for fp in footprints.values():
        device_stats.unpublish(fp.get("label", ""))
    footprints.clear()


def _batch_major_hint(block, op):
    """IR-level gate for the shape-bucketing row mask, resolved from the
    op's primary input var: False for persistable inputs and for vars
    with a known STATIC leading dim (a parameter, or anything derived
    only from parameters — their rows are never the batch, even when
    dim 0 aliases the bucket size), True when the IR marks the var
    batch-major (-1 leading dim, propagated by shape inference), None
    when provenance is unknown (the dim0 heuristic decides)."""
    names = op.inputs.get("X") or op.input_arg_names[:1]
    if not names:
        return None
    v = block._find_var_recursive(names[0])
    if v is None:
        return None
    if v.persistable:
        return False
    if names[0] in (block.program._hints.get("carry_vars") or ()):
        # declared carried state (decode KV caches): its leading dim is
        # the state's slot capacity, never the step's batch — exempt
        # from the padded-row mask like a parameter
        return False
    if v.shape is None:
        return None
    return len(v.shape) >= 1 and v.shape[0] == -1


def run_block_ops(block: Block, env: Dict[str, Any], ctx: LoweringContext,
                  stop_at: Optional[int] = None, ops=None,
                  call_op=None):
    """Interpret the block's ops by invoking each lowering rule; under jit
    this builds the jaxpr (trace-time loop — zero runtime dispatch cost).

    `ops` restricts execution to an explicit op list (pipeline stages /
    recompute segments); `call_op` overrides how a lowering rule is invoked
    (the functional-autodiff path wraps custom_grad ops in jax.custom_vjp).
    """
    from . import control_flow_impl
    op_list = block.ops if ops is None else ops
    debug_nan = getattr(ctx, "debug_nan", False)
    # observability plane: ONE boolean read for the whole loop; when off the
    # per-op cost is a single `if` (acceptance: no measurable overhead).
    # Under jit these spans time host dispatch/lowering per op — the
    # operator.cc RunImpl host-side cost (see trace.py module docstring).
    tr_on = trace.enabled()
    # IR-level constant folding for tensor-array indices: under jit EVERY
    # value is staged abstract, but fill_constant/increment counter chains
    # are statically known from the op stream — fold them so
    # write/read_to_array resolve their slot at trace time
    const_env: Dict[str, float] = {}
    n_dispatched = 0
    for i, op in enumerate(op_list):
        if stop_at is not None and i >= stop_at:
            break
        if op.type in ("feed", "fetch"):
            continue
        n_dispatched += 1
        if op.type in ("while", "conditional_block", "select_input",
                       "select_output"):
            for n in op.output_arg_names:    # runtime writes: un-fold
                const_env.pop(n, None)
            _t0 = trace.now() if tr_on else 0
            control_flow_impl.run_control_flow_op(op, block, env, ctx)
            if tr_on:
                trace.complete(op.type, _t0, cat="op")
            continue
        opdef = get_op(op.type)
        ins = {}
        amp_cast = op.attrs.get("__amp_cast__")
        for slot, names in op.inputs.items():
            if amp_cast and slot in amp_cast:
                # folded AMP cast (passes/amp.py prune_redundant_casts):
                # the astype happens here, inline, instead of as its own
                # dispatched cast op — zero extra ops in the traced block
                dts = amp_cast[slot]
                vals = [env[n] if j >= len(dts) or dts[j] is None
                        else env[n].astype(dts[j])
                        for j, n in enumerate(names) if n in env]
            else:
                vals = [env[n] for n in names if n in env]
            if vals or names:
                ins[slot] = vals
        op_attrs = op.attrs
        if op.type == "recurrent":   # StaticRNN needs its step sub-block
            op_attrs = dict(op.attrs, __program__=block.program)
        if op.type == "fill_constant" and not op.inputs.get("ShapeTensor"):
            for n in op.output_arg_names:
                const_env[n] = float(op.attrs.get("value", 0.0))
        elif op.type == "increment":
            src = op.input_arg_names[0] if op.input_arg_names else None
            for n in op.output_arg_names:
                if src in const_env:
                    const_env[n] = const_env[src] + op.attrs.get("step", 1.0)
                else:
                    const_env.pop(n, None)
        elif op.type in ("write_to_array", "read_from_array",
                         "shrink_rnn_memory"):
            iname = (op.inputs.get("I") or [None])[0]
            if iname in const_env:
                op_attrs = dict(op_attrs, __index__=int(const_env[iname]))
        else:
            for n in op.output_arg_names:   # any other writer invalidates
                const_env.pop(n, None)
        if ctx.batch_valid is not None:
            # trace-time only (cost is per compile, not per step): tell
            # the masked reductions whether this op's input is really
            # batch-major, so a parameter whose dim 0 aliases the bucket
            # size is never masked
            ctx.cur_op_batch_major = _batch_major_hint(block, op)
        # named_scope: per-op spans in profiler traces / HLO metadata
        # (platform/profiler.h:127 RecordEvent placement, operator.cc:1077)
        _t0 = trace.now() if tr_on else 0
        with jax.named_scope(op.type):
            if call_op is not None:
                outs = call_op(opdef, ins, op_attrs, ctx)
            else:
                if "SkipUpdate" in ins:   # GradientMerge k-step gate
                    from ..ops.optimizer_ops import apply_skip_update
                    plain = {k: v for k, v in ins.items()
                             if k != "SkipUpdate"}
                    outs = apply_skip_update(
                        ins, opdef.fn(plain, op_attrs, ctx))
                else:
                    outs = opdef.fn(ins, op_attrs, ctx)
        if tr_on:
            trace.complete(op.type, _t0, cat="op")
        for slot, names in op.outputs.items():
            produced = outs.get(slot, [])
            for name, val in zip(names, produced):
                if val is not None:
                    env[name] = val
                    if debug_nan and hasattr(val, "dtype") and \
                            jnp.issubdtype(val.dtype, jnp.floating):
                        # per-op-output NaN scan compiled into the program
                        # (operator.cc:1149 CheckOpHasNanOrInf, XLA-native
                        # via checkify so the failing OP NAME surfaces)
                        from jax.experimental import checkify
                        checkify.check(
                            jnp.all(jnp.isfinite(val)),
                            f"NaN/Inf in output '{name}' of op "
                            f"'{op.type}'")
    if n_dispatched:
        # trace-time dispatch volume (always-on int bump per BLOCK, not
        # per op): the executed-op counter the pass pipeline's end-to-end
        # gate compares pipeline-on vs -off (docs/passes.md)
        trace.metrics().counter("executor.ops_dispatched").inc(n_dispatched)
    return env


class Executor:
    """fluid.Executor(place) — API per python/paddle/fluid/executor.py:914."""

    def __init__(self, place: Optional[core.Place] = None):
        self.place = place or (core.TPUPlace(0) if core.is_compiled_with_tpu()
                               else core.CPUPlace())
        # LRU over compiled executables (FLAGS_executor_cache_capacity):
        # unbounded growth on shape-churning workloads held every traced
        # program + XLA executable alive for the process lifetime
        self._cache: "OrderedDict[tuple, _CompiledBlock]" = OrderedDict()
        self._storm = compile_cache.RecompileStormDetector()
        self._step = 0
        # run_async keeps one AsyncStepRunner per (program, fetches, scope),
        # LRU-bounded like _cache (a runner pins its program, scope, and
        # in-flight device buffers) — evicted runners are drained first
        self._async_runners: "OrderedDict[tuple, Any]" = OrderedDict()
        # weakrefs to every live state-aliasing FetchHandle issued by a
        # lazy run on this executor: the next DONATING dispatch (from any
        # runner, or a plain sync run) persists these before it
        # invalidates the scope's state buffers.  Executor-level because
        # scope state is shared across runners and programs — a read-only
        # eval fetch of W must survive the train step donating W.
        # Weakrefs so handles the caller dropped cost nothing.
        self._alias_live: List[Any] = []
        # device truth (fluid/device_stats.py): per-live-executable
        # footprint records keyed like _cache, populated on compile when
        # FLAGS_device_cost_analysis allows — eviction drops the record
        # and its gauges, OOM errors get the top footprints attached
        self._footprints: "OrderedDict[tuple, Dict[str, Any]]" = \
            OrderedDict()
        self._fp_finalizer = None   # GC-time unpublish (set on capture)

    # -- public API ---------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True):
        program = program or default_main_program()
        fetch_names = [_fetch_name(f) for f in _as_list(fetch_list)]
        # CompiledProgram facade (compiler.py) unwraps to its program +
        # mesh + sharding plan (parallel/sharding.py — the whole-step
        # pjit path; a plain frozen Program may carry a plan too)
        mesh = getattr(program, "_mesh", None)
        plan = getattr(program, "_sharding_plan", None)
        if hasattr(program, "_program"):   # CompiledProgram
            # BuildStrategy.sharding lowers to its plan + the
            # shard_collectives rewrite before fingerprinting
            if hasattr(program, "_ensure_sharding_plan"):
                plan = program._ensure_sharding_plan() or plan
            # BuildStrategy-selected IR passes run ONCE, seeded/protected
            # by this first run's fetch set, before the program is
            # fingerprinted — the pass framework contract (fluid/passes/)
            if hasattr(program, "_apply_ir_passes"):
                program._apply_ir_passes(fetch_names)
            mesh = getattr(program, "_mesh", None) or mesh
            program = program._program
            plan = getattr(program, "_sharding_plan", None) or plan
        if plan is not None:
            mesh = None     # the plan path subsumes the legacy auto mode
        if program._hints.get("ps_server") is not None:
            # pserver program from DistributeTranspiler.get_pserver_program:
            # running it IS the server loop (listen_and_serv_op role) —
            # blocks until the trainers send stop
            from .transpiler.distribute_transpiler import serve_ps_program
            return serve_ps_program(program._hints["ps_server"])
        if (program._hints.get("ps_plan") is not None
                and not getattr(self, "_in_ps_run", False)):
            # PS-served program: the pull -> device step -> push loop
            # (downpour_worker.cc analog) wraps this very run()
            from ..distributed.ps.program_pass import run_program_with_ps
            return run_program_with_ps(self, program, feed, fetch_list,
                                       scope, return_numpy,
                                       use_program_cache)
        scope = scope or global_scope()
        feed = self._normalize_feed(feed)

        # profile-guided self-tuning (fluid/autotune.py): a program that
        # opted in (BuildStrategy.auto_tune hint or FLAGS_auto_tune)
        # tunes ONCE per fingerprint before its first real step — a
        # persisted winner applies with zero probe cost; the search
        # itself re-enters run()/run_async() under the _in_autotune
        # guard.  Placed BEFORE bucketing so a tuned bucket_edges hint
        # shapes this very run.
        if (feed and (program._hints.get("auto_tune")
                      or core.get_flag("auto_tune"))
                and not getattr(self, "_in_autotune", False)):
            from . import autotune
            autotune.maybe_tune_executor(self, program, feed,
                                         fetch_names, scope)

        # shape bucketing (fluid/compile_cache.py): pad the leading batch
        # dim up to a bucket edge BEFORE computing feed_sig, so a ragged
        # epoch compiles <= len(edges) executables instead of one per
        # distinct tail shape.  The true batch size rides into the
        # compiled step as the traced __batch_valid__ scalar; mask-aware
        # batch reductions keep numerics padding-invariant, and fetches
        # are sliced back below.  Mesh / pipeline / recompute paths keep
        # exact shapes (their step builders do per-axis surgery).
        bucket = n_valid = None
        want_bucketing = program._hints.get("shape_bucketing")
        if want_bucketing is None:
            want_bucketing = core.get_flag("shape_bucketing")
        if (want_bucketing and feed and mesh is None
                and (plan is None or plan.data_axis is None)
                and not program._hints.get("pipeline_microbatches")
                and not program._hints.get("recompute_checkpoints")):
            dims = {np.shape(v)[0] for v in feed.values() if np.ndim(v) >= 1}
            if len(dims) == 1:
                n_valid = int(next(iter(dims)))
                edges = compile_cache.normalize_edges(
                    program._hints.get("bucket_edges")
                    or core.get_flag("shape_bucket_edges"))
                bucket = compile_cache.bucket_for(n_valid, edges)
                if bucket != n_valid:
                    feed = {k: compile_cache.pad_dim0(v, bucket)
                            for k, v in feed.items()}
            else:
                # mixed leading dims: no common batch axis to pad.  Count
                # it — the storm warning points here so an enabled-but-
                # inert bucketing flag is discoverable, not silent
                trace.metrics().counter(
                    "executor.bucketing_skipped_mixed_feeds").inc()

        feed_sig = tuple(sorted(
            (k, tuple(np.shape(v)), str(v.dtype))
            for k, v in feed.items()))
        key = (_fingerprint(program), feed_sig, tuple(fetch_names),
               id(scope), bool(program._hints.get("is_test")),
               tuple(program._hints.get("recompute_checkpoints") or ()),
               program._hints.get("pipeline_microbatches"),
               id(mesh) if mesh is not None else None,
               bool(core.get_flag("check_nan_inf")),
               bool(program._hints.get("inference_no_prune")),
               bool(program._hints.get("donate_buffers")),
               bucket,
               id(plan) if plan is not None else None)
        # compile-cache instrumentation (the _ExecutorCache hit-rate is THE
        # first-order perf signal on this stack: a miss is a whole-block
        # XLA recompile).  Counters are always on (one int bump per run);
        # timeline events only when the plane is enabled.
        tr_on = trace.enabled()
        pending_compile = None
        compiled = self._cache.get(key)
        if compiled is None:
            trace.metrics().counter("executor.compile_cache_miss").inc()
            if tr_on:
                trace.instant("compile_cache_miss", cat="compile",
                              args={"fingerprint": key[0][:12],
                                    "n_feeds": len(feed), "bucket": bucket,
                                    "batch_valid": n_valid})
            if not program._hints.get("expected_shape_churn"):
                # iteration engines (serving/decode.py) compile one
                # executable per DECLARED bucket — expected, not a storm
                self._note_recompile(feed_sig, bucket, tr_on)
            # persistent program-level cache: jax's on-disk compilation
            # cache serves the XLA compile; the index tells a COLD miss
            # (never compiled on this cache dir) from a persistent-warm
            # re-trace after a process restart
            pcache = compile_cache.persistent_cache()
            pkey = pwarm = None
            if pcache is not None:
                # key minus the process-local ids (scope, mesh, plan
                # objects); the plan contributes its stable description,
                # never its id (an id would defeat warm starts)
                pkey = compile_cache.persistent_key(
                    key[0], feed_sig, fetch_names,
                    extras=key[4:7] + (mesh is not None,) + key[8:12]
                    + (repr(sorted(plan.describe().items()))
                       if plan is not None else None,))
                pwarm = pcache.has(pkey)
            if pwarm:
                trace.metrics().counter(
                    "executor.compile_cache_persistent_hit").inc()
                if tr_on:
                    trace.instant("compile_cache_persistent_hit",
                                  cat="compile",
                                  args={"fingerprint": key[0][:12]})
            else:
                trace.metrics().counter(
                    "executor.compile_cache_cold_miss").inc()
            _t0 = trace.now()
            compiled = self._prepare(program, feed, fetch_names, scope, mesh,
                                     bucket=bucket, plan=plan)
            # the XLA compile itself happens lazily on the FIRST jitted
            # call — the executor::compile span, the compile_seconds
            # observation, and the persistent record all land after the
            # step call below so they cover the real compile
            pending_compile = (_t0, pcache, pkey, pwarm)
            if use_program_cache:
                self._cache_store(key, compiled)
        else:
            self._cache.move_to_end(key)
            trace.metrics().counter("executor.compile_cache_hit").inc()
            if tr_on:
                trace.instant("compile_cache_hit", cat="compile",
                              args={"fingerprint": key[0][:12]})

        mut = {n: scope.find_var(n) for n in compiled.param_names
               if n in compiled.written_names}
        ro = {n: scope.find_var(n) for n in compiled.param_names
              if n not in compiled.written_names}
        feeds = {k: jnp.asarray(v) for k, v in feed.items()}
        if bucket is not None:
            feeds["__batch_valid__"] = jnp.asarray(n_valid, jnp.int32)
        seed = program.random_seed if program.random_seed is not None else 0
        step_key = jax.random.fold_in(jax.random.PRNGKey(seed), self._step)
        self._step += 1

        if compiled.donates:
            self._persist_alias_live()
        _t0 = trace.now()               # always: the flight recorder and
        _g_step_live.add(1)             # the watchdog time every step
        if pending_compile is not None:
            _g_compiling.add(1)
        try:
            fetches, new_vals = compiled.fn(mut, ro, feeds, step_key)
        except Exception as e:          # noqa: BLE001 — OOM forensics only
            if device_stats.is_oom(e):
                device_stats.attach_oom_report(e, self.top_footprints())
            raise
        finally:
            _g_step_live.add(-1)
            if pending_compile is not None:
                _g_compiling.add(-1)
        if tr_on:
            # device-program launch span (per-step time; the per-op "op"
            # spans above are per-compile host cost)
            trace.complete("executor::step", _t0, cat="step",
                           args={"step": self._step - 1,
                                 "n_fetch": len(fetch_names)})
        _c_steps_done.inc()
        if _flight.enabled():
            # one wide event per step, tracing on or off (the flight
            # recorder is the always-on forensic ring)
            _flight.record_step(
                step=self._step - 1, dur_us=(trace.now() - _t0) / 1e3,
                bucket=bucket, batch_valid=n_valid,
                compile_miss=pending_compile is not None,
                fp=key[0][:12], n_fetch=len(fetch_names))
        if pending_compile is not None:
            # trace + XLA compile both happened inside this first call
            _t0c, pcache, pkey, pwarm = pending_compile
            compile_s = (trace.now() - _t0c) / 1e9
            trace.metrics().histogram("executor.compile_seconds").observe(
                compile_s)
            if tr_on:
                trace.complete("executor::compile", _t0c, cat="compile",
                               args={"fingerprint": key[0][:12],
                                     "n_ops": compiled.n_ops})
            # device truth AFTER the compile span closes: the AOT
            # analysis pays a second (only partially cached) compile,
            # which must not pollute executor.compile_seconds (it lands
            # in xla.analysis_seconds instead).  Uncached runs
            # (use_program_cache=False) miss on EVERY call — capturing
            # there would put the analysis on the step path and grow
            # _footprints without an eviction to retire it.
            dinfo = self._capture_device_stats(
                key, compiled, (mut, ro, feeds, step_key),
                bucket=bucket,
                n_devices=plan.n_devices if plan is not None else 1) \
                if use_program_cache else None
            if pcache is not None and not pwarm:
                meta = {
                    "fingerprint": key[0], "feed_sig": list(feed_sig),
                    "fetch": list(fetch_names), "bucket": bucket,
                    "compile_seconds": round(compile_s, 4),
                    "n_ops": compiled.n_ops}
                if dinfo is not None:
                    meta["device"] = {
                        "flops": dinfo.get("flops"),
                        "peak_bytes": dinfo.get("peak_bytes"),
                        "argument_bytes": dinfo.get("argument_bytes")}
                pcache.record(pkey, meta)
        deferred_err = (compiled.err_cell.pop("err", None)
                        if compiled.err_cell else None)
        if bucket is not None and bucket != n_valid:
            fetches = self._slice_true_batch(program, compiled.fetch_names,
                                             fetches, bucket, n_valid)
        for n, v in new_vals.items():
            scope.set_var(n, v)

        if return_numpy:
            if deferred_err is not None:
                deferred_err.throw()
            # ONE D2H transfer for the whole fetch tree (was: np.asarray
            # per fetch — N serial device syncs per step)
            host = jax.device_get(list(fetches))
            if core.get_flag("check_nan_inf"):
                for n, v in zip(compiled.fetch_names, host):
                    va = np.asarray(v)
                    if np.issubdtype(va.dtype, np.floating) \
                            and not np.all(np.isfinite(va)):
                        raise FloatingPointError(
                            f"NaN/Inf in fetched var '{n}'")
            return [np.asarray(f) for f in host]
        # lazy fetches: live device arrays behind FetchHandle — no sync at
        # all until someone materialises.  NaN scans and deferred checkify
        # errors fire at materialisation; aliases_state marks fetches that
        # share a buffer with scope state (the donation-safety signal the
        # async runner consumes before the next dispatch donates).
        from .async_pipeline import FetchHandle, _once
        check = bool(core.get_flag("check_nan_inf"))
        mask = compiled.fetch_alias_mask(len(fetches))
        pre = _once(deferred_err.throw) if deferred_err is not None else None
        handles = [FetchHandle(f, name=n, aliases_state=alias,
                               check_nan=check, pre_check=pre)
                   for n, f, alias
                   in zip(compiled.fetch_names, fetches, mask)]
        import weakref
        self._alias_live.extend(weakref.ref(h) for h in handles
                                if h.aliases_state)
        if len(self._alias_live) > 4096:
            # never-donating processes (CPU) only ever append: compact to
            # the handles still alive and unpersisted
            self._alias_live = [r for r in self._alias_live
                                if (h := r()) is not None
                                and not h.is_materialized()]
        return handles

    # -- checkpoint plane ---------------------------------------------------
    @property
    def step_counter(self) -> int:
        """The per-step PRNG counter (`fold_in(PRNGKey(seed), step)`).
        CheckpointManager saves/restores it so RNG-bearing programs
        (dropout, *_random ops) resume bit-deterministically."""
        return self._step

    @step_counter.setter
    def step_counter(self, value: int) -> None:
        self._step = int(value)

    def snapshot_vars(self, names, scope: Optional[Scope] = None,
                      handle_factory=None):
        """Donation-safe point-in-time snapshot of scope vars: each array
        is wrapped in a state-aliasing FetchHandle registered on
        ``_alias_live``, so a later dispatch that donates the scope's
        buffers host-persists these first (the PR-4 alias-guard
        invariant).  The caller (fluid/checkpoint.py's background writer)
        materialises them OFF the training thread — an async checkpoint
        never stalls the step window.  ``handle_factory(value, name)``
        overrides handle construction (checkpoint's per-shard-persisting
        handle for mesh-sharded state)."""
        from .async_pipeline import FetchHandle
        import weakref
        scope = scope or global_scope()
        make = handle_factory or (
            lambda v, n: FetchHandle(v, name=n, aliases_state=True))
        out = {}
        for n in names:
            v = scope.find_var(n)
            if v is not None:
                out[n] = make(v, n)
        self._alias_live.extend(weakref.ref(h) for h in out.values())
        return out

    def _persist_alias_live(self):
        """Host-copy every outstanding state-aliasing lazy fetch before a
        donating dispatch invalidates the scope's state buffers — shared
        across runners, programs, and sync runs (the donation-safety
        invariant)."""
        for ref in self._alias_live:
            h = ref()
            if h is not None:
                h.persist()
        del self._alias_live[:]

    def _slice_true_batch(self, program, fetch_names, fetches, bucket,
                          n_valid):
        """Slice padded fetches back to the TRUE batch size (device-side
        lazy slice — no extra sync).  The IR vetoes the dim0 heuristic:
        persistable vars (parameters/state) and vars with a known STATIC
        leading dim are never batch-major, even when dim 0 aliases the
        bucket size."""
        blk = program.global_block()
        carry = set(program._hints.get("carry_vars") or ())

        def _not_batch(n):
            if n in carry:      # carried state: dim 0 is slot capacity
                return True
            v = blk._find_var_recursive(n)
            return v is not None and (
                v.persistable or (v.shape is not None
                                  and len(v.shape) >= 1
                                  and v.shape[0] != -1))

        return [
            f if (getattr(f, "ndim", 0) < 1 or f.shape[0] != bucket
                  or _not_batch(n))
            else f[:n_valid]
            for n, f in zip(fetch_names, fetches)]

    # -- async / multi-step dispatch ----------------------------------------
    def run_async(self, program: Optional[Program] = None,
                  feed: Optional[Dict[str, Any]] = None,
                  fetch_list: Optional[Sequence] = None,
                  scope: Optional[Scope] = None,
                  max_inflight: Optional[int] = None,
                  steps_per_dispatch: Optional[int] = None):
        """Async analog of :meth:`run`: submit the step into a bounded
        in-flight window (`FLAGS_max_inflight_steps`) and return a
        StepFuture of FetchHandles immediately — the host keeps feeding
        while the device computes (fluid/async_pipeline.py).  One runner
        is kept per (program, fetch set, scope) on this Executor;
        :meth:`drain_async` flushes and waits on all of them."""
        from .async_pipeline import AsyncStepRunner
        program = program or default_main_program()
        fetch_names = tuple(_fetch_name(f) for f in _as_list(fetch_list))
        # explicit window params are part of the key: a later call with a
        # different max_inflight/K gets its own runner, never a silently
        # reused one with the old bounds
        key = (id(program), fetch_names, id(scope), max_inflight,
               steps_per_dispatch)
        runner = self._async_runners.get(key)
        if runner is None:
            runner = self._async_runners[key] = AsyncStepRunner(
                self, program, _as_list(fetch_list), scope=scope,
                max_inflight=max_inflight,
                steps_per_dispatch=steps_per_dispatch)
            cap = int(core.get_flag("executor_cache_capacity", 128) or 0)
            while cap > 0 and len(self._async_runners) > cap:
                _, old = self._async_runners.popitem(last=False)
                old.drain()
        else:
            self._async_runners.move_to_end(key)
        return runner.submit(feed or {})

    def drain_async(self):
        """Flush partial scan groups, wait on every in-flight step, and
        re-raise any unconsumed dispatch error."""
        for runner in list(self._async_runners.values()):
            runner.drain()

    def run_scan(self, program: Optional[Program] = None,
                 feed_list: Optional[Sequence[Dict[str, Any]]] = None,
                 fetch_list: Optional[Sequence] = None,
                 scope: Optional[Scope] = None,
                 return_numpy: bool = True,
                 use_program_cache: bool = True,
                 return_handles: bool = False):
        """Multi-step fusion: run K feeds through ONE ``lax.scan``-wrapped
        executable — one Python dispatch, K device steps, with the scope
        state (params/opt state) carried device-side between iterations
        (never through numpy).  Bit-equal to K sequential :meth:`run`
        calls: same per-step PRNG fold_in, same op stream, and with shape
        bucketing the per-step true batch size rides in as a stacked
        ``__batch_valid__`` vector.  Raises :class:`ScanUnsupportedError`
        for programs whose step builders do their own batch surgery
        (mesh / pipeline / recompute / PS) or checkify debug mode — the
        AsyncStepRunner degrades to sequential dispatches on that signal.
        Compile accounting mirrors run() (hit/miss counters, compile
        span); the persistent program index only records single-step
        executables."""
        from .async_pipeline import FetchHandle, ScanUnsupportedError
        program = program or default_main_program()
        feeds_in = list(feed_list or [])
        if not feeds_in:
            return []
        fetch_names = [_fetch_name(f) for f in _as_list(fetch_list)]
        mesh = getattr(program, "_mesh", None)
        plan = getattr(program, "_sharding_plan", None)
        if hasattr(program, "_program"):   # CompiledProgram
            if hasattr(program, "_ensure_sharding_plan"):
                plan = program._ensure_sharding_plan() or plan
            if hasattr(program, "_apply_ir_passes"):
                program._apply_ir_passes(fetch_names)
            mesh = getattr(program, "_mesh", None) or mesh
            program = program._program
            plan = getattr(program, "_sharding_plan", None) or plan
        if (mesh is not None or plan is not None
                or program._hints.get("pipeline_microbatches")
                or program._hints.get("recompute_checkpoints")
                or program._hints.get("ps_plan") is not None
                or program._hints.get("ps_server") is not None):
            raise ScanUnsupportedError(
                "run_scan: mesh/sharded/pipeline/recompute/PS programs do "
                "their own per-step surgery — dispatch them one step at a "
                "time")
        if core.get_flag("check_nan_inf"):
            raise ScanUnsupportedError(
                "run_scan: FLAGS_check_nan_inf compiles per-op checkify "
                "checks that cannot nest under lax.scan", permanent=False)
        if len(feeds_in) == 1:
            out = self.run(program, feed=feeds_in[0],
                           fetch_list=fetch_list, scope=scope,
                           return_numpy=return_numpy and not return_handles,
                           use_program_cache=use_program_cache)
            return [out]
        scope = scope or global_scope()
        k_steps = len(feeds_in)

        feeds = [self._normalize_feed(f) for f in feeds_in]

        # shape bucketing: every feed in the group pads to the GROUP's
        # bucket (max of the per-step edges) so the stacked batch is
        # rectangular; the per-step true size rides in __batch_valid__
        bucket = None
        n_valids = None
        want_bucketing = program._hints.get("shape_bucketing")
        if want_bucketing is None:
            want_bucketing = core.get_flag("shape_bucketing")
        if want_bucketing and feeds[0]:
            per_feed = []
            for f in feeds:
                dims = {np.shape(v)[0] for v in f.values()
                        if np.ndim(v) >= 1}
                per_feed.append(int(next(iter(dims)))
                                if len(dims) == 1 else None)
            if all(n is not None for n in per_feed):
                n_valids = per_feed
                edges = compile_cache.normalize_edges(
                    program._hints.get("bucket_edges")
                    or core.get_flag("shape_bucket_edges"))
                bucket = max(compile_cache.bucket_for(n, edges)
                             for n in n_valids)
                feeds = [{k: (compile_cache.pad_dim0(v, bucket)
                              if np.ndim(v) >= 1
                              and np.shape(v)[0] != bucket else v)
                          for k, v in f.items()} for f in feeds]
            else:
                trace.metrics().counter(
                    "executor.bucketing_skipped_mixed_feeds").inc()

        sigs = {tuple(sorted((k, tuple(np.shape(v)), str(v.dtype))
                             for k, v in f.items())) for f in feeds}
        if len(sigs) != 1:
            raise ScanUnsupportedError(
                "run_scan: feed shapes differ across the group and no "
                "common bucket edge covers them — enable "
                "FLAGS_shape_bucketing or feed uniform shapes",
                permanent=False)
        feed_sig = next(iter(sigs))

        # MIRRORS run()'s key tuple (positions 4-12) with the rejected
        # paths pinned to their inert values and a ("scan", K) suffix —
        # a new field added to run()'s key must be added here too, or the
        # two paths cache under inconsistent keys
        key = (_fingerprint(program), feed_sig, tuple(fetch_names),
               id(scope), bool(program._hints.get("is_test")), (), None,
               None, False,
               bool(program._hints.get("inference_no_prune")),
               bool(program._hints.get("donate_buffers")),
               bucket, None, ("scan", k_steps))
        tr_on = trace.enabled()
        pending_compile = None
        compiled = self._cache.get(key)
        if compiled is None:
            trace.metrics().counter("executor.compile_cache_miss").inc()
            if tr_on:
                trace.instant("compile_cache_miss", cat="compile",
                              args={"fingerprint": key[0][:12],
                                    "n_feeds": len(feeds[0]),
                                    "bucket": bucket, "scan": k_steps})
            self._note_recompile(feed_sig, bucket, tr_on)
            _t0 = trace.now()
            base = self._prepare(program, feeds[0], fetch_names, scope,
                                 None, bucket=bucket)
            if base.raw_fn is None:
                raise ScanUnsupportedError(
                    "run_scan: this program compiles through a step "
                    "builder with no scannable raw step")
            raw = base.raw_fn

            def scan_fn(carry, ro, stacked, keys):
                def body(c, xs):
                    fd, kk = xs
                    step_fetches, new_vals = raw(dict(c), ro, fd, kk)
                    c2 = {n: new_vals.get(n, c[n]) for n in c}
                    extras = {n: v for n, v in new_vals.items()
                              if n not in c}
                    return c2, (list(step_fetches), extras)
                c_end, (ys, extras) = jax.lax.scan(body, carry,
                                                   (stacked, keys))
                return ys, c_end, extras

            donate = base.donates
            jfn = jax.jit(scan_fn, donate_argnums=(0,) if donate else ())
            compiled = _CompiledBlock(jfn, base.param_names,
                                      base.written_names, fetch_names,
                                      n_ops=base.n_ops, donates=donate,
                                      jitted=jfn)
            pending_compile = _t0
            if use_program_cache:
                self._cache_store(key, compiled)
        else:
            self._cache.move_to_end(key)
            trace.metrics().counter("executor.compile_cache_hit").inc()
            if tr_on:
                trace.instant("compile_cache_hit", cat="compile",
                              args={"fingerprint": key[0][:12],
                                    "scan": k_steps})

        mut = {n: scope.find_var(n) for n in compiled.param_names
               if n in compiled.written_names}
        ro = {n: scope.find_var(n) for n in compiled.param_names
              if n not in compiled.written_names}
        stacked = {k: jnp.stack([jnp.asarray(f[k]) for f in feeds])
                   for k in feeds[0]}
        if bucket is not None:
            stacked["__batch_valid__"] = jnp.asarray(n_valids, jnp.int32)
        seed = program.random_seed if program.random_seed is not None else 0
        base_key = jax.random.PRNGKey(seed)
        keys = jnp.stack([jax.random.fold_in(base_key, self._step + i)
                          for i in range(k_steps)])
        self._step += k_steps

        if compiled.donates:
            self._persist_alias_live()
        _t0 = trace.now()
        _g_step_live.add(1)
        if pending_compile is not None:
            _g_compiling.add(1)
        try:
            st_fetches, carry_end, st_extras = compiled.fn(mut, ro, stacked,
                                                           keys)
        except Exception as e:          # noqa: BLE001 — OOM forensics only
            if device_stats.is_oom(e):
                device_stats.attach_oom_report(e, self.top_footprints())
            raise
        finally:
            _g_step_live.add(-1)
            if pending_compile is not None:
                _g_compiling.add(-1)
        if tr_on:
            trace.complete("executor::step", _t0, cat="step",
                           args={"step": self._step - k_steps,
                                 "steps_fused": k_steps,
                                 "n_fetch": len(fetch_names)})
        _c_steps_done.inc(k_steps)
        if _flight.enabled():
            _flight.record_step(
                step=self._step - k_steps,
                dur_us=(trace.now() - _t0) / 1e3, bucket=bucket,
                compile_miss=pending_compile is not None,
                fp=key[0][:12], n_fetch=len(fetch_names), scan=k_steps)
        if pending_compile is not None:
            compile_s = (trace.now() - pending_compile) / 1e9
            trace.metrics().histogram("executor.compile_seconds").observe(
                compile_s)
            if tr_on:
                trace.complete("executor::compile", pending_compile,
                               cat="compile",
                               args={"fingerprint": key[0][:12],
                                     "scan": k_steps,
                                     "n_ops": compiled.n_ops})
            if use_program_cache:   # uncached scans miss every call
                self._capture_device_stats(key, compiled,
                                           (mut, ro, stacked, keys),
                                           bucket=bucket, scan=k_steps)
        for n, v in carry_end.items():
            scope.set_var(n, v)
        for n, v in st_extras.items():
            scope.set_var(n, v[-1])

        out = []
        for i in range(k_steps):
            row = [f[i] for f in st_fetches]
            if bucket is not None and bucket != n_valids[i]:
                row = self._slice_true_batch(program, fetch_names, row,
                                             bucket, n_valids[i])
            out.append(row)
        if return_handles:
            return [[FetchHandle(f, name=n)
                     for n, f in zip(fetch_names, row)] for row in out]
        if return_numpy:
            host = jax.device_get(out)    # ONE transfer for all K steps
            return [[np.asarray(f) for f in row] for row in host]
        return out

    @staticmethod
    def _normalize_feed(feed):
        """ONE host conversion per feed (np.asarray on a device array
        forces a D2H sync, serialising the prefetch pipeline) + the
        64-bit-width check.  Shared by run() and run_scan()."""
        feed = {k: (v if hasattr(v, "dtype") else np.asarray(v))
                for k, v in (feed or {}).items()}
        for k, v in feed.items():
            check_feed_width(k, v)
        return feed

    def _cache_store(self, key, compiled):
        """Insert into the LRU-bounded executable cache
        (FLAGS_executor_cache_capacity), counting evictions.  Evicting
        an executable also retires its device-footprint record and
        gauges — and, when tracing, names the evictee and its HBM
        footprint so eviction decisions are auditable."""
        self._cache[key] = compiled
        cap = int(core.get_flag("executor_cache_capacity", 128) or 0)
        while cap > 0 and len(self._cache) > cap:
            old_key, _ = self._cache.popitem(last=False)
            trace.metrics().counter("executor.compile_cache_evict").inc()
            fp = self._footprints.pop(old_key, None)
            if fp is not None:
                device_stats.unpublish(fp.get("label", ""))
                if trace.enabled():
                    trace.instant(
                        "compile_cache_evict", cat="compile",
                        args={"label": fp.get("label"),
                              "peak_bytes": fp.get("peak_bytes")})

    # -- device truth (fluid/device_stats.py) --------------------------------
    def _capture_device_stats(self, key, compiled, example_args,
                              bucket=None, scan=None, n_devices=1):
        """AOT cost/memory analysis of a freshly compiled executable,
        published as per-executable gauges and kept beside the LRU for
        OOM forensics.  Runs only on a compile miss and only when
        FLAGS_device_cost_analysis allows — never on the step path."""
        if compiled.jitted is None or not device_stats.capture_enabled():
            return None
        # label salt includes THIS executor: two Executors compiling the
        # same (program, scope) produce identical cache keys, and a
        # shared label would let one executor's close()/eviction retire
        # the other's still-resident footprint from the process-wide
        # aggregates
        label = (key[0][:8] + "-"
                 + hashlib.sha1(repr((id(self), key)).encode())
                 .hexdigest()[:6])
        info = device_stats.capture(compiled.jitted, example_args,
                                    label=label, n_devices=n_devices)
        if info is None:
            return None
        info["bucket"] = bucket
        info["n_ops"] = compiled.n_ops
        if scan:
            info["scan"] = scan
        self._footprints[key] = info
        # publish maintains the per-executable gauges AND the
        # process-wide xla.mem.lru_* aggregates (device_stats._agg —
        # shared across every Executor in the process)
        device_stats.publish(label, info)
        if self._fp_finalizer is None:
            # an Executor dropped WITHOUT close() must still retire its
            # footprints, or the process-wide aggregates over-report
            # dead executables forever.  The finalizer holds only the
            # footprint dict (never self — that would defeat GC).
            import weakref
            self._fp_finalizer = weakref.finalize(
                self, _unpublish_footprints, self._footprints)
        return info

    def analyze(self, program: Optional[Program] = None,
                feed: Optional[Dict[str, Any]] = None,
                fetch_list: Optional[Sequence] = None,
                scope: Optional[Scope] = None) -> Optional[Dict[str, Any]]:
        """AOT cost/memory analysis of (program, feed) WITHOUT executing
        a step: lower + compile at ShapeDtypeStruct examples and return
        the ``device_stats.capture`` record (flops, bytes_accessed,
        per_device_peak_bytes, ...), or None when the backend refuses.

        This is the autotuner's free pricing path — a candidate config
        is judged OOM from ``memory_analysis`` here before any probe
        window runs it — but it is also a public "would this fit?"
        question for tooling.  No step executes, no scope state moves,
        nothing lands in the run cache or the footprint gauges."""
        program = program or default_main_program()
        fetch_names = [_fetch_name(f) for f in _as_list(fetch_list)]
        mesh = getattr(program, "_mesh", None)
        plan = getattr(program, "_sharding_plan", None)
        if hasattr(program, "_program"):   # CompiledProgram
            if hasattr(program, "_ensure_sharding_plan"):
                plan = program._ensure_sharding_plan() or plan
            if hasattr(program, "_apply_ir_passes"):
                program._apply_ir_passes(fetch_names)
            mesh = getattr(program, "_mesh", None) or mesh
            program = program._program
            plan = getattr(program, "_sharding_plan", None) or plan
        if plan is not None:
            mesh = None
        scope = scope or global_scope()
        feed = self._normalize_feed(feed)
        # mirror run()'s bucketing so the analysed shapes are the shapes
        # a real step would compile
        bucket = n_valid = None
        want_bucketing = program._hints.get("shape_bucketing")
        if want_bucketing is None:
            want_bucketing = core.get_flag("shape_bucketing")
        if (want_bucketing and feed and mesh is None
                and (plan is None or plan.data_axis is None)
                and not program._hints.get("pipeline_microbatches")
                and not program._hints.get("recompute_checkpoints")):
            dims = {np.shape(v)[0] for v in feed.values() if np.ndim(v) >= 1}
            if len(dims) == 1:
                n_valid = int(next(iter(dims)))
                edges = compile_cache.normalize_edges(
                    program._hints.get("bucket_edges")
                    or core.get_flag("shape_bucket_edges"))
                bucket = compile_cache.bucket_for(n_valid, edges)
                if bucket != n_valid:
                    feed = {k: compile_cache.pad_dim0(v, bucket)
                            for k, v in feed.items()}
        compiled = self._prepare(program, feed, fetch_names, scope, mesh,
                                 bucket=bucket, plan=plan)
        if compiled.jitted is None:
            return None
        mut = {n: scope.find_var(n) for n in compiled.param_names
               if n in compiled.written_names}
        ro = {n: scope.find_var(n) for n in compiled.param_names
              if n not in compiled.written_names}
        feeds = {k: jnp.asarray(v) for k, v in feed.items()}
        if bucket is not None:
            feeds["__batch_valid__"] = jnp.asarray(n_valid, jnp.int32)
        seed = program.random_seed if program.random_seed is not None else 0
        info = device_stats.capture(
            compiled.jitted,
            (mut, ro, feeds, jax.random.PRNGKey(seed)),
            n_devices=plan.n_devices if plan is not None else 1)
        if info is not None:
            info["bucket"] = bucket
            info["n_ops"] = compiled.n_ops
        return info

    def top_footprints(self, n: int = 5):
        """The n biggest live executables by XLA-reported peak bytes —
        what a RESOURCE_EXHAUSTED error gets attached (OOM forensics
        names executables, not guesses)."""
        return sorted(self._footprints.values(),
                      key=device_stats.peak_bytes_of, reverse=True)[:n]

    def _note_recompile(self, feed_sig, bucket, tr_on):
        """Recompile-storm detection: a burst of compile misses means
        something upstream feeds unstable shapes (a drop_last=False loader
        without bucketing, per-step attr churn).  One warning per storm,
        with shape/bucket attribution so the timeline names the culprit."""
        thr = int(core.get_flag("recompile_warn_threshold", 0) or 0)
        if thr <= 0:
            return
        window = float(core.get_flag("recompile_warn_window", 60.0))
        info = {"shapes": [f"{k}{list(s)}" for k, s, _ in feed_sig],
                "bucket": bucket}
        recent = self._storm.note_miss(info, thr, window)
        if recent is None:
            return
        trace.metrics().counter("executor.recompile_storm").inc()
        if tr_on:
            trace.instant("recompile_storm", cat="compile",
                          args={"misses": len(recent),
                                "window_s": window,
                                "recent": recent[-5:]})
        import sys
        skipped = trace.metrics().counter(
            "executor.bucketing_skipped_mixed_feeds").value
        why = (f"bucketing is ON but was skipped on {skipped} runs — "
               f"feeds had no common leading dim; align the batch axis "
               f"of every feed"
               if core.get_flag("shape_bucketing") and skipped
               else "enable FLAGS_shape_bucketing (and set "
                    "FLAGS_shape_bucket_edges to your loader's sizes) or "
                    "stabilise the feed shapes")
        print(f"paddle_tpu: WARNING: recompile storm — {len(recent)} "
              f"compile-cache misses within {window:.0f}s; recent feed "
              f"shapes: {[i['shapes'] for i in recent[-3:]]}.  {why} — "
              f"every miss is a whole-block XLA recompile "
              f"(docs/performance.md)", file=sys.stderr)

    # -- compilation --------------------------------------------------------
    def _prepare(self, program: Program, feed, fetch_names, scope,
                 mesh=None, bucket=None, plan=None) -> _CompiledBlock:
        block = program.global_block()
        is_test = bool(program._hints.get("is_test"))
        checkpoints = program._hints.get("recompute_checkpoints")
        microbatches = program._hints.get("pipeline_microbatches")

        # vars read from the scope: persistables already materialised
        param_names = sorted(
            n for n, v in block.vars.items()
            if (v.persistable or scope.find_var(n) is not None)
            and scope.find_var(n) is not None and n not in feed)
        persist = {n for n, v in block.vars.items() if v.persistable}
        # non-persistable vars the user seeded into the scope count as
        # state too: their updates must survive pruning + be written back
        scope_state = {n for op in block.ops for n in op.output_arg_names
                       if n not in persist and scope.find_var(n) is not None}
        # DECLARED carried state (program._hints["carry_vars"], the decode
        # plane's KV caches — docs/serving.md "Autoregressive decode"):
        # written back like scope-seeded state whether or not the scope
        # held a value at compile time, so a carry write can never be
        # silently pruned by a fetch-seeded compile that happened before
        # the state was seeded
        carry = set(program._hints.get("carry_vars") or ())
        if carry:
            scope_state |= {n for op in block.ops
                            for n in op.output_arg_names if n in carry}
            # a carried data var that is READ before any op writes it,
            # yet neither fed nor seeded, would surface later as a
            # baffling missing-input lowering error; fail at the boundary
            # with the actual fix instead.  Write-only carries (assign
            # into fresh state) need no seed — the write defines them.
            def _read_before_write(n):
                for op in block.ops:
                    if n in op.input_arg_names:
                        return True
                    if n in op.output_arg_names:
                        return False
                return False
            missing = [n for n in sorted(carry)
                       if n in block.vars and block.vars[n].is_data
                       and n not in feed and scope.find_var(n) is None
                       and _read_before_write(n)]
            if missing:
                raise ValueError(
                    f"carry_vars {missing} are declared data vars but "
                    f"neither fed nor seeded in the scope — seed the "
                    f"initial carried state with scope.set_var(name, "
                    f"value) before the first run (docs/serving.md)")
        written_names = sorted(
            {n for op in block.ops for n in op.output_arg_names
             if n in persist or n in scope_state})
        mesh_axes = dict(getattr(program, "_mesh_axes", {}) or {})

        # --- static pipeline path (PipelineOptimizer + device_guard) -------
        if (microbatches and mesh is not None
                and "pp" in getattr(mesh, "axis_names", ())
                and mesh.shape["pp"] > 1):
            from ..parallel.pipeline import classify_block, build_pipeline_step
            stage_plan = classify_block(block)
            example_env = {}
            for n in param_names:
                v = scope.find_var(n)   # shape/dtype only — no host copy
                example_env[n] = jax.ShapeDtypeStruct(
                    tuple(np.shape(v)), np.dtype(getattr(v, "dtype", "f4")))
            for k, v in feed.items():
                shape = list(np.shape(v))
                if shape and shape[0] % int(microbatches) == 0:
                    shape[0] //= int(microbatches)
                example_env[k] = jax.ShapeDtypeStruct(
                    tuple(shape), np.asarray(v).dtype)
            jfn = build_pipeline_step(
                block, stage_plan, mesh, microbatches, fetch_names,
                mesh_axes, is_test, written_names, example_env, list(feed))
            return _CompiledBlock(jfn, param_names, written_names,
                                  fetch_names, jitted=jfn)

        # --- recompute path (RecomputeOptimizer checkpoints) ---------------
        if checkpoints:
            from ..parallel.pipeline import (classify_block,
                                             build_functional_step)
            stage_plan = classify_block(block)
            # inference clones keep the hint but have no backward to
            # rematerialise — fall through to the plain path
            if stage_plan.loss_name is not None:
                fn = build_functional_step(block, stage_plan, fetch_names,
                                           mesh_axes, is_test, checkpoints,
                                           written_names)
                backend = self.place.jax_device().platform
                donate = (core.get_flag("use_donated_buffers")
                          and backend != "cpu")
                if mesh is not None:
                    from ..parallel.api import wrap_with_mesh
                    jfn = wrap_with_mesh(fn, mesh, program)
                    donate = False
                else:
                    jfn = jax.jit(fn, donate_argnums=(0,) if donate else ())
                # no alias_cell: fetch_alias_mask degrades to all-True
                # when donating — conservative, the guard persists every
                # lazy fetch before the next donating dispatch
                return _CompiledBlock(jfn, param_names, written_names,
                                      fetch_names, donates=donate,
                                      jitted=jfn)

        # prune to fetch-reachable ops (framework/prune.cc analog):
        # persistable/scope-state writes (optimizer, BN stats, user scope
        # vars) always survive, so training semantics are unchanged while
        # an eval fetch on the same program compiles a strictly smaller
        # executable.  Pipeline/recompute paths above run the full block.
        from .framework import prune_ops
        if program._hints.get("inference_no_prune"):
            # AnalysisConfig.switch_ir_optim(False): run the full block
            run_ops = [op for op in block.ops
                       if op.type not in ("feed", "fetch")]
        else:
            run_ops = prune_ops(block, block.ops, targets=list(fetch_names),
                                extra_state=scope_state,
                                feeds=set(feed))
            # a PARTIAL intermediate feed leaves a kept op needing a var
            # whose producer only survives the no-feed prune — that would
            # die deep in a lowering with an opaque IndexError (grad
            # fan-in `sum` tolerating truly-pruned partials is fine);
            # name the missing var up front instead
            if feed and any(n not in (v.name for v in block.vars.values()
                                      if v.is_data) for n in feed):
                nofeed_out = {
                    n for op in prune_ops(block, block.ops,
                                          targets=list(fetch_names),
                                          extra_state=scope_state)
                    for n in op.output_arg_names}
                kept_out = {n for op in run_ops
                            for n in op.output_arg_names}
                for op in run_ops:
                    for n in op.input_arg_names:
                        if n in feed or scope.find_var(n) is not None \
                                or n in kept_out:
                            continue
                        v = block._find_var_recursive(n)
                        if n in nofeed_out or (v is not None
                                               and v.is_data):
                            raise ValueError(
                                f"op '{op.type}' needs var '{n}', which "
                                f"the feed set {sorted(feed)} neither "
                                f"supplies nor makes reachable — when "
                                f"feeding an intermediate, all vars its "
                                f"producer chain would have provided "
                                f"must be fed together")
        written_names = sorted(
            {n for op in run_ops for n in op.output_arg_names
             if n in persist or n in scope_state})
        # post-prune op volume for this executable (bench.py reports it as
        # ops_per_step beside throughput; the IR passes shrink it)
        trace.metrics().gauge("executor.ops_per_step").set(len(run_ops))
        dce_targets = program._hints.get("ir_pass_dce_targets")
        if dce_targets is not None:
            # the pass pipeline's DCE ran seeded by the first run's fetch
            # set — a fetch of a var it pruned must fail with the cause,
            # not a bare KeyError deep inside the jit trace
            producible = set(feed) | set(param_names) | {
                n for op in run_ops for n in op.output_arg_names}
            for n in fetch_names:
                if n not in producible:
                    raise ValueError(
                        f"fetch target '{n}' is no longer produced by "
                        f"this program: the IR pass pipeline ran "
                        f"dead-code elimination seeded by the FIRST "
                        f"run's fetch set {sorted(dce_targets)}.  Fetch "
                        f"every var you will ever need on the first run "
                        f"of a CompiledProgram, or leave enable_dce / "
                        f"memory_optimize off (docs/passes.md)")
        # per-op checkify checks can't be staged under wrap_with_mesh's
        # plain jit — mesh/sharded runs keep the post-hoc fetched-var
        # scan instead
        debug_nan = bool(core.get_flag("check_nan_inf")) \
            and mesh is None and plan is None
        plan_mesh = plan.mesh if plan is not None else None

        alias_cell: list = []

        def fn(mut_params, ro_params, feeds, step_key):
            env = dict(mut_params)
            env.update(ro_params)
            env.update(feeds)
            ctx = LoweringContext(base_key=step_key, mesh_axes=mesh_axes,
                                  is_test=is_test)
            ctx.debug_nan = debug_nan
            # sharded compile: shard_constraint ops (the rewritten
            # collectives) pin values through this mesh; everything else
            # is GSPMD's problem, not per-op dispatch
            ctx.mesh = plan_mesh
            if bucket is not None:
                # true batch size rides in as a traced scalar: varying
                # tails within one bucket share ONE executable
                ctx.batch_valid = env.pop("__batch_valid__", None)
                ctx.batch_padded = bucket
            run_block_ops(block, env, ctx, ops=run_ops)
            fetches = [env[n] for n in fetch_names]
            new_vals = {n: env[n] for n in written_names if n in env}
            if not alias_cell:
                # trace-time: which fetches return the very value that is
                # (or becomes) scope state?  Those share the state's XLA
                # buffer, which a LATER donating dispatch may invalidate —
                # the executor persists them first (_persist_alias_live).
                # ro params count too: a read-only fetch of W from an eval
                # program aliases the same scope buffer a train program
                # donates.  Feeds are excluded — donation never touches
                # the feed arguments.
                state_vals = list(mut_params.values()) \
                    + list(ro_params.values()) + list(new_vals.values())
                alias_cell.append(tuple(
                    any(f is v for v in state_vals) for f in fetches))
            return fetches, new_vals

        backend = self.place.jax_device().platform
        donate = ((core.get_flag("use_donated_buffers")
                   or program._hints.get("donate_buffers"))
                  and backend != "cpu")
        err_cell = None
        if plan is not None:
            # the whole-step sharded compile (parallel/sharding.py):
            # in_shardings from the plan's rules, state donation for the
            # in-place optimizer update, collectives implied by
            # constraints instead of dispatched — ONE executable per step
            from ..parallel.sharding import wrap_with_plan
            shapes = {n: scope.find_var(n) for n in param_names}
            plan_feed = dict(feed)
            if bucket is not None:
                plan_feed["__batch_valid__"] = np.int32(0)
            mut_names = [n for n in param_names if n in written_names]
            ro_names = [n for n in param_names if n not in written_names]
            jfn, jitted = wrap_with_plan(
                fn, plan, shapes, mut_names, ro_names, plan_feed,
                block=block, donate=donate)
            return _CompiledBlock(jfn, param_names, written_names,
                                  fetch_names, n_ops=len(run_ops),
                                  raw_fn=fn, donates=donate,
                                  alias_cell=alias_cell, jitted=jitted)
        if mesh is not None:
            from ..parallel.api import wrap_with_mesh
            jfn = wrap_with_mesh(fn, mesh, program)
            donate = False
        elif debug_nan:
            # debug recompile: every op output carries a compiled-in
            # finite-check.  The error is stashed, not thrown here: run()
            # throws at dispatch for the sync path, and lazy fetches defer
            # the throw to materialisation (no forced sync at dispatch).
            from jax.experimental import checkify
            checked = jax.jit(checkify.checkify(
                fn, errors=checkify.user_checks))
            err_cell = {}

            def jfn(mut, ro, feeds, key):
                err, out = checked(mut, ro, feeds, key)
                err_cell["err"] = err
                return out
            donate = False
        else:
            jfn = jax.jit(fn, donate_argnums=(0,) if donate else ())
        return _CompiledBlock(jfn, param_names, written_names, fetch_names,
                              n_ops=len(run_ops), raw_fn=fn, donates=donate,
                              err_cell=err_cell, alias_cell=alias_cell,
                              jitted=jfn)

    # -- Trainer/dataset path (executor.cc:139-173 analog) ------------------
    def train_from_dataset(self, program, dataset, scope=None, thread=0,
                           debug=False, fetch_list=None, fetch_info=None,
                           print_period=100):
        from ..distributed.trainer import run_from_dataset
        return run_from_dataset(self, program, dataset, fetch_list,
                                print_period, train=True)

    def infer_from_dataset(self, program, dataset, scope=None, thread=0,
                           debug=False, fetch_list=None, fetch_info=None,
                           print_period=100):
        from ..distributed.trainer import run_from_dataset
        return run_from_dataset(self, program, dataset, fetch_list,
                                print_period, train=False)

    def train_passes(self, program, datasets, fetch_list=None,
                     print_period=100):
        """Multi-pass BoxPS training with pass N+1's host staging and
        pass N's writeback overlapped against device compute
        (box_wrapper.h BeginFeedPass/EndPass double buffering)."""
        from ..distributed.trainer import train_passes
        return train_passes(self, program, datasets, fetch_list,
                            print_period, train=True)

    def close(self):
        for runner in list(self._async_runners.values()):
            try:
                runner.drain()
            except Exception:       # noqa: BLE001 — close() is cleanup;
                pass                # unconsumed errors were best-effort
        self._async_runners.clear()
        self._cache.clear()
        _unpublish_footprints(self._footprints)
